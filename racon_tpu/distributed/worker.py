"""The distributed worker loop: claim → polish → split → complete → merge.

One ``racon_tpu --ledger-dir`` invocation is one worker. Workers share
nothing but the ledger directory; each runs the full single-process
engine (``Polisher.polish_records``) restricted to its claimed shard's
target range, committing every finished contig into that shard's
checkpoint store before renewing the lease. Eviction at any instruction
is recoverable:

- mid-contig: the store's committed prefix survives; the thief resumes
  it (``CheckpointStore.resume`` + ``skip_targets``) and recomputes
  only the in-flight contig;
- mid-commit: crash-consistency ordering (shard bytes fsync'd before
  the manifest record, torn manifest tails dropped on resume) means
  the thief sees either the whole contig or none of it;
- mid-merge: the merge is a lease-fenced pseudo-shard writing through
  tmp+rename — a dead merger's thief redoes the cheap read-only pass.

Dynamic splitting (docs/DISTRIBUTED.md "Elastic fleets"): a worker
holding a long-running shard while the rest of the fleet is starved —
idle live workers and nothing claimable — carves the uncommitted tail
past its in-flight contig into a child shard any idle worker claims at
its next poll. The trigger is evaluated when a shard is (re)claimed
(BEFORE the polisher is built, so the donated range's consensus is
never computed here at all) and again after every commit (frees the
tail mid-shard in pipeline mode). ``RACON_TPU_SPLIT=0`` disables;
``RACON_TPU_SPLIT_AFTER_S`` sets how long a shard must have been held
first (default: one lease term; 0 splits at the first starved poll).

Fault sites: ``dist/shard`` fires once per claimed shard (before any
polishing), ``dist/contig`` once per retired contig (before its
commit), ``dist/claim`` per claim attempt, ``dist/split`` inside the
split publication, ``dist/merge`` before the merge pass — so eviction
drills can target any phase deterministically.
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
import sys
import time
from typing import Callable, Optional

from racon_tpu.distributed import ledger as dledger
from racon_tpu.distributed.ledger import Claim, LeaseLost, WorkLedger
from racon_tpu.obs import fleet
from racon_tpu.obs.metrics import record_dist, set_dist
from racon_tpu.obs.trace import get_tracer
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience.faults import maybe_fault
from racon_tpu.server.engine import JobHooks, polish_job

ENV_POLL = "RACON_TPU_DIST_POLL"
ENV_AVOID = "RACON_TPU_DIST_AVOID"
ENV_SPLIT_AFTER = "RACON_TPU_SPLIT_AFTER_S"


def default_worker_id() -> str:
    import socket
    return f"{socket.gethostname()}-{os.getpid()}"


def _poll_interval(lease_s: float) -> float:
    env = envspec.read(ENV_POLL)
    if env:
        return max(0.01, float(env))
    # Often enough to steal promptly after expiry, rare enough that an
    # idle fleet doesn't hammer the shared filesystem.
    return min(1.0, max(0.05, lease_s / 10.0))


def _avoid_shards() -> list:
    """Shard names this worker should claim LAST (never excluded) —
    seeded by the autoscaler when replacing a self-evicted worker, so
    the replacement doesn't immediately re-claim the assignment that
    wedged its predecessor."""
    env = envspec.read(ENV_AVOID)
    return [s for s in (p.strip() for p in env.split(",")) if s]


def _split_after_s(lease_s: float) -> float:
    env = envspec.read(ENV_SPLIT_AFTER).strip()
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    # One lease term of evidence that the shard is long before
    # fragmenting it; a floor keeps tiny test leases from splitting
    # every run.
    return max(5.0, lease_s)


def _live_workers(ledger_dir: str) -> int:
    """Workers whose latest metric snapshot is not final — the best
    coordinator-free liveness proxy. A kill -9 victim counts as live
    until its lease expires and a steal resolves it, which at worst
    delays a split by one trigger evaluation."""
    try:
        shards = fleet.load_worker_shards(fleet.obs_dir_for(ledger_dir))
    except OSError:
        return 0
    return sum(1 for sh in shards
               if sh["records"] and not sh["records"][-1].get("final"))


def _maybe_split(ledger: WorkLedger, claim: Claim, next_tid: int,
                 t_shard: float, log) -> bool:
    """Evaluate the split trigger and, when the fleet is starved, carve
    ``[next_tid + 1, end)`` off the held shard (keep the in-flight
    contig, donate everything behind it). Returns True when a child
    was published; ``claim.info.end`` has shrunk then. Raises
    LeaseLost if the lease was stolen inside the split protocol — the
    caller's abandon path handles it like any other steal."""
    info = claim.info
    if info is None or not dledger.split_enabled():
        return False
    if dledger.split_depth(info.name) >= dledger.max_split_depth():
        return False  # re-splitting children cascades into handoff thrash
    if info.end - next_tid < 2:
        return False  # nothing to donate beyond the in-flight contig
    if time.monotonic() - t_shard < _split_after_s(ledger.lease_s):
        return False
    stats = ledger.open_shard_stats()
    if stats["claimable"] > 0:
        return False  # idle workers already have work to take
    if _live_workers(ledger.directory) <= stats["leased"]:
        return False  # nobody is idle — a split would only fragment
    child = ledger.split(claim, next_tid + 1)
    if child is None:
        return False
    print(f"[racon_tpu::dist] worker {claim.worker}: split "
          f"{info.name} at {next_tid + 1} — child {child.name} "
          f"[{child.start}, {child.end}) now stealable", file=log)
    return True


def _open_store(ledger: WorkLedger, shard,
                seg_targets: int = 0) -> ckpt.CheckpointStore:
    d = ledger.shard_ckpt_dir(shard)
    fp = ledger.shard_fp(shard)
    if os.path.exists(os.path.join(d, ckpt.META_NAME)):
        # Resume reads the manifest flavor from its own header — the
        # seg_targets this worker was launched with never rewrites an
        # existing store's mode.
        return ckpt.CheckpointStore.resume(d, fp)
    return ckpt.CheckpointStore.create(d, fp,
                                       segment_targets=seg_targets)


def _shard_cache():
    """The fleet-shared shard CAS, or None when unarmed. Gateway runs
    arm it by pointing ``RACON_TPU_CACHE_DIR`` at one directory under
    the gateway root (docs/GATEWAY.md), so every worker on every run
    shares one Tier-1 store keyed by shard fingerprint — a resubmitted
    fleet job replays its shards without polishing a window. Plain
    ledger runs leave the env unset and skip all of this; the global
    ``RACON_TPU_CACHE=0`` kill switch is honoured here too."""
    from racon_tpu.cache import ENV_CACHE_DIR, cache_enabled
    cache_dir = envspec.read(ENV_CACHE_DIR).strip()
    if not cache_dir or not cache_enabled():
        return None
    from racon_tpu.cache import ResultCache
    try:
        return ResultCache(cache_dir)
    except Exception as exc:
        print(f"[racon_tpu::dist] shard cache disabled ({exc})",
              file=sys.stderr)
        return None


def _polish_shard(ledger: WorkLedger, claim: Claim,
                  make_polisher: Callable, drop_unpolished: bool, log,
                  t_shard: float, seg_targets: int = 0) -> int:
    """Polish one claimed shard to completion; returns the number of
    committed targets in the shard's final effective range. Raises
    LeaseLost the moment the lease is observed stolen.

    The loop itself is the shared engine's ``polish_job``
    (racon_tpu/server/engine.py) — this frontend contributes only the
    ledger-specific hooks: lease renewal per contig, the ``dist/*``
    fault drills, dist accounting, and the dynamic split protocol
    (``claim.info.end`` shrinks mid-run when a starved fleet steals
    the uncommitted tail, which the hooks surface as the loop's live
    range end).
    """
    info = claim.info
    store = _open_store(ledger, info, seg_targets)
    cache = _shard_cache()
    try:
        start = info.start
        if cache is not None and not store.committed:
            # Fleet-shared Tier-1 probe: a verified hit replays the
            # whole shard's committed records into this store — the
            # polish loop below then sees a fully-resumed shard and
            # computes nothing. Probes only on a fresh store: a
            # partially-committed (stolen) shard already resumes from
            # its own prefix.
            hit = cache.load(ledger.shard_fp(info))
            if hit is not None:
                from racon_tpu.cache import replay_records
                replay_records(hit, store=store)
                record_dist("contigs_replayed", claim.shard,
                            claim.worker, value=len(store.committed))
                print(f"[racon_tpu::dist] worker {claim.worker}: "
                      f"shard {info.name} replayed from the shared "
                      f"cache ({len(store.committed)} contig(s))",
                      file=log)
        if store.committed:
            # A stolen (or re-claimed) shard: everything the victim
            # committed re-emits from its store, zero recompute.
            record_dist("contigs_resumed", claim.shard, claim.worker,
                        value=len(store.committed))
            print(f"[racon_tpu::dist] worker {claim.worker}: shard "
                  f"{info.name} resumes {len(store.committed)}/"
                  f"{info.end - start} committed contig(s) from "
                  "previous holder", file=log)

        def _before_build(first_tid: int) -> None:
            # Claim-time trigger: splitting BEFORE the polisher is
            # built means the donated range's windows are never
            # constructed here — in serial engine mode all consensus
            # compute runs up-front, so this is the evaluation that
            # actually shortens the tail.
            _maybe_split(ledger, claim, first_tid, t_shard, log)

        def _before_commit(tid: int, rec) -> None:
            maybe_fault("dist/contig")
            ledger.renew(claim)
            # Per-contig cadence: cheap (interval-gated) and tied to
            # the same heartbeat the lease renewal proves, so a live
            # worker's metric shard is never staler than its lease.
            fleet.maybe_flush()

        def _after_commit(tid: int, rec) -> None:
            record_dist("contigs_polished", claim.shard, claim.worker,
                        tid=tid)
            if claim.stolen:
                record_dist("contigs_repolished", claim.shard,
                            claim.worker, tid=tid)
            if tid + 1 < claim.info.end:
                _maybe_split(ledger, claim, tid + 1, t_shard, log)

        n = polish_job(
            make_polisher, drop_unpolished=drop_unpolished,
            store=store, tid_range=(start, info.end), fill_drops=True,
            hooks=JobHooks(
                range_end=lambda default: claim.info.end,
                before_build=_before_build,
                before_commit=_before_commit,
                after_commit=_after_commit,
                before_fill=lambda tid: ledger.renew(claim)))
        if cache is not None:
            # Publish the finished shard for the next run of this
            # fingerprint; cache trouble never fails a polished shard.
            from racon_tpu.cache import records_from_store
            try:
                cache.store(ledger.shard_fp(info),
                            records_from_store(store))
            except OSError:
                pass
        return n
    finally:
        store.close()


def _merge_phase(ledger: WorkLedger, worker: str, out, log,
                 poll: float) -> Optional[int]:
    """Every worker races for the merge pseudo-shard; exactly one wins
    and emits the merged FASTA. Losers wait for the done marker so the
    process exit means the run's output exists. Returns None — back to
    the shard loop — when a shard turns out to be pending after all: a
    split child published inside the parent's completion race window
    lands as new work, and the merge must wait for it."""
    import shutil
    while True:
        if ledger.merge_done():
            print(f"[racon_tpu::dist] worker {worker}: merged output "
                  f"already published by another worker "
                  f"({ledger.out_path})", file=log)
            return 0
        claim = ledger.claim_merge(worker)
        if claim is None:
            if not ledger.shards_done():
                return None  # late split child — resume polishing
            time.sleep(poll)
            continue
        if not ledger.shards_done():
            ledger.release(claim)
            return None
        maybe_fault("dist/merge")
        try:
            nbytes, emitted = ledger.merge()
            ledger.complete(claim, n_bytes=nbytes,
                            contigs_emitted=emitted)
        except LeaseLost:
            print(f"[racon_tpu::dist] worker {worker}: lost the merge "
                  "lease mid-pass — retrying against the thief's "
                  "result", file=log)
            continue
        record_dist("merges", -1, worker, bytes=nbytes)
        with open(ledger.out_path, "rb") as fh:
            shutil.copyfileobj(fh, out)
        out.flush()
        print(f"[racon_tpu::dist] worker {worker}: merged "
              f"{emitted} contig(s), {nbytes} bytes, from "
              f"{len(ledger.all_shards())} shard(s)", file=log)
        return 0


def run_worker(*, ledger_dir: str, fingerprint: str,
               worker_id: Optional[str], workers: int, lease_s: float,
               make_polisher: Callable, drop_unpolished: bool,
               n_targets: Optional[int] = None, scan_targets=None,
               fragment_correction: bool = False,
               seg_targets: Optional[int] = None,
               window_length: int = 500,
               out=None, log=None) -> int:
    """Drive one worker from fleet join to merged output.

    ``make_polisher`` builds a fresh (uninitialized) Polisher — one per
    claimed shard, since windows are pruned destructively. Returns a
    process exit code; crashes (injected or real) propagate so the
    process dies exactly as a preempted worker would.

    Pass ``scan_targets`` (io.parsers.scan_sequence_index, deferred)
    instead of an eager ``n_targets`` so only the meta-publishing
    worker pays the target-file pass — every later joiner adopts the
    published count (WorkLedger.open docstring).

    Ingest: workers ride the same RACON_TPU_INGEST data plane as the
    serial CLI — ``scan_targets`` routes to the mmap structural scan
    and every per-shard Polisher's initialize() uses the parallel
    inflate / index-first readers, so fleets (and chaos drills armed at
    ``io/read`` / ``io/inflate``) exercise exactly the production
    reader. The gauge below puts the gate state in every fleet metric
    shard.

    Ava (docs/AVA.md): ``fragment_correction`` selects the v2
    segmented checkpoint manifest for fresh shard stores
    (``seg_targets`` overrides the ``ava.seg_targets_for`` default)
    and, when the ledger published per-target offsets, runs the shape
    planner once at join time — publishing the run's bucket plan
    against the compile budget before any shard is claimed.
    """
    out = out if out is not None else sys.stdout.buffer
    log = log if log is not None else sys.stderr
    worker = worker_id or default_worker_id()
    ledger = WorkLedger.open(ledger_dir, fingerprint,
                             n_targets=n_targets, workers=workers,
                             lease_s=lease_s, scan_targets=scan_targets,
                             weighted=bool(fragment_correction))
    from racon_tpu.io.ingest import ingest_enabled
    from racon_tpu.obs.metrics import registry as _registry
    _registry().set("ingest_enabled", int(ingest_enabled()))
    set_dist("workers", int(workers))
    set_dist("shards", ledger.n_shards)
    set_dist("n_targets", ledger.n_targets)
    from racon_tpu.ava import seg_targets_for
    if seg_targets is None:
        seg_targets = seg_targets_for(fragment_correction)
    if fragment_correction and ledger.target_offsets:
        # Shape-bucket plan for the whole run, from the published
        # offsets (no file I/O): every worker computes the identical
        # plan, so the published gauges agree fleet-wide.
        from racon_tpu.ava.planner import lengths_from_offsets, \
            plan_buckets
        from racon_tpu.obs.metrics import record_ava_plan
        plan = plan_buckets(lengths_from_offsets(ledger.target_offsets),
                            window_length=window_length)
        record_ava_plan(plan)
        print(f"[racon_tpu::ava] worker: {plan.n_targets} target(s) "
              f"in {plan.n_buckets} shape bucket(s) "
              f"(quantum {plan.quantum}, "
              f"{len(plan.compile_keys)} compile key(s) vs budget "
              f"{plan.budget}, pad {plan.pad_frac:.2%})", file=log)
    # Fleet observability plane (racon_tpu/obs/fleet.py): publish this
    # worker's metric shard at join time, tag every span with the
    # worker identity, and keep the shard fresh per contig. The CLI's
    # teardown paths call fleet.flush_final() so SIGTERM evictions
    # leave a final snapshot.
    fleet.install_writer(os.path.join(ledger_dir, fleet.OBS_SUBDIR),
                         worker, fingerprint)
    get_tracer().set_context(worker_id=worker, run_fp=fingerprint)
    # Trace adoption: RACON_TPU_TRACE_CTX first (set by the spawning
    # autoscaler/smoke), else the context the meta publisher stamped
    # into the ledger — so every worker span joins the submitting
    # process's trace without any live channel between them. Malformed
    # or absent contexts degrade to a fresh root trace, never an error.
    from racon_tpu.obs.trace import adopt_trace_context
    if adopt_trace_context() is None:
        meta_ctx = str(ledger.meta.get("trace_ctx", ""))
        if meta_ctx:
            adopt_trace_context(meta_ctx)
    poll = _poll_interval(ledger.lease_s)
    avoid = _avoid_shards()
    print(f"[racon_tpu::dist] worker {worker}: joined ledger "
          f"{ledger_dir} ({ledger.n_targets} target(s) in "
          f"{ledger.n_shards} shard(s), lease {ledger.lease_s:g}s)",
          file=log)

    while True:
        while not ledger.shards_done():
            claim = ledger.claim_shard(worker, avoid=avoid)
            if claim is None:
                # Everything is live-leased elsewhere: wait for a
                # completion, an expiry to steal, or a split child.
                time.sleep(poll)
                continue
            maybe_fault("dist/shard")
            get_tracer().set_context(shard=claim.shard)
            t0 = time.perf_counter()
            try:
                n = _polish_shard(ledger, claim, make_polisher,
                                  drop_unpolished, log,
                                  time.monotonic(), seg_targets)
                ledger.complete(claim, n_committed=n)
            except LeaseLost:
                # The shard was stolen while we held it (our own lease
                # expired — e.g. a long pause). The thief owns the work
                # now; our commits so far are still valid prefix for it.
                print(f"[racon_tpu::dist] worker {worker}: abandoning "
                      f"shard {claim.name} — lease stolen while "
                      "working", file=log)
                continue
            except BaseException as exc:  # noqa: BLE001 — terminal check only
                if getattr(exc, "signum", None) is not None:
                    # Supervisor-driven retirement (SIGTERM routed
                    # through the CLI's signal handler): hand the lease
                    # back explicitly so the shard is claimable at the
                    # fleet's next poll, then let the signal path finish
                    # teardown (final snapshot, exit 128+signum).
                    ledger.release(claim)
                    record_dist("retires", claim.shard, worker)
                    print(f"[racon_tpu::dist] worker {worker}: retiring"
                          f" from shard {claim.name} on signal "
                          f"{exc.signum} (lease released)", file=log)
                    raise
                # Fail-slow self-eviction: this host has crossed its
                # terminal watchdog breach budget, so it hands the shard
                # back EXPLICITLY (lease release — thieves claim it at
                # the next poll instead of waiting out the lease term)
                # and exits with a distinct code. Committed prefix work
                # survives in the shard store; the successor resumes it
                # byte-identically. Every other exception propagates so
                # the process dies exactly as a preempted worker would.
                from racon_tpu.resilience.watchdog import (
                    EXIT_SELF_EVICT, is_terminal)
                if not is_terminal(exc):
                    raise
                ledger.release(claim)
                record_dist("self_evictions", claim.shard, worker)
                print(f"[racon_tpu::dist] worker {worker}: "
                      f"self-evicting from shard {claim.shard} — {exc} "
                      f"(lease released; exit {EXIT_SELF_EVICT})",
                      file=log)
                # The CLI tail handles fleet.flush_final() +
                # tracer.finish on this return value, so the eviction
                # leaves a final obs snapshot like any clean exit.
                return EXIT_SELF_EVICT
            finally:
                get_tracer().set_context(shard=None)
                fleet.maybe_flush()
            record_dist("shards_completed", claim.shard, worker)
            if claim.stolen:
                record_dist("recovery_wall_s", claim.shard, worker,
                            value=time.perf_counter() - t0)
            print(f"[racon_tpu::dist] worker {worker}: shard "
                  f"{claim.name} complete ({n} target(s))"
                  f"{' [stolen]' if claim.stolen else ''}", file=log)

        rc = _merge_phase(ledger, worker, out, log, poll)
        if rc is not None:
            return rc
