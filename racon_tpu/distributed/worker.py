"""The distributed worker loop: claim → polish → complete → merge.

One ``racon_tpu --ledger-dir`` invocation is one worker. Workers share
nothing but the ledger directory; each runs the full single-process
engine (``Polisher.polish_records``) restricted to its claimed shard's
target range, committing every finished contig into that shard's
checkpoint store before renewing the lease. Eviction at any instruction
is recoverable:

- mid-contig: the store's committed prefix survives; the thief resumes
  it (``CheckpointStore.resume`` + ``skip_targets``) and recomputes
  only the in-flight contig;
- mid-commit: crash-consistency ordering (shard bytes fsync'd before
  the manifest record, torn manifest tails dropped on resume) means
  the thief sees either the whole contig or none of it;
- mid-merge: the merge is a lease-fenced pseudo-shard writing through
  tmp+rename — a dead merger's thief redoes the cheap read-only pass.

Fault sites: ``dist/shard`` fires once per claimed shard (before any
polishing), ``dist/contig`` once per retired contig (before its
commit), ``dist/claim`` per claim attempt, ``dist/merge`` before the
merge pass — so eviction drills can target any phase deterministically.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Callable, Optional

from racon_tpu.distributed.ledger import Claim, LeaseLost, WorkLedger
from racon_tpu.obs import fleet
from racon_tpu.obs.metrics import record_dist, set_dist
from racon_tpu.obs.trace import get_tracer
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience.faults import maybe_fault

ENV_POLL = "RACON_TPU_DIST_POLL"


def default_worker_id() -> str:
    import socket
    return f"{socket.gethostname()}-{os.getpid()}"


def _poll_interval(lease_s: float) -> float:
    env = os.environ.get(ENV_POLL, "")
    if env:
        return max(0.01, float(env))
    # Often enough to steal promptly after expiry, rare enough that an
    # idle fleet doesn't hammer the shared filesystem.
    return min(1.0, max(0.05, lease_s / 10.0))


def _open_store(ledger: WorkLedger, k: int) -> ckpt.CheckpointStore:
    d = ledger.shard_ckpt_dir(k)
    fp = ledger.shard_fp(k)
    if os.path.exists(os.path.join(d, ckpt.META_NAME)):
        return ckpt.CheckpointStore.resume(d, fp)
    return ckpt.CheckpointStore.create(d, fp)


def _polish_shard(ledger: WorkLedger, claim: Claim,
                  make_polisher: Callable,
                  drop_unpolished: bool, log) -> int:
    """Polish one claimed shard to completion; returns the number of
    committed targets. Raises LeaseLost the moment the lease is
    observed stolen."""
    k = claim.shard
    start, end = ledger.shard_range(k)
    store = _open_store(ledger, k)
    try:
        if store.committed:
            # A stolen (or re-claimed) shard: everything the victim
            # committed re-emits from its store, zero recompute.
            record_dist("contigs_resumed", k, claim.worker,
                        value=len(store.committed))
            print(f"[racon_tpu::dist] worker {claim.worker}: shard "
                  f"{k} resumes {len(store.committed)}/{end - start} "
                  "committed contig(s) from previous holder",
                  file=log)
        if len(store.committed) < end - start:
            polisher = make_polisher()
            polisher.initialize()
            polisher.restrict_targets(range(start, end))
            if store.committed:
                polisher.skip_targets(store.committed)
            for tid, rec in polisher.polish_records(drop_unpolished):
                maybe_fault("dist/contig")
                ledger.renew(claim)
                # Per-contig cadence: cheap (interval-gated) and tied
                # to the same heartbeat the lease renewal proves, so a
                # live worker's metric shard is never staler than its
                # lease.
                fleet.maybe_flush()
                if rec is not None:
                    store.commit(tid, rec.name.encode(), rec.data)
                else:
                    store.commit_dropped(tid)
                record_dist("contigs_polished", k, claim.worker,
                            tid=tid)
                if claim.stolen:
                    record_dist("contigs_repolished", k, claim.worker,
                                tid=tid)
        # Targets with zero windows never reach the assembler, so they
        # yield nothing above — commit them as drops explicitly so the
        # done marker really means "every tid in range accounted for".
        for tid in range(start, end):
            if tid not in store.committed:
                ledger.renew(claim)
                store.commit_dropped(tid)
        return len(store.committed)
    finally:
        store.close()


def _merge_phase(ledger: WorkLedger, worker: str, out, log,
                 poll: float) -> int:
    """Every worker races for the merge pseudo-shard; exactly one wins
    and emits the merged FASTA. Losers wait for the done marker so the
    process exit means the run's output exists."""
    import shutil
    while True:
        if ledger.merge_done():
            print(f"[racon_tpu::dist] worker {worker}: merged output "
                  f"already published by another worker "
                  f"({ledger.out_path})", file=log)
            return 0
        claim = ledger.claim_merge(worker)
        if claim is None:
            time.sleep(poll)
            continue
        maybe_fault("dist/merge")
        try:
            nbytes, emitted = ledger.merge()
            ledger.complete(claim, n_bytes=nbytes,
                            contigs_emitted=emitted)
        except LeaseLost:
            print(f"[racon_tpu::dist] worker {worker}: lost the merge "
                  "lease mid-pass — retrying against the thief's "
                  "result", file=log)
            continue
        record_dist("merges", -1, worker, bytes=nbytes)
        with open(ledger.out_path, "rb") as fh:
            shutil.copyfileobj(fh, out)
        out.flush()
        print(f"[racon_tpu::dist] worker {worker}: merged "
              f"{emitted} contig(s), {nbytes} bytes, from "
              f"{ledger.n_shards} shard(s)", file=log)
        return 0


def run_worker(*, ledger_dir: str, fingerprint: str,
               worker_id: Optional[str], workers: int, lease_s: float,
               make_polisher: Callable, drop_unpolished: bool,
               n_targets: Optional[int] = None, scan_targets=None,
               out=None, log=None) -> int:
    """Drive one worker from fleet join to merged output.

    ``make_polisher`` builds a fresh (uninitialized) Polisher — one per
    claimed shard, since windows are pruned destructively. Returns a
    process exit code; crashes (injected or real) propagate so the
    process dies exactly as a preempted worker would.

    Pass ``scan_targets`` (io.parsers.scan_sequence_index, deferred)
    instead of an eager ``n_targets`` so only the meta-publishing
    worker pays the target-file pass — every later joiner adopts the
    published count (WorkLedger.open docstring).
    """
    out = out if out is not None else sys.stdout.buffer
    log = log if log is not None else sys.stderr
    worker = worker_id or default_worker_id()
    ledger = WorkLedger.open(ledger_dir, fingerprint,
                             n_targets=n_targets, workers=workers,
                             lease_s=lease_s, scan_targets=scan_targets)
    set_dist("workers", int(workers))
    set_dist("shards", ledger.n_shards)
    set_dist("n_targets", ledger.n_targets)
    # Fleet observability plane (racon_tpu/obs/fleet.py): publish this
    # worker's metric shard at join time, tag every span with the
    # worker identity, and keep the shard fresh per contig. The CLI's
    # teardown paths call fleet.flush_final() so SIGTERM evictions
    # leave a final snapshot.
    fleet.install_writer(os.path.join(ledger_dir, fleet.OBS_SUBDIR),
                         worker, fingerprint)
    get_tracer().set_context(worker_id=worker, run_fp=fingerprint)
    poll = _poll_interval(ledger.lease_s)
    print(f"[racon_tpu::dist] worker {worker}: joined ledger "
          f"{ledger_dir} ({ledger.n_targets} target(s) in "
          f"{ledger.n_shards} shard(s), lease {ledger.lease_s:g}s)",
          file=log)

    while not ledger.shards_done():
        claim = ledger.claim_shard(worker)
        if claim is None:
            # Everything is live-leased elsewhere: wait for a
            # completion or an expiry to steal.
            time.sleep(poll)
            continue
        maybe_fault("dist/shard")
        get_tracer().set_context(shard=claim.shard)
        t0 = time.perf_counter()
        try:
            n = _polish_shard(ledger, claim, make_polisher,
                              drop_unpolished, log)
            ledger.complete(claim, n_committed=n)
        except LeaseLost:
            # The shard was stolen while we held it (our own lease
            # expired — e.g. a long pause). The thief owns the work
            # now; our commits so far are still valid prefix for it.
            print(f"[racon_tpu::dist] worker {worker}: abandoning "
                  f"shard {claim.shard} — lease stolen while working",
                  file=log)
            continue
        except BaseException as exc:  # noqa: BLE001 — terminal check only
            # Fail-slow self-eviction: this host has crossed its
            # terminal watchdog breach budget, so it hands the shard
            # back EXPLICITLY (lease release — thieves claim it at the
            # next poll instead of waiting out the lease term) and
            # exits with a distinct code. Committed prefix work
            # survives in the shard store; the successor resumes it
            # byte-identically. Every other exception propagates so
            # the process dies exactly as a preempted worker would.
            from racon_tpu.resilience.watchdog import (EXIT_SELF_EVICT,
                                                       is_terminal)
            if not is_terminal(exc):
                raise
            ledger.release(claim)
            record_dist("self_evictions", claim.shard, worker)
            print(f"[racon_tpu::dist] worker {worker}: self-evicting "
                  f"from shard {claim.shard} — {exc} (lease released; "
                  f"exit {EXIT_SELF_EVICT})", file=log)
            # The CLI tail handles fleet.flush_final() + tracer.finish
            # on this return value, so the eviction leaves a final obs
            # snapshot like any clean exit.
            return EXIT_SELF_EVICT
        finally:
            get_tracer().set_context(shard=None)
            fleet.maybe_flush()
        record_dist("shards_completed", claim.shard, worker)
        if claim.stolen:
            record_dist("recovery_wall_s", claim.shard, worker,
                        value=time.perf_counter() - t0)
        print(f"[racon_tpu::dist] worker {worker}: shard "
              f"{claim.shard} complete ({n} target(s))"
              f"{' [stolen]' if claim.stolen else ''}", file=log)

    return _merge_phase(ledger, worker, out, log, poll)
