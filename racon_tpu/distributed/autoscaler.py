"""Elastic fleet supervisor: spawn, retire, and replace ledger workers.

ROADMAP item 2's control loop. Everything the autoscaler needs is
already measured — ``obs/fleet.aggregate`` computes per-worker rates
and straggler flags, the ledger supports stealing and explicit release,
and self-eviction (exit 75) distinguishes "sick worker" from "done" —
this module just closes the loop. The supervisor is deliberately dumb
and stateless-on-disk:

- each control tick it reaps exited workers, attaches to the ledger
  (read-only) and sets ``target = clamp(open shards + unfinished
  merge, min, max)``;
- it holds the fleet at target by spawning real CLI subprocesses
  (``python -m racon_tpu.cli`` with the run's own argv plus a unique
  ``--worker-id``) against the same ``--ledger-dir``, and retiring
  surplus workers with SIGTERM — the worker's signal path releases its
  lease (instantly claimable), leaves a final metric snapshot, and
  exits 128+15;
- exit-75 self-evictions are replaced immediately (outside the target
  policy), with ``RACON_TPU_DIST_AVOID`` seeded from the shard the
  sick worker released so the replacement deprioritizes the wedged
  assignment instead of re-claiming it first;
- any other nonzero exit is an eviction: the next tick's target policy
  refills the slot (spawns are budgeted, so a crash-looping input
  can't fork-bomb the host);
- every tick writes an atomic heartbeat (``obs/autoscaler.json``)
  carrying the decision counters; ``/healthz``'s fleet view
  (obs/export.py::fleet_health) turns a stale heartbeat into a 503.

The supervisor holds no lease and owns no shard state: killing it
mid-run loses nothing — workers finish on their own, and a new
supervisor can attach to the same ledger. When the merge lands it
copies ``out.fasta`` to its stdout, so ``--autoscale`` is a drop-in
for the serial CLI contract (byte-identical output on stdout).

Policy knobs (all ``RACON_TPU_AUTOSCALE_*``):

- ``MIN`` / ``MAX``: worker count clamp (defaults 1 / ``--workers``);
- ``INTERVAL_S``: control cadence (default 0.5);
- ``MAX_SPAWNS``: lifetime spawn budget (default ``max(8, 4*MAX)``);
- ``DEADLINE_S``: kill the fleet and fail after this long (default 0 =
  no deadline);
- ``FAULT_PLAN``: path to a JSON list of fault specs assigned to spawn
  ordinals — worker #i runs with ``RACON_TPU_FAULTS`` set to entry i
  (missing/empty entries run clean). This is scripts/chaos_bench.py's
  seeded injection hook; the supervisor itself never injects.
"""

from __future__ import annotations

import json
import os
from racon_tpu.utils import envspec
import signal
import subprocess
import sys
import time
from typing import Dict, List, Optional

from racon_tpu.distributed import ledger as dledger
from racon_tpu.distributed.ledger import LedgerError, WorkLedger
from racon_tpu.obs import fleet
from racon_tpu.obs.trace import (ENV_TRACE, ENV_TRACE_CTX, env_trace_ctx,
                                 parse_trace_ctx)
from racon_tpu.resilience.faults import ENV_FAULTS
from racon_tpu.resilience.watchdog import EXIT_SELF_EVICT
from racon_tpu.utils.atomicio import atomic_write_bytes

ENV_MIN = "RACON_TPU_AUTOSCALE_MIN"
ENV_MAX = "RACON_TPU_AUTOSCALE_MAX"
ENV_INTERVAL = "RACON_TPU_AUTOSCALE_INTERVAL_S"
ENV_MAX_SPAWNS = "RACON_TPU_AUTOSCALE_MAX_SPAWNS"
ENV_DEADLINE = "RACON_TPU_AUTOSCALE_DEADLINE_S"
ENV_FAULT_PLAN = "RACON_TPU_AUTOSCALE_FAULT_PLAN"

#: How long after merge_done lingering workers (merge losers mid-poll,
#: injected stall sleepers) get before the supervisor SIGTERMs them —
#: the output is already published by then, so the nudge is benign.
DRAIN_GRACE_S = 5.0


def _env_float(name: str, default: float) -> float:
    raw = envspec.read(name).strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise LedgerError(
            f"[racon_tpu::autoscale] {name}={raw!r} is not a number")


class AutoscalePolicy:
    """The clamp + cadence knobs, resolved once at startup."""

    __slots__ = ("min_workers", "max_workers", "interval_s",
                 "max_spawns", "deadline_s")

    def __init__(self, min_workers: int, max_workers: int,
                 interval_s: float, max_spawns: int,
                 deadline_s: float):
        self.min_workers = max(0, int(min_workers))
        self.max_workers = max(1, int(max_workers))
        if self.min_workers > self.max_workers:
            raise LedgerError(
                f"[racon_tpu::autoscale] MIN {self.min_workers} > MAX "
                f"{self.max_workers}")
        self.interval_s = max(0.05, float(interval_s))
        self.max_spawns = max(1, int(max_spawns))
        self.deadline_s = max(0.0, float(deadline_s))

    @classmethod
    def from_env(cls, default_max: int) -> "AutoscalePolicy":
        max_w = int(_env_float(ENV_MAX, max(1, int(default_max))))
        return cls(
            min_workers=int(_env_float(ENV_MIN, 1)),
            max_workers=max_w,
            interval_s=_env_float(ENV_INTERVAL, 0.5),
            max_spawns=int(_env_float(ENV_MAX_SPAWNS,
                                      max(8, 4 * max_w))),
            deadline_s=_env_float(ENV_DEADLINE, 0.0),
        )


def decide(open_work: Optional[int], policy: AutoscalePolicy) -> int:
    """Target worker count for one tick. ``open_work`` counts pending
    shards plus an unfinished merge pseudo-shard; None means the
    ledger meta is not published yet — spawn at MAX optimistically
    (the first worker up publishes the partition and the next tick
    sees real numbers)."""
    if open_work is None:
        return policy.max_workers
    return max(policy.min_workers,
               min(policy.max_workers, open_work))


def worker_argv(raw_argv: List[str]) -> List[str]:
    """The argv a spawned worker runs: the supervisor's own CLI argv
    minus ``--autoscale`` and any ``--worker-id`` (each worker gets a
    unique one appended at spawn)."""
    out: List[str] = []
    skip = False
    for arg in raw_argv:
        if skip:
            skip = False
            continue
        if arg == "--autoscale":
            continue
        if arg == "--worker-id":
            skip = True
            continue
        if arg.startswith("--worker-id="):
            continue
        out.append(arg)
    return out


def _load_fault_plan(log) -> List[str]:
    path = envspec.read(ENV_FAULT_PLAN).strip()
    if not path:
        return []
    try:
        with open(path, "r", encoding="utf-8") as fh:
            plan = json.load(fh)
    except (OSError, ValueError) as exc:
        raise LedgerError(
            f"[racon_tpu::autoscale] unreadable fault plan "
            f"{path!r}: {exc}")
    if not isinstance(plan, list) or \
            not all(isinstance(p, str) for p in plan):
        raise LedgerError(
            f"[racon_tpu::autoscale] fault plan {path!r} must be a "
            "JSON list of RACON_TPU_FAULTS spec strings")
    if any(plan):
        print(f"[racon_tpu::autoscale] fault plan loaded: "
              f"{sum(1 for p in plan if p)} faulted spawn(s) of "
              f"{len(plan)}", file=log)
    return plan


class Autoscaler:
    def __init__(self, ledger_dir: str, raw_argv: List[str], *,
                 policy: Optional[AutoscalePolicy] = None,
                 default_max: int = 1, out=None, log=None,
                 extra_env: Optional[Dict[str, str]] = None,
                 target_fn=None, trace_dir: Optional[str] = None):
        self.ledger_dir = ledger_dir
        self.policy = policy or AutoscalePolicy.from_env(default_max)
        self.out = out if out is not None else sys.stdout.buffer
        self.log = log if log is not None else sys.stderr
        self.argv = worker_argv(raw_argv)
        # Gateway hooks: extra_env is applied to every spawn's env
        # LAST (it wins over the fault-plan/avoid/trace handling —
        # the caller owns those keys when it sets them); target_fn,
        # when given, replaces decide() as the per-tick sizing policy
        # (same (open_work, policy) -> int contract); trace_dir gives
        # every spawn its own trace file so a fleet run's workers
        # land as separate span streams beside the ledger's metric
        # shards.
        self.extra_env = dict(extra_env) if extra_env else {}
        self.target_fn = target_fn
        self.trace_dir = trace_dir
        self.fault_plan = _load_fault_plan(self.log)
        self.obs_dir = os.path.join(ledger_dir, fleet.OBS_SUBDIR)
        self.logs_dir = os.path.join(ledger_dir, "logs")
        self.procs: List[Dict] = []  # {proc, wid, log_fh, retiring}
        self.spawned = 0
        self.counters = {"scale_up_total": 0, "scale_down_total": 0,
                         "replaced_total": 0, "retired_total": 0,
                         "evicted_total": 0, "self_evicted_total": 0,
                         "done_total": 0}
        self.seq = 0

    # ---------------------------------------------------------- spawn
    def _trace_ctx(self) -> str:
        """The context workers should inherit: the supervisor's own
        validated RACON_TPU_TRACE_CTX, else whatever the ledger meta
        publisher stamped ("" when neither exists)."""
        ctx = env_trace_ctx()
        if ctx:
            return ctx
        try:
            led = WorkLedger.attach(self.ledger_dir)
        except LedgerError:
            return ""
        meta_ctx = str(led.meta.get("trace_ctx", ""))
        return meta_ctx if parse_trace_ctx(meta_ctx) else ""

    def _spawn(self, reason: str,
               avoid: Optional[List[str]] = None) -> bool:
        if self.spawned >= self.policy.max_spawns:
            print(f"[racon_tpu::autoscale] spawn budget "
                  f"({self.policy.max_spawns}) exhausted — not "
                  f"spawning ({reason})", file=self.log)
            return False
        wid = f"as{self.spawned}"
        env = dict(os.environ)
        # Workers run clean unless the fault plan targets this spawn
        # ordinal — the supervisor's own env must never leak faults.
        spec = self.fault_plan[self.spawned] \
            if self.spawned < len(self.fault_plan) else ""
        if spec:
            env[ENV_FAULTS] = spec
        else:
            env.pop(ENV_FAULTS, None)
        if avoid:
            env["RACON_TPU_DIST_AVOID"] = ",".join(avoid)
        else:
            env.pop("RACON_TPU_DIST_AVOID", None)
        # Trace handoff: supervisor-spawned workers inherit this
        # process's trace context (own env, else the ledger meta's),
        # so autoscaled replacements land in the same job timeline.
        ctx = self._trace_ctx()
        if ctx:
            env[ENV_TRACE_CTX] = ctx
        else:
            env.pop(ENV_TRACE_CTX, None)
        if self.trace_dir:
            # One trace file per spawn: worker span streams must not
            # clobber each other (or the supervisor's own trace).
            env[ENV_TRACE] = os.path.join(self.trace_dir,
                                          f"worker_{wid}.jsonl")
        env.update(self.extra_env)
        argv = ([sys.executable, "-m", "racon_tpu.cli"] + self.argv +
                ["--worker-id", wid])
        os.makedirs(self.logs_dir, exist_ok=True)
        log_fh = open(os.path.join(self.logs_dir, f"{wid}.log"), "ab")
        try:
            # Worker stdout goes to its log too: only the supervisor
            # emits the merged FASTA (copied from out.fasta), so the
            # merge winner's stdout copy is just a duplicate record.
            proc = subprocess.Popen(argv, stdout=log_fh,
                                    stderr=subprocess.STDOUT, env=env)
        except OSError as exc:
            log_fh.close()
            print(f"[racon_tpu::autoscale] spawn failed: {exc}",
                  file=self.log)
            return False
        self.spawned += 1
        self.procs.append({"proc": proc, "wid": wid, "log_fh": log_fh,
                           "retiring": False})
        dledger.append_event(self.ledger_dir, {
            "ev": "spawn", "worker": wid, "reason": reason,
            "pid": proc.pid, **({"faults": spec} if spec else {}),
            **({"avoid": avoid} if avoid else {}),
            **({"trace_ctx": ctx} if ctx else {})})
        print(f"[racon_tpu::autoscale] spawned worker {wid} "
              f"(pid {proc.pid}, {reason})"
              f"{' faults=' + spec if spec else ''}", file=self.log)
        return True

    # ----------------------------------------------------------- reap
    def _released_shards(self, wid: str) -> List[str]:
        """The shard(s) a worker explicitly released before dying —
        the wedged assignment its replacement should claim last."""
        try:
            led = WorkLedger.attach(self.ledger_dir)
        except LedgerError:
            return []
        return sorted({e["name"] for e in led.events()
                       if e.get("ev") == "release" and
                       e.get("worker") == wid and
                       isinstance(e.get("name"), str)})

    def _reap(self) -> None:
        still: List[Dict] = []
        for w in self.procs:
            rc = w["proc"].poll()
            if rc is None:
                still.append(w)
                continue
            w["log_fh"].close()
            if rc == EXIT_SELF_EVICT:
                # Sick, not done: the worker judged its own host wedged
                # and released its lease. Replace immediately — outside
                # the target policy — steering the replacement away
                # from the assignment that wedged its predecessor.
                self.counters["self_evicted_total"] += 1
                avoid = self._released_shards(w["wid"])
                print(f"[racon_tpu::autoscale] worker {w['wid']} "
                      f"self-evicted (exit {rc}); replacing"
                      f"{' avoiding ' + ','.join(avoid) if avoid else ''}",
                      file=self.log)
                if self._spawn("replace-self-evict", avoid=avoid):
                    self.counters["replaced_total"] += 1
            elif rc == 0:
                self.counters["done_total"] += 1
            elif w["retiring"]:
                self.counters["retired_total"] += 1
            else:
                self.counters["evicted_total"] += 1
                print(f"[racon_tpu::autoscale] worker {w['wid']} "
                      f"evicted (exit {rc}); target policy refills "
                      "next tick", file=self.log)
        self.procs = still

    # --------------------------------------------------------- retire
    def _lease_holders(self, led: WorkLedger) -> set:
        holders = set()
        now = led._now()
        for info in led.all_shards():
            cur = led._read_lease(info.name)
            if cur and not cur.get("released") and \
                    float(cur.get("deadline", 0.0)) > now:
                holders.add(str(cur.get("worker")))
        cur = led._read_lease(dledger.MERGE_NAME)
        if cur and not cur.get("released") and \
                float(cur.get("deadline", 0.0)) > now:
            holders.add(str(cur.get("worker")))
        return holders

    def _retire(self, n: int, led: Optional[WorkLedger],
                reason: str) -> None:
        """SIGTERM ``n`` workers, idle (non-lease-holding) ones first,
        youngest first — a retiring holder releases its lease on the
        signal path, so retiring a holder costs one shard handoff, not
        a lease-expiry wait."""
        holders = self._lease_holders(led) if led is not None else set()
        active = [w for w in self.procs if not w["retiring"]]
        victims = ([w for w in reversed(active)
                    if w["wid"] not in holders] +
                   [w for w in reversed(active) if w["wid"] in holders])
        for w in victims[:n]:
            w["retiring"] = True
            try:
                w["proc"].send_signal(signal.SIGTERM)
            except OSError:
                continue
            dledger.append_event(self.ledger_dir, {
                "ev": "retire", "worker": w["wid"], "reason": reason})
            print(f"[racon_tpu::autoscale] retiring worker {w['wid']} "
                  f"({reason})", file=self.log)

    # ------------------------------------------------------ heartbeat
    def _heartbeat(self, target: int, open_work: Optional[int],
                   done: bool) -> None:
        live = sum(1 for w in self.procs if not w["retiring"])
        rec = {
            "schema": 1,
            "unix_time": round(time.time(), 3),
            "interval_s": self.policy.interval_s,
            "target_workers": target,
            "live_workers": live,
            "open_shards": open_work,
            "spawned_total": self.spawned,
            "done": bool(done),
            "seq": self.seq,
            "workers_live": live,
            "workers_retired": self.counters["retired_total"],
            "workers_evicted": self.counters["evicted_total"] +
            self.counters["self_evicted_total"],
            "workers_done": self.counters["done_total"],
            **self.counters,
            "metrics": {
                "dist_scale_up_total":
                    self.counters["scale_up_total"],
                "dist_scale_down_total":
                    self.counters["scale_down_total"],
                "fleet_target_workers": target,
            },
        }
        self.seq += 1
        os.makedirs(self.obs_dir, exist_ok=True)
        try:
            atomic_write_bytes(
                os.path.join(self.obs_dir, fleet.SUPERVISOR_NAME),
                (json.dumps(rec, sort_keys=True) + "\n").encode())
        except OSError:
            pass  # heartbeat is advisory; the fleet runs without it

    # ------------------------------------------------------------ run
    def run(self) -> int:
        import shutil
        pol = self.policy
        os.makedirs(self.ledger_dir, exist_ok=True)
        print(f"[racon_tpu::autoscale] supervising {self.ledger_dir}: "
              f"workers [{pol.min_workers}, {pol.max_workers}], tick "
              f"{pol.interval_s:g}s, spawn budget {pol.max_spawns}",
              file=self.log)
        t0 = time.monotonic()
        drain_since: Optional[float] = None
        try:
            while True:
                self._reap()
                try:
                    led: Optional[WorkLedger] = \
                        WorkLedger.attach(self.ledger_dir)
                except LedgerError:
                    led = None  # meta not yet published
                done = led is not None and led.merge_done()
                open_work: Optional[int] = None
                if led is not None:
                    open_work = len(led.pending_shards()) + \
                        (0 if done else 1)
                if done:
                    target = 0
                    if not self.procs:
                        self._heartbeat(target, open_work, True)
                        break
                    if drain_since is None:
                        drain_since = time.monotonic()
                    elif time.monotonic() - drain_since > \
                            DRAIN_GRACE_S:
                        # Output is published; lingering merge losers
                        # or injected stall sleepers just need a nudge.
                        self._retire(len(self.procs), led, "drain")
                        drain_since = time.monotonic()
                else:
                    target = self.target_fn(open_work, pol) \
                        if self.target_fn is not None \
                        else decide(open_work, pol)
                    live = sum(1 for w in self.procs
                               if not w["retiring"])
                    while live < target:
                        if not self._spawn("scale-up"):
                            break
                        self.counters["scale_up_total"] += 1
                        live += 1
                    if live > target:
                        self.counters["scale_down_total"] += \
                            live - target
                        self._retire(live - target, led, "scale-down")
                    if not self.procs and \
                            self.spawned >= pol.max_spawns:
                        self._heartbeat(target, open_work, False)
                        print("[racon_tpu::autoscale] error: spawn "
                              "budget exhausted with the run "
                              "unfinished — giving up", file=self.log)
                        return 1
                self._heartbeat(target, open_work, done)
                if pol.deadline_s and \
                        time.monotonic() - t0 > pol.deadline_s:
                    print(f"[racon_tpu::autoscale] error: deadline "
                          f"{pol.deadline_s:g}s exceeded — killing "
                          "the fleet", file=self.log)
                    return 1
                time.sleep(pol.interval_s)
        finally:
            # Whatever path exits the loop (success, budget, deadline,
            # signal): never leave orphan workers running.
            for w in self.procs:
                try:
                    w["proc"].kill()
                    w["proc"].wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    pass
                w["log_fh"].close()
        led = WorkLedger.attach(self.ledger_dir)
        with open(led.out_path, "rb") as fh:
            shutil.copyfileobj(fh, self.out)
        self.out.flush()
        wall = time.monotonic() - t0
        print(f"[racon_tpu::autoscale] fleet finished in {wall:.1f}s: "
              f"{self.spawned} spawn(s), "
              f"{self.counters['done_total']} done, "
              f"{self.counters['evicted_total']} evicted, "
              f"{self.counters['self_evicted_total']} self-evicted, "
              f"{self.counters['retired_total']} retired "
              f"({led.out_path} -> stdout)", file=self.log)
        return 0


def run_supervisor(*, ledger_dir: str, raw_argv: List[str],
                   default_max: int = 1, out=None, log=None) -> int:
    """CLI entry (``--autoscale``): supervise a fleet against
    ``ledger_dir`` until the merged output exists, then emit it on
    stdout. Returns a process exit code."""
    scaler = Autoscaler(ledger_dir, raw_argv, default_max=default_max,
                        out=out, log=log)
    return scaler.run()
