"""Contig work ledger: shards, leases, stealing, ordered merge.

The ledger is a directory on a filesystem every worker can reach::

    <ledger-dir>/
      meta.json          run identity + shard partition (published once,
                         atomically — publish_exclusive)
      events.jsonl       append-only audit log (claims/steals/completes)
      shard_<k>.lease    {"name", "worker", "epoch", "nonce", "deadline"}
      shard_<k>.done     completion marker (lease-fenced write)
      shard_<k>/         that shard's CheckpointStore (meta.json,
                         contigs.fasta, manifest.jsonl)
      merge.lease        the merge phase is itself a stealable
      merge.done         pseudo-shard, so a worker evicted mid-merge
      out.fasta          doesn't strand the run

There is no coordinator. Liveness is a **time-bounded lease**: a worker
claims a shard by publishing its lease file, renews the deadline as it
polishes, and any survivor may rewrite an *expired* lease to steal the
shard. Mutual exclusion is best-effort (two workers can transiently
hold the same shard across a steal race or a paused-then-resumed
victim); correctness never depends on it:

- compute is deterministic, and commits land in the shard's own
  append-only checkpoint store — a duplicate commit re-appends the
  same bytes and the manifest's last record wins, so the merged output
  is unchanged;
- the **nonce is the fence**: every renew/complete re-reads the lease
  and raises :class:`LeaseLost` when its nonce is gone, so a stale
  worker stops promptly instead of finishing a stolen shard;
- ``meta.json`` is immutable after publication and carries the run
  fingerprint, so two differently-configured runs can never share a
  ledger (same refusal discipline as resilience/checkpoint.py).

Steals verify their write won by re-reading the lease and comparing
nonces — with rename-atomic lease files, the last writer wins and every
loser observes a foreign nonce. Lease clocks honor ``clock_skew()``
(the ``skew=`` fault clause), so expiry is provable in tier-1 without
wall-clock waits.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Iterator, List, Optional, Tuple

from racon_tpu.obs.metrics import record_dist
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience.faults import clock_skew, maybe_fault
from racon_tpu.utils.atomicio import (append_fsync, atomic_write_bytes,
                                      atomic_writer, publish_exclusive)

SCHEMA = 1
META_NAME = "meta.json"
EVENTS_NAME = "events.jsonl"
MERGE_NAME = "merge"
OUT_NAME = "out.fasta"
ENV_SHARDS = "RACON_TPU_DIST_SHARDS"


class LedgerError(ValueError):
    """Unusable ledger: fingerprint/schema mismatch, corrupt metadata,
    or a done shard whose store doesn't cover its target range. A hard
    error — silently recomputing would mask operator mistakes."""


class LeaseLost(RuntimeError):
    """This worker's lease was stolen (its nonce is gone). The holder
    must abandon the shard immediately; the thief owns it now."""

    def __init__(self, name: str, worker: str):
        super().__init__(
            f"[racon_tpu::dist] worker {worker} lost its lease on "
            f"{name} — shard was stolen after lease expiry")
        self.name = name


class Claim:
    """A held lease. ``shard`` is the shard index (-1 for the merge
    pseudo-shard); ``stolen`` records whether this claim evicted a
    previous holder (its committed prefix will be resumed)."""

    __slots__ = ("name", "shard", "worker", "epoch", "nonce", "stolen",
                 "deadline")

    def __init__(self, name: str, shard: int, worker: str, epoch: int,
                 nonce: str, stolen: bool, deadline: float):
        self.name = name
        self.shard = shard
        self.worker = worker
        self.epoch = epoch
        self.nonce = nonce
        self.stolen = stolen
        self.deadline = deadline


def _partition(n_targets: int, n_shards: int) -> List[int]:
    """Contiguous balanced partition bounds: shard k owns targets
    [bounds[k], bounds[k+1]). Contiguity keeps each shard's checkpoint
    manifest a prefix of an input-order walk — the same invariant the
    serial resume path relies on."""
    base, extra = divmod(n_targets, n_shards)
    bounds = [0]
    for k in range(n_shards):
        bounds.append(bounds[-1] + base + (1 if k < extra else 0))
    return bounds


class WorkLedger:
    def __init__(self, directory: str, meta: Dict):
        self.directory = directory
        self.meta = meta
        self.fingerprint: str = meta["fingerprint"]
        self.bounds: List[int] = [int(b) for b in meta["bounds"]]
        self.n_shards: int = len(self.bounds) - 1
        self.n_targets: int = int(meta["n_targets"])
        self.lease_s: float = float(meta["lease_s"])
        # Optional per-target byte offsets into the target file (from
        # io.parsers.scan_sequence_index, published by the winner) —
        # observability plus a future seek-to-shard ingest hook.
        off = meta.get("target_offsets")
        self.target_offsets: Optional[List[int]] = \
            None if off is None else [int(o) for o in off]

    # ------------------------------------------------------- open
    @classmethod
    def open(cls, directory: str, fingerprint: str, *,
             n_targets: Optional[int] = None, workers: int = 1,
             lease_s: float = 30.0, n_shards: Optional[int] = None,
             scan_targets=None) -> "WorkLedger":
        """Open (publishing if first) the ledger for this run.

        Every worker calls this with its own view of the run identity;
        whoever gets here first publishes ``meta.json`` atomically and
        everyone else adopts the published partition — so all workers
        agree on shard bounds and lease duration even if their CLI
        flags disagree.

        ``n_targets`` may be None when ``scan_targets`` (a callable
        returning ``(count, per-target byte offsets)``, typically
        io.parsers.scan_sequence_index on the target file) is given: a
        worker joining an ALREADY-PUBLISHED ledger then adopts the
        published count without touching the target file at all — the
        fingerprint check still guards against mismatched inputs, so
        the per-worker recount it replaces was pure duplicated I/O
        (docs/DISTRIBUTED.md's ingest note). Only the publishing worker
        pays the scan, and it publishes the offsets alongside the count
        so nobody ever scans twice.
        """
        path = os.path.join(directory, META_NAME)
        published: Optional[Dict] = None
        if os.path.isfile(path):
            published = cls._read_meta(path, directory)
        offsets = None
        if published is None:
            if n_targets is None:
                if scan_targets is None:
                    raise LedgerError(
                        "[racon_tpu::dist] opening an unpublished "
                        "ledger needs n_targets or scan_targets")
                n_targets, offsets = scan_targets()
            if n_targets < 1:
                raise LedgerError(
                    "[racon_tpu::dist] refusing to open a ledger for "
                    "an empty target set")
            if n_shards is None:
                env = os.environ.get(ENV_SHARDS, "")
                if env:
                    n_shards = int(env)
                else:
                    # Over-partition ~2x the fleet so a steal transfers
                    # a shard's worth of work, not half the run.
                    n_shards = max(1, int(workers) * 2)
            n_shards = max(1, min(int(n_shards), n_targets))
            os.makedirs(directory, exist_ok=True)
            meta = {
                "schema": SCHEMA,
                "fingerprint": fingerprint,
                "n_targets": int(n_targets),
                "bounds": _partition(n_targets, n_shards),
                "lease_s": float(lease_s),
                "workers": int(workers),
            }
            if offsets is not None:
                meta["target_offsets"] = [int(o) for o in offsets]
            blob = (json.dumps(meta, sort_keys=True) + "\n").encode()
            publish_exclusive(path, blob)
            # Winner or not, the published file is the contract.
            published = cls._read_meta(path, directory)
        if published.get("schema") != SCHEMA:
            raise LedgerError(
                f"[racon_tpu::dist] ledger schema "
                f"{published.get('schema')!r} != {SCHEMA}")
        if published.get("fingerprint") != fingerprint:
            raise LedgerError(
                "[racon_tpu::dist] refusing to join ledger "
                f"{directory!r}: its fingerprint does not match this "
                "run — inputs or output-affecting options changed")
        if n_targets is not None and \
                published.get("n_targets") != n_targets:
            raise LedgerError(
                f"[racon_tpu::dist] ledger target count "
                f"{published.get('n_targets')!r} != {n_targets} seen "
                "by this worker")
        return cls(directory, published)

    @staticmethod
    def _read_meta(path: str, directory: str) -> Dict:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError) as exc:
            raise LedgerError(
                f"[racon_tpu::dist] unreadable ledger {META_NAME} in "
                f"{directory!r} ({exc})") from exc

    # ------------------------------------------------------ layout
    def shard_range(self, k: int) -> Tuple[int, int]:
        return self.bounds[k], self.bounds[k + 1]

    def shard_ckpt_dir(self, k: int) -> str:
        return os.path.join(self.directory, f"shard_{k}")

    def shard_fp(self, k: int) -> str:
        return ckpt.shard_fingerprint(self.fingerprint, k)

    @property
    def out_path(self) -> str:
        return os.path.join(self.directory, OUT_NAME)

    def _lease_path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.lease")

    def _done_path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.done")

    def _now(self) -> float:
        return time.time() + clock_skew()

    # ------------------------------------------------------ events
    def _event(self, rec: Dict) -> None:
        rec = dict(rec, t=round(time.time(), 3))
        data = (json.dumps(rec, sort_keys=True) + "\n").encode()
        # O_APPEND: concurrent single-write appends from multiple
        # workers interleave whole records. Advisory, so best-effort.
        try:
            with open(os.path.join(self.directory, EVENTS_NAME),
                      "ab") as fh:
                append_fsync(fh, data)
        except OSError:
            pass

    def events(self) -> List[Dict]:
        from racon_tpu.utils.atomicio import load_jsonl_prefix
        path = os.path.join(self.directory, EVENTS_NAME)
        if not os.path.exists(path):
            return []
        records, _ = load_jsonl_prefix(path)
        return records

    # ------------------------------------------------------ leases
    def _read_lease(self, name: str) -> Optional[Dict]:
        """None when absent, unreadable, or torn — an unreadable lease
        is treated as expired (its writer crashed mid-publish; nothing
        can renew it)."""
        try:
            with open(self._lease_path(name), "rb") as fh:
                rec = json.loads(fh.read())
            if not isinstance(rec, dict):
                return None
            return rec
        except (OSError, ValueError):
            return None

    def is_done(self, name: str) -> bool:
        return os.path.exists(self._done_path(name))

    def _try_claim(self, name: str, shard: int,
                   worker: str) -> Optional[Claim]:
        """Claim ``name`` if unclaimed, or steal it if its lease
        expired. Returns None when someone else holds a live lease (or
        won the race)."""
        if self.is_done(name):
            return None
        maybe_fault("dist/claim")
        path = self._lease_path(name)
        nonce = os.urandom(8).hex()
        now = self._now()
        lease = {"name": name, "worker": worker, "epoch": 1,
                 "nonce": nonce, "deadline": now + self.lease_s}
        if not os.path.exists(path):
            blob = (json.dumps(lease, sort_keys=True) + "\n").encode()
            if publish_exclusive(path, blob):
                self._event({"ev": "claim", "name": name,
                             "worker": worker, "epoch": 1})
                record_dist("claims" if shard >= 0 else "merge_claims",
                            shard, worker)
                return Claim(name, shard, worker, 1, nonce, False,
                             lease["deadline"])
            # Lost the first-claim race; fall through and look at what
            # the winner published.
        cur = self._read_lease(name)
        if cur is not None and float(cur.get("deadline", 0.0)) > now:
            return None  # live lease — not ours to touch
        # Expired (or torn) lease: steal by rewriting it, then verify
        # our write survived — concurrent stealers race on the rename
        # and every loser sees a foreign nonce on re-read.
        epoch = int(cur.get("epoch", 0)) + 1 if cur else 1
        expired_for = max(0.0, now - float(cur.get("deadline", now))) \
            if cur else 0.0
        victim = cur.get("worker", "?") if cur else "?"
        lease["epoch"] = epoch
        lease["deadline"] = self._now() + self.lease_s
        atomic_write_bytes(path, (json.dumps(
            lease, sort_keys=True) + "\n").encode())
        back = self._read_lease(name)
        if back is None or back.get("nonce") != nonce:
            return None  # another stealer's rename landed after ours
        if shard >= 0:
            record_dist("leases_expired", shard, worker)
            record_dist("shards_stolen", shard, worker, epoch=epoch)
            record_dist("steal_latency_s", shard, worker,
                        value=expired_for)
        else:
            record_dist("merge_steals", shard, worker, epoch=epoch)
        self._event({"ev": "steal", "name": name, "worker": worker,
                     "victim": victim, "epoch": epoch,
                     "expired_for_s": round(expired_for, 3)})
        return Claim(name, shard, worker, epoch, nonce, True,
                     lease["deadline"])

    def claim_shard(self, worker: str) -> Optional[Claim]:
        """The next shard this worker can own, scanning in index order
        (earliest incomplete work first, which also keeps the merge's
        wait roughly FIFO). None when every shard is done or
        live-leased elsewhere."""
        for k in range(self.n_shards):
            claim = self._try_claim(f"shard_{k}", k, worker)
            if claim is not None:
                return claim
        return None

    def claim_merge(self, worker: str) -> Optional[Claim]:
        return self._try_claim(MERGE_NAME, -1, worker)

    def verify(self, claim: Claim) -> None:
        """Fencing check: raise LeaseLost unless ``claim``'s nonce is
        still the one on disk."""
        cur = self._read_lease(claim.name)
        if cur is None or cur.get("nonce") != claim.nonce:
            record_dist("leases_lost", claim.shard, claim.worker)
            raise LeaseLost(claim.name, claim.worker)

    def renew(self, claim: Claim) -> None:
        """Push the deadline out; raises LeaseLost if stolen. Renewing
        an expired-but-unstolen lease succeeds — expiry only matters
        if a thief acted on it."""
        self.verify(claim)
        lease = {"name": claim.name, "worker": claim.worker,
                 "epoch": claim.epoch, "nonce": claim.nonce,
                 "deadline": self._now() + self.lease_s}
        atomic_write_bytes(self._lease_path(claim.name), (json.dumps(
            lease, sort_keys=True) + "\n").encode())
        claim.deadline = lease["deadline"]
        record_dist("lease_renewals", claim.shard, claim.worker)
        self._event({"ev": "renew", "name": claim.name,
                     "worker": claim.worker, "epoch": claim.epoch})

    def release(self, claim: Claim) -> None:
        """Hand a held lease back WITHOUT completing it — the self-
        eviction path (resilience/watchdog.py): a worker that has
        judged itself wedged unlinks its lease so any thief can claim
        the shard immediately via the first-claim fast path instead of
        waiting out the lease term. Committed prefix work stays in the
        shard's checkpoint store; the successor resumes it
        byte-identically.

        A foreign nonce on disk means the lease was already stolen —
        benign (nonce fencing protects completion), so the release is
        a silent no-op rather than an error on a worker that is
        already giving up.
        """
        cur = self._read_lease(claim.name)
        if cur is None or cur.get("nonce") != claim.nonce:
            return
        try:
            os.remove(self._lease_path(claim.name))
        except OSError:
            return
        record_dist("releases", claim.shard, claim.worker)
        self._event({"ev": "release", "name": claim.name,
                     "worker": claim.worker, "epoch": claim.epoch})

    def complete(self, claim: Claim, **info) -> None:
        """Publish the done marker, fenced by a final verify so a stale
        worker can't mark a shard done with a thief mid-recompute."""
        self.verify(claim)
        rec = {"name": claim.name, "worker": claim.worker,
               "epoch": claim.epoch}
        rec.update(info)
        atomic_write_bytes(self._done_path(claim.name), (json.dumps(
            rec, sort_keys=True) + "\n").encode())
        self._event(dict(rec, ev="complete"))

    def shards_done(self) -> bool:
        return all(self.is_done(f"shard_{k}")
                   for k in range(self.n_shards))

    def pending_shards(self) -> List[int]:
        return [k for k in range(self.n_shards)
                if not self.is_done(f"shard_{k}")]

    def merge_done(self) -> bool:
        return self.is_done(MERGE_NAME) and os.path.exists(
            self.out_path)

    # ------------------------------------------------------- merge
    def iter_merged(self) -> Iterator[Tuple[int, Optional[bytes]]]:
        """Yield ``(tid, blob-or-None)`` in target input order across
        all shard stores — the exact bytes each shard committed, so
        concatenation is byte-identical to the serial path. Requires
        every shard done."""
        for k in range(self.n_shards):
            start, end = self.shard_range(k)
            if start == end:
                continue
            store = ckpt.CheckpointStore.resume(self.shard_ckpt_dir(k),
                                                self.shard_fp(k))
            try:
                for tid in range(start, end):
                    if tid not in store.committed:
                        raise LedgerError(
                            f"[racon_tpu::dist] shard {k} is marked "
                            f"done but target {tid} has no committed "
                            "record — ledger corrupt")
                    yield tid, store.read_emitted(tid)
            finally:
                store.close()

    def merge(self) -> Tuple[int, int]:
        """Assemble ``out.fasta`` from the shard stores (caller holds
        the merge claim). Returns ``(bytes, contigs_emitted)``. Written
        via tmp + fsync + atomic finalize, so a worker evicted
        mid-merge leaves no partial output and its thief redoes the
        whole (cheap, read-only) pass."""
        if not self.shards_done():
            raise LedgerError(
                "[racon_tpu::dist] merge requested with shards still "
                f"pending: {self.pending_shards()}")
        total = emitted = 0
        with atomic_writer(self.out_path) as fh:
            for _tid, blob in self.iter_merged():
                if blob is None:
                    continue
                # Per-blob drill point: a term/kill/raise here proves a
                # death mid-merge never leaves a torn out.fasta (the
                # writer unlinks its tmp; the thief redoes the pass).
                maybe_fault("dist/merge_write")
                fh.write(blob)
                total += len(blob)
                emitted += 1
        return total, emitted
