"""Contig work ledger: shards, leases, stealing, splitting, ordered merge.

The ledger is a directory on a filesystem every worker can reach::

    <ledger-dir>/
      meta.json          run identity + shard partition (published once,
                         atomically — publish_exclusive)
      events.jsonl       append-only audit log (claims/steals/completes/
                         splits, plus the autoscaler's spawn/retire)
      shard_<k>.lease    {"name", "worker", "epoch", "nonce", "deadline"}
      shard_<k>.done     completion marker (lease-fenced write)
      shard_<k>/         that shard's CheckpointStore (meta.json,
                         contigs.fasta, manifest.jsonl)
      shard_<k>s<e>_<i>.range
                         a child shard carved off shard_<k> by a dynamic
                         split: {"parent", "start", "end", ...} published
                         atomically (publish_exclusive). The child has
                         its own lease/done/store files under its own
                         name and is itself splittable, so lineages nest.
      merge.lease        the merge phase is itself a stealable
      merge.done         pseudo-shard, so a worker evicted mid-merge
      out.fasta          doesn't strand the run

There is no coordinator. Liveness is a **time-bounded lease**: a worker
claims a shard by publishing its lease file, renews the deadline as it
polishes, and any survivor may rewrite an *expired* lease to steal the
shard. Mutual exclusion is best-effort (two workers can transiently
hold the same shard across a steal race or a paused-then-resumed
victim); correctness never depends on it:

- compute is deterministic, and commits land in the shard's own
  append-only checkpoint store — a duplicate commit re-appends the
  same bytes and the manifest's last record wins, so the merged output
  is unchanged;
- the **nonce is the fence**: every renew/complete re-reads the lease
  and raises :class:`LeaseLost` when its nonce is gone, so a stale
  worker stops promptly instead of finishing a stolen shard;
- ``meta.json`` is immutable after publication and carries the run
  fingerprint, so two differently-configured runs can never share a
  ledger (same refusal discipline as resilience/checkpoint.py).

Steals verify their write won by re-reading the lease and comparing
nonces — with rename-atomic lease files, the last writer wins and every
loser observes a foreign nonce. Lease clocks honor ``clock_skew()``
(the ``skew=`` fault clause), so expiry is provable in tier-1 without
wall-clock waits.

The published partition is only the *initial* one: a worker stuck on a
long shard can :meth:`WorkLedger.split` it at a committed-contig
boundary, carving the tail into a new instantly-stealable child shard
(docs/DISTRIBUTED.md "Elastic fleets"). :meth:`all_shards` is the
single source of truth for what is claimable: base shards with every
child's carve applied, effective ranges tiling [0, n_targets) exactly.
"""

from __future__ import annotations

import json
import os
from racon_tpu.utils import envspec
import re
import time
from typing import Dict, Iterator, List, Optional, Tuple, Union

from racon_tpu.obs.metrics import record_dist
from racon_tpu.resilience import checkpoint as ckpt
from racon_tpu.resilience.faults import (clock_skew, hard_exit,
                                         maybe_fault, maybe_torn)
from racon_tpu.utils.atomicio import (append_fsync, atomic_write_bytes,
                                      atomic_writer, publish_exclusive)

SCHEMA = 1
META_NAME = "meta.json"
EVENTS_NAME = "events.jsonl"
MERGE_NAME = "merge"
OUT_NAME = "out.fasta"
RANGE_SUFFIX = ".range"
ENV_SHARDS = "RACON_TPU_DIST_SHARDS"
ENV_SPLIT = "RACON_TPU_SPLIT"


def split_enabled() -> bool:
    """Dynamic shard splitting is on unless RACON_TPU_SPLIT=0 — the
    off switch exists so the monster-contig drill (scripts/
    chaos_bench.py --monster) can measure the serialized tail it
    kills."""
    return envspec.read(ENV_SPLIT).strip().lower() not in (
        "0", "false", "no", "off")


ENV_SPLIT_DEPTH = "RACON_TPU_SPLIT_DEPTH"

_SPLIT_SEG = re.compile(r"s\d+_\d+")


def split_depth(name: str) -> int:
    """How many split generations deep a shard name is (0 for a seed
    shard): every :meth:`WorkLedger.split` appends one
    ``s<epoch>_<seq>`` segment to the parent's name."""
    return len(_SPLIT_SEG.findall(name))


def max_split_depth() -> int:
    """Depth cap for dynamic splitting (RACON_TPU_SPLIT_DEPTH,
    default 1: seed shards split, children don't). Every handoff costs
    the new holder a fresh polisher build, so without a cap two
    workers trading a shrinking tail back and forth — each donating
    its remainder to the other the moment the other goes idle — turn
    one shard into a cascade of one-contig claims that is strictly
    slower than never splitting at all."""
    env = envspec.read(ENV_SPLIT_DEPTH).strip()
    if env:
        try:
            return max(0, int(env))
        except ValueError:
            pass
    return 1


def append_event(directory: str, rec: Dict) -> None:
    """Append one record to the ledger's events.jsonl. O_APPEND:
    concurrent single-write appends from multiple processes interleave
    whole records. The log is advisory (timelines, obs_report), so
    failures are swallowed — module-level so the autoscaler can log
    spawn/retire decisions without holding a ledger."""
    rec = dict(rec, t=round(time.time(), 3))  # lint: wallclock-ok (advisory event timestamp, not run state)
    data = (json.dumps(rec, sort_keys=True) + "\n").encode()
    try:
        with open(os.path.join(directory, EVENTS_NAME), "ab") as fh:
            append_fsync(fh, data)
    except OSError:
        pass


class LedgerError(ValueError):
    """Unusable ledger: fingerprint/schema mismatch, corrupt metadata,
    or a done shard whose store doesn't cover its target range. A hard
    error — silently recomputing would mask operator mistakes."""


class LeaseLost(RuntimeError):
    """This worker's lease was stolen (its nonce is gone). The holder
    must abandon the shard immediately; the thief owns it now."""

    def __init__(self, name: str, worker: str):
        super().__init__(
            f"[racon_tpu::dist] worker {worker} lost its lease on "
            f"{name} — shard was stolen after lease expiry")
        self.name = name


class ShardInfo:
    """One claimable unit of work: a base shard of the published
    partition, or a child carved off a parent by a dynamic split.

    ``end`` is the *effective* end — the published end minus every
    child carved off this shard's tail — so effective ranges always
    tile [0, n_targets). ``root`` is the base-partition index the
    lineage descends from (the int metrics/trace tag); ``key`` seeds
    the checkpoint fingerprint, so a parent store and a child store are
    mutually unspliceable even though they cover adjacent targets.
    """

    __slots__ = ("name", "key", "start", "end", "parent", "root")

    def __init__(self, name: str, key: Union[int, str], start: int,
                 end: int, parent: Optional[str] = None, root: int = 0):
        self.name = name
        self.key = key
        self.start = int(start)
        self.end = int(end)
        self.parent = parent
        self.root = int(root)

    @property
    def n_targets(self) -> int:
        return self.end - self.start

    def __repr__(self) -> str:  # debugging/log aid only
        return (f"ShardInfo({self.name}, [{self.start}, {self.end})"
                f"{', child of ' + self.parent if self.parent else ''})")


class Claim:
    """A held lease. ``shard`` is the lineage-root shard index (-1 for
    the merge pseudo-shard); ``info`` carries the claimed shard's
    effective range (None for merge); ``stolen`` records whether this
    claim evicted a previous holder (its committed prefix will be
    resumed)."""

    __slots__ = ("name", "shard", "worker", "epoch", "nonce", "stolen",
                 "deadline", "info")

    def __init__(self, name: str, shard: int, worker: str, epoch: int,
                 nonce: str, stolen: bool, deadline: float,
                 info: Optional[ShardInfo] = None):
        self.name = name
        self.shard = shard
        self.worker = worker
        self.epoch = epoch
        self.nonce = nonce
        self.stolen = stolen
        self.deadline = deadline
        self.info = info


def _partition(n_targets: int, n_shards: int) -> List[int]:
    """Contiguous balanced partition bounds: shard k owns targets
    [bounds[k], bounds[k+1]). Contiguity keeps each shard's checkpoint
    manifest a prefix of an input-order walk — the same invariant the
    serial resume path relies on."""
    base, extra = divmod(n_targets, n_shards)
    bounds = [0]
    for k in range(n_shards):
        bounds.append(bounds[-1] + base + (1 if k < extra else 0))
    return bounds


class WorkLedger:
    def __init__(self, directory: str, meta: Dict):
        self.directory = directory
        self.meta = meta
        self.fingerprint: str = meta["fingerprint"]
        self.bounds: List[int] = [int(b) for b in meta["bounds"]]
        self.n_shards: int = len(self.bounds) - 1
        self.n_targets: int = int(meta["n_targets"])
        self.lease_s: float = float(meta["lease_s"])
        # Optional per-target byte offsets into the target file (from
        # io.parsers.scan_sequence_index, published by the winner).
        # They drive the weighted partition above, feed the ava shape
        # planner (every worker derives per-target lengths from them
        # without re-scanning), and remain the seek-to-shard hook.
        off = meta.get("target_offsets")
        self.target_offsets: Optional[List[int]] = \
            None if off is None else [int(o) for o in off]

    # ------------------------------------------------------- open
    @classmethod
    def open(cls, directory: str, fingerprint: str, *,
             n_targets: Optional[int] = None, workers: int = 1,
             lease_s: float = 30.0, n_shards: Optional[int] = None,
             scan_targets=None, weighted: bool = False) -> "WorkLedger":
        """Open (publishing if first) the ledger for this run.

        Every worker calls this with its own view of the run identity;
        whoever gets here first publishes ``meta.json`` atomically and
        everyone else adopts the published partition — so all workers
        agree on shard bounds and lease duration even if their CLI
        flags disagree.

        ``n_targets`` may be None when ``scan_targets`` (a callable
        returning ``(count, per-target byte offsets)``, typically
        io.parsers.scan_sequence_index on the target file) is given: a
        worker joining an ALREADY-PUBLISHED ledger then adopts the
        published count without touching the target file at all — the
        fingerprint check still guards against mismatched inputs, so
        the per-worker recount it replaces was pure duplicated I/O
        (docs/DISTRIBUTED.md's ingest note). Only the publishing worker
        pays the scan, and it publishes the offsets alongside the count
        so nobody ever scans twice.
        """
        path = os.path.join(directory, META_NAME)
        published: Optional[Dict] = None
        if os.path.isfile(path):
            published = cls._read_meta(path, directory)
        offsets = None
        if published is None:
            if n_targets is None:
                if scan_targets is None:
                    raise LedgerError(
                        "[racon_tpu::dist] opening an unpublished "
                        "ledger needs n_targets or scan_targets")
                n_targets, offsets = scan_targets()
            if n_targets < 1:
                raise LedgerError(
                    "[racon_tpu::dist] refusing to open a ledger for "
                    "an empty target set")
            if n_shards is None:
                env = envspec.read(ENV_SHARDS)
                if env:
                    n_shards = int(env)
                else:
                    # Over-partition ~2x the fleet so a steal transfers
                    # a shard's worth of work, not half the run.
                    n_shards = max(1, int(workers) * 2)
            n_shards = max(1, min(int(n_shards), n_targets))
            os.makedirs(directory, exist_ok=True)
            bounds = _partition(n_targets, n_shards)
            if weighted and offsets is not None:
                # Length-weighted bounds for read-scale target sets:
                # the ava regime's targets span orders of magnitude in
                # size, so equal-count shards can differ 10x in work.
                # Opt-in per open (the kF worker passes weighted=True)
                # so contig-polish runs keep the count partition their
                # fault-index drills are written against. Only the
                # publishing worker computes this (from the offsets it
                # just scanned); joiners adopt the published bounds
                # like any other partition (docs/AVA.md).
                from racon_tpu.ava.partition import weighted_bounds
                wb = weighted_bounds(n_targets, n_shards, offsets)
                if wb is not None:
                    bounds = wb
            meta = {
                "schema": SCHEMA,
                "fingerprint": fingerprint,
                "n_targets": int(n_targets),
                "bounds": bounds,
                "lease_s": float(lease_s),
                "workers": int(workers),
            }
            if offsets is not None:
                meta["target_offsets"] = [int(o) for o in offsets]
            # Publish the submitting process's trace context (if any)
            # so late joiners with no RACON_TPU_TRACE_CTX of their own
            # still adopt the job's trace_id. Published once with the
            # meta, immutable like everything else in it.
            from racon_tpu.obs.trace import env_trace_ctx
            ctx = env_trace_ctx()
            if ctx:
                meta["trace_ctx"] = ctx
            blob = (json.dumps(meta, sort_keys=True) + "\n").encode()
            publish_exclusive(path, blob)
            # Winner or not, the published file is the contract.
            published = cls._read_meta(path, directory)
        if published.get("schema") != SCHEMA:
            raise LedgerError(
                f"[racon_tpu::dist] ledger schema "
                f"{published.get('schema')!r} != {SCHEMA}")
        if published.get("fingerprint") != fingerprint:
            raise LedgerError(
                "[racon_tpu::dist] refusing to join ledger "
                f"{directory!r}: its fingerprint does not match this "
                "run — inputs or output-affecting options changed")
        if n_targets is not None and \
                published.get("n_targets") != n_targets:
            raise LedgerError(
                f"[racon_tpu::dist] ledger target count "
                f"{published.get('n_targets')!r} != {n_targets} seen "
                "by this worker")
        return cls(directory, published)

    @classmethod
    def attach(cls, directory: str) -> "WorkLedger":
        """Read-mostly attach for tooling — the autoscaler, the
        /healthz fleet view, obs_report — which observes shard/lease
        state but never polishes or merges: it adopts whatever
        fingerprint the published meta carries instead of proving its
        own inputs match."""
        meta = cls._read_meta(os.path.join(directory, META_NAME),
                              directory)
        if meta.get("schema") != SCHEMA:
            raise LedgerError(
                f"[racon_tpu::dist] ledger schema "
                f"{meta.get('schema')!r} != {SCHEMA}")
        return cls(directory, meta)

    @staticmethod
    def _read_meta(path: str, directory: str) -> Dict:
        try:
            with open(path, "r", encoding="utf-8") as fh:
                return json.load(fh)
        except (OSError, ValueError) as exc:
            raise LedgerError(
                f"[racon_tpu::dist] unreadable ledger {META_NAME} in "
                f"{directory!r} ({exc})") from exc

    # ------------------------------------------------------ layout
    def shard_range(self, k: int) -> Tuple[int, int]:
        return self.bounds[k], self.bounds[k + 1]

    def shard_ckpt_dir(self, k: Union[int, str, ShardInfo]) -> str:
        if isinstance(k, ShardInfo):
            name = k.name
        elif isinstance(k, str):
            name = k
        else:
            name = f"shard_{k}"
        return os.path.join(self.directory, name)

    def shard_fp(self, k: Union[int, str, ShardInfo]) -> str:
        key = k.key if isinstance(k, ShardInfo) else k
        return ckpt.shard_fingerprint(self.fingerprint, key)

    @property
    def out_path(self) -> str:
        return os.path.join(self.directory, OUT_NAME)

    def _lease_path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.lease")

    def _done_path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}.done")

    def _range_path(self, name: str) -> str:
        return os.path.join(self.directory, f"{name}{RANGE_SUFFIX}")

    def _now(self) -> float:
        return time.time() + clock_skew()

    # ------------------------------------------------------ events
    def _event(self, rec: Dict) -> None:
        append_event(self.directory, rec)

    def events(self) -> List[Dict]:
        from racon_tpu.utils.atomicio import load_jsonl_prefix
        path = os.path.join(self.directory, EVENTS_NAME)
        if not os.path.exists(path):
            return []
        records, _ = load_jsonl_prefix(path)
        return records

    # ------------------------------------------------------ shards
    def _read_range(self, path: str) -> Optional[Dict]:
        """A child shard's published .range record, or None when the
        file is torn, foreign, or structurally invalid — an invalid
        .range means the split never happened (the dist/split torn
        drill's contract: a half-published child is invisible)."""
        try:
            with open(path, "rb") as fh:
                rec = json.loads(fh.read())
        except (OSError, ValueError):
            return None
        if not isinstance(rec, dict):
            return None
        try:
            name = rec["name"]
            parent = rec["parent"]
            start, end = int(rec["start"]), int(rec["end"])
            root = int(rec["root"])
        except (KeyError, TypeError, ValueError):
            return None
        if rec.get("fingerprint") != self.fingerprint:
            return None
        if not (isinstance(name, str) and isinstance(parent, str)):
            return None
        if not 0 <= start < end <= self.n_targets:
            return None
        return {"name": name, "parent": parent, "start": start,
                "end": end, "root": root}

    def all_shards(self) -> List[ShardInfo]:
        """Every claimable shard — the published base partition plus
        all dynamically split children — with carving applied: each
        child shrinks its parent's effective end to the child's start,
        so the effective ranges tile [0, n_targets) exactly, in start
        order. Rescans the directory: a split published by another
        worker is visible at this worker's next claim poll."""
        infos: Dict[str, ShardInfo] = {}
        for k in range(self.n_shards):
            s, e = self.bounds[k], self.bounds[k + 1]
            infos[f"shard_{k}"] = ShardInfo(f"shard_{k}", k, s, e,
                                            None, k)
        try:
            entries = sorted(os.listdir(self.directory))
        except OSError:
            entries = []
        for fn in entries:
            if not fn.endswith(RANGE_SUFFIX):
                continue
            rec = self._read_range(os.path.join(self.directory, fn))
            if rec is None or rec["name"] != fn[:-len(RANGE_SUFFIX)]:
                continue
            # A child's name extends its parent's ("<parent>s<e>_<i>"),
            # so the sorted scan inserts parents before their children
            # and nested lineages resolve in one pass.
            if rec["parent"] not in infos:
                continue
            infos[rec["name"]] = ShardInfo(
                rec["name"], rec["name"][len("shard_"):],
                rec["start"], rec["end"], rec["parent"], rec["root"])
        for info in infos.values():
            if info.parent is not None:
                parent = infos[info.parent]
                if info.start < parent.end:
                    parent.end = info.start
        return sorted(infos.values(), key=lambda i: (i.start, i.end))

    def open_shard_stats(self) -> Dict[str, int]:
        """One pass over live shard state: ``open`` (not done),
        ``claimable`` (open with no live lease — a steal or first
        claim would succeed right now), ``leased`` (open under a live
        lease). Feeds the worker's split trigger and the autoscaler's
        target policy."""
        now = self._now()
        stats = {"open": 0, "claimable": 0, "leased": 0}
        for info in self.all_shards():
            if info.start >= info.end or self.is_done(info.name):
                continue
            stats["open"] += 1
            cur = self._read_lease(info.name)
            if cur is not None and float(cur.get("deadline", 0.0)) > now:
                stats["leased"] += 1
            else:
                stats["claimable"] += 1
        return stats

    # ------------------------------------------------------ leases
    def _read_lease(self, name: str) -> Optional[Dict]:
        """None when absent, unreadable, or torn — an unreadable lease
        is treated as expired (its writer crashed mid-publish; nothing
        can renew it)."""
        try:
            with open(self._lease_path(name), "rb") as fh:
                rec = json.loads(fh.read())
            if not isinstance(rec, dict):
                return None
            return rec
        except (OSError, ValueError):
            return None

    def is_done(self, name: str) -> bool:
        return os.path.exists(self._done_path(name))

    def _try_claim(self, name: str, shard: int, worker: str,
                   info: Optional[ShardInfo] = None) -> Optional[Claim]:
        """Claim ``name`` if unclaimed, or steal it if its lease
        expired. Returns None when someone else holds a live lease (or
        won the race)."""
        if self.is_done(name):
            return None
        maybe_fault("dist/claim")
        path = self._lease_path(name)
        nonce = os.urandom(8).hex()
        now = self._now()
        lease = {"name": name, "worker": worker, "epoch": 1,
                 "nonce": nonce, "deadline": now + self.lease_s}
        if not os.path.exists(path):
            blob = (json.dumps(lease, sort_keys=True) + "\n").encode()
            if publish_exclusive(path, blob):
                self._event({"ev": "claim", "name": name,
                             "worker": worker, "epoch": 1})
                record_dist("claims" if shard >= 0 else "merge_claims",
                            shard, worker)
                return Claim(name, shard, worker, 1, nonce, False,
                             lease["deadline"], info)
            # Lost the first-claim race; fall through and look at what
            # the winner published.
        cur = self._read_lease(name)
        if cur is not None and float(cur.get("deadline", 0.0)) > now:
            return None  # live lease — not ours to touch
        # Expired, explicitly released, or torn lease: take it by
        # rewriting, then verify our write survived — concurrent takers
        # race on the rename and every loser sees a foreign nonce on
        # re-read.
        released = bool(cur.get("released")) if cur else False
        epoch = int(cur.get("epoch", 0)) + 1 if cur else 1
        expired_for = max(0.0, now - float(cur.get("deadline", now))) \
            if cur else 0.0
        victim = cur.get("worker", "?") if cur else "?"
        lease["epoch"] = epoch
        lease["deadline"] = self._now() + self.lease_s
        atomic_write_bytes(path, (json.dumps(
            lease, sort_keys=True) + "\n").encode())
        back = self._read_lease(name)
        if back is None or back.get("nonce") != nonce:
            return None  # another taker's rename landed after ours
        if released:
            # A released marker is a cooperative handoff, not an
            # eviction: count it as a claim, and ``stolen`` stays False
            # (the committed prefix still resumes — resume keys off the
            # store, not the flag).
            self._event({"ev": "claim", "name": name, "worker": worker,
                         "epoch": epoch, "released_by": victim})
            record_dist("claims" if shard >= 0 else "merge_claims",
                        shard, worker)
            return Claim(name, shard, worker, epoch, nonce, False,
                         lease["deadline"], info)
        if shard >= 0:
            record_dist("leases_expired", shard, worker)
            record_dist("shards_stolen", shard, worker, epoch=epoch)
            record_dist("steal_latency_s", shard, worker,
                        value=expired_for)
        else:
            record_dist("merge_steals", shard, worker, epoch=epoch)
        self._event({"ev": "steal", "name": name, "worker": worker,
                     "victim": victim, "epoch": epoch,
                     "expired_for_s": round(expired_for, 3)})
        return Claim(name, shard, worker, epoch, nonce, True,
                     lease["deadline"], info)

    def claim_shard(self, worker: str,
                    avoid: Optional[List[str]] = None) -> \
            Optional[Claim]:
        """The next shard this worker can own, scanning effective
        shards (base partition plus split children) in target order —
        earliest incomplete work first, which also keeps the merge's
        wait roughly FIFO. ``avoid`` deprioritizes named shards (the
        autoscaler hands a replacement worker the shard its sick
        predecessor released) without ever excluding them: a wedged
        shard is still claimed when nothing else is left. None when
        every shard is done or live-leased elsewhere."""
        avoided = set(avoid or ())
        shards = self.all_shards()
        ordered = [i for i in shards if i.name not in avoided] + \
                  [i for i in shards if i.name in avoided]
        for info in ordered:
            if info.start >= info.end:
                continue
            claim = self._try_claim(info.name, info.root, worker,
                                    info=info)
            if claim is not None:
                return claim
        return None

    def claim_merge(self, worker: str) -> Optional[Claim]:
        return self._try_claim(MERGE_NAME, -1, worker)

    def verify(self, claim: Claim) -> None:
        """Fencing check: raise LeaseLost unless ``claim``'s nonce is
        still the one on disk."""
        cur = self._read_lease(claim.name)
        if cur is None or cur.get("nonce") != claim.nonce:
            record_dist("leases_lost", claim.shard, claim.worker)
            raise LeaseLost(claim.name, claim.worker)

    def renew(self, claim: Claim) -> None:
        """Push the deadline out; raises LeaseLost if stolen. Renewing
        an expired-but-unstolen lease succeeds — expiry only matters
        if a thief acted on it."""
        self.verify(claim)
        lease = {"name": claim.name, "worker": claim.worker,
                 "epoch": claim.epoch, "nonce": claim.nonce,
                 "deadline": self._now() + self.lease_s}
        atomic_write_bytes(self._lease_path(claim.name), (json.dumps(
            lease, sort_keys=True) + "\n").encode())
        claim.deadline = lease["deadline"]
        record_dist("lease_renewals", claim.shard, claim.worker)
        self._event({"ev": "renew", "name": claim.name,
                     "worker": claim.worker, "epoch": claim.epoch})

    def release(self, claim: Claim) -> None:
        """Hand a held lease back WITHOUT completing it — self-eviction
        (resilience/watchdog.py) and supervisor-driven retirement: the
        shard becomes claimable at any worker's next poll instead of
        waiting out the lease term. Committed prefix work stays in the
        shard's checkpoint store; the successor resumes it
        byte-identically.

        The release is published as a *marker lease* (``released``,
        deadline 0) via the same atomic rename every steal uses — never
        an unlink. Check-then-unlink had a race window: a thief's
        steal-rewrite landing between our nonce read and our remove
        would be deleted, silently revoking the thief's freshly won
        claim. Renames serialize instead — whichever lands last wins,
        and the other side's nonce re-read refuses. Regression:
        tests/test_distributed.py two-thief release/split race.

        A foreign nonce on disk means the lease was already stolen —
        benign (nonce fencing protects completion), so the release is
        a silent no-op rather than an error on a worker that is
        already giving up.
        """
        cur = self._read_lease(claim.name)
        if cur is None or cur.get("nonce") != claim.nonce:
            return
        marker = {"name": claim.name, "worker": claim.worker,
                  "epoch": claim.epoch, "nonce": os.urandom(8).hex(),
                  "deadline": 0.0, "released": True}
        atomic_write_bytes(self._lease_path(claim.name), (json.dumps(
            marker, sort_keys=True) + "\n").encode())
        record_dist("releases", claim.shard, claim.worker)
        self._event({"ev": "release", "name": claim.name,
                     "worker": claim.worker, "epoch": claim.epoch})

    def complete(self, claim: Claim, **info) -> None:
        """Publish the done marker, fenced by a final verify so a stale
        worker can't mark a shard done with a thief mid-recompute."""
        self.verify(claim)
        rec = {"name": claim.name, "worker": claim.worker,
               "epoch": claim.epoch}
        rec.update(info)
        atomic_write_bytes(self._done_path(claim.name), (json.dumps(
            rec, sort_keys=True) + "\n").encode())
        self._event(dict(rec, ev="complete"))

    # ------------------------------------------------------- split
    def split(self, claim: Claim, cut: int) -> Optional[ShardInfo]:
        """Carve ``[cut, end)`` off a held shard into a new child shard
        that any idle worker can claim immediately — the dynamic
        re-sharding that kills the monster-contig tail
        (docs/DISTRIBUTED.md "Elastic fleets").

        Protocol (nonce-fenced both sides of the publish):

        1. verify the lease — only the live holder may split;
        2. publish the child's ``.range`` file with publish_exclusive
           (``dist/split`` is the torn-write drill site: a split that
           dies mid-publish must be invisible, so readers drop
           unparseable .range files);
        3. re-verify — if the lease was stolen inside the publish
           window, the thief claimed the *full* parent range, so the
           child is retracted (unlinked) and LeaseLost raised; without
           the retraction the fleet could polish [cut, end) twice under
           two names and the tiling check would refuse the merge.

        The child gets its own lease/done/checkpoint files under its
        own name and a checkpoint fingerprint derived from that name,
        so parent and child stores are mutually unspliceable; its
        ``.range`` record carries the parent name, making lineage
        reconstructable (obs_report --fleet renders the chain). Returns
        the child's ShardInfo, or None when the publish lost a name
        race (the caller may simply retry later). ``claim.info.end``
        shrinks to ``cut`` on success.
        """
        info = claim.info
        if info is None:
            raise LedgerError(
                "[racon_tpu::dist] only shard claims can split")
        if not info.start < cut < info.end:
            raise LedgerError(
                f"[racon_tpu::dist] split cut {cut} outside the held "
                f"range [{info.start}, {info.end}) of {info.name}")
        self.verify(claim)
        try:
            n_prior = sum(
                1 for fn in os.listdir(self.directory)
                if fn.startswith(info.name + "s") and
                fn.endswith(RANGE_SUFFIX))
        except OSError:
            n_prior = 0
        child = f"{info.name}s{claim.epoch}_{n_prior + 1}"
        rec = {"schema": SCHEMA, "name": child, "parent": info.name,
               "root": info.root, "start": int(cut),
               "end": int(info.end), "fingerprint": self.fingerprint}
        blob = (json.dumps(rec, sort_keys=True) + "\n").encode()
        path = self._range_path(child)
        if maybe_torn("dist/split"):
            # The drill: die mid-publish leaving a truncated .range at
            # the final path (publish_exclusive's tmp+link can't tear,
            # so the drill bypasses it), durable, then hard-exit —
            # readers must treat the torn child as "no split happened".
            with open(path, "wb") as fh:  # lint: atomic-ok (torn-write drill)
                fh.write(blob[:max(1, len(blob) - 9)])
                fh.flush()
                os.fsync(fh.fileno())
            hard_exit(137)
        if not publish_exclusive(path, blob):
            return None
        try:
            self.verify(claim)
        except LeaseLost:
            try:
                os.remove(path)
            except OSError:
                pass
            raise
        old_end, info.end = info.end, int(cut)
        record_dist("splits_total", info.root, claim.worker,
                    child=child)
        self._event({"ev": "split", "name": info.name, "child": child,
                     "worker": claim.worker, "epoch": claim.epoch,
                     "start": int(cut), "end": int(old_end)})
        return ShardInfo(child, child[len("shard_"):], cut, old_end,
                         info.name, info.root)

    # ----------------------------------------------------- progress
    def shards_done(self) -> bool:
        return all(self.is_done(i.name) for i in self.all_shards()
                   if i.start < i.end)

    def pending_shards(self) -> List[str]:
        return [i.name for i in self.all_shards()
                if i.start < i.end and not self.is_done(i.name)]

    def merge_done(self) -> bool:
        return self.is_done(MERGE_NAME) and os.path.exists(
            self.out_path)

    # ------------------------------------------------------- merge
    def iter_merged(self) -> Iterator[Tuple[int, Optional[bytes]]]:
        """Yield ``(tid, blob-or-None)`` in target input order across
        all shard stores — base shards and split children stitched by
        their effective ranges — the exact bytes each shard committed,
        so concatenation is byte-identical to the serial path. Requires
        every shard done; refuses when the split lineage does not tile
        the target range (a corrupt .range escaped the readers'
        validation)."""
        pos = 0
        for info in self.all_shards():
            if info.start >= info.end:
                continue
            if info.start != pos:
                raise LedgerError(
                    f"[racon_tpu::dist] split lineage does not tile "
                    f"the target range: expected a shard starting at "
                    f"{pos}, found {info.name} at {info.start} — "
                    "ledger corrupt")
            pos = info.end
            store = ckpt.CheckpointStore.resume(
                self.shard_ckpt_dir(info), self.shard_fp(info))
            try:
                for tid in range(info.start, info.end):
                    if tid not in store.committed:
                        raise LedgerError(
                            f"[racon_tpu::dist] shard {info.name} is "
                            f"marked done but target {tid} has no "
                            "committed record — ledger corrupt")
                    yield tid, store.read_emitted(tid)
            finally:
                store.close()
        if pos != self.n_targets:
            raise LedgerError(
                f"[racon_tpu::dist] split lineage does not tile the "
                f"target range: coverage ends at {pos}, expected "
                f"{self.n_targets} — ledger corrupt")

    def merge(self) -> Tuple[int, int]:
        """Assemble ``out.fasta`` from the shard stores (caller holds
        the merge claim). Returns ``(bytes, contigs_emitted)``. Written
        via tmp + fsync + atomic finalize, so a worker evicted
        mid-merge leaves no partial output and its thief redoes the
        whole (cheap, read-only) pass."""
        if not self.shards_done():
            raise LedgerError(
                "[racon_tpu::dist] merge requested with shards still "
                f"pending: {self.pending_shards()}")
        total = emitted = 0
        with atomic_writer(self.out_path) as fh:
            for _tid, blob in self.iter_merged():
                if blob is None:
                    continue
                # Per-blob drill point: a term/kill/raise here proves a
                # death mid-merge never leaves a torn out.fasta (the
                # writer unlinks its tmp; the thief redoes the pass).
                maybe_fault("dist/merge_write")
                fh.write(blob)
                total += len(blob)
                emitted += 1
        return total, emitted
