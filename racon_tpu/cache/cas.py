"""Tier 1: the job-level content-addressed result store.

One entry per job fingerprint (:meth:`JobSpec.fingerprint` — scoring
config plus the content digests of all three inputs), holding the
job's committed contig records exactly as the checkpoint store would
replay them. An entry is a single object file::

    {"schema": 1, "key": ..., "digest": ..., "records": [...]}\\n
    <payload bytes — every record's data, concatenated in order>

The header's ``digest`` is sha256 over the canonical records metadata
plus the payload, so *any* corruption — a flipped bit, a torn tail, a
truncated write — is caught on load. The safety contract is strict:

- **Verify on hit.** A hit is only served after the digest recomputes
  clean. Anything else demotes to a miss, increments
  ``cache_verify_fail_total``, and quarantines the object (renamed to
  ``*.quarantine`` so the evidence survives but can never be served).
  A poisoned cache can cost recompute time; it can never change
  output bytes.
- **Atomic publication.** Object files and the LRU index are written
  via :mod:`racon_tpu.utils.atomicio`, so a crash mid-store leaves
  either the old state or the new — never a half-entry. Recovery is
  journal-aware: the constructor reloads the index, drops entries
  whose object vanished, and does *not* re-hash payloads (that work
  happens per hit, where it pays).
- **Bounded.** ``RACON_TPU_CACHE_MAX_MB`` bounds total object bytes;
  eviction is LRU over an integer recency sequence (no wallclock —
  DET001) and republishes the index atomically.

Fault sites: ``cache/store`` fires *before* the object write (an
injected failure skips the store; the job result is unaffected);
``cache/load`` supports the ``!torn`` action, which truncates the
just-read object bytes in process to simulate reading a torn entry —
the drill scripts/cache_smoke.py runs to prove verify-on-hit holds.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

from racon_tpu.obs.metrics import record_cache
from racon_tpu.resilience.faults import InjectedFault, maybe_fault, \
    maybe_torn
from racon_tpu.utils import envspec
from racon_tpu.utils.atomicio import atomic_write_bytes, \
    atomic_write_text

ENV_CACHE_MAX_MB = "RACON_TPU_CACHE_MAX_MB"

_SCHEMA = 1
_INDEX = "index.json"

# A record is (tid, name, data): name None marks a dropped target
# (committed with no emission — checkpoint.commit_dropped).
Record = Tuple[int, Optional[bytes], bytes]


class CacheError(RuntimeError):
    """Raised for unusable cache roots; never for entry corruption
    (corruption is demoted to a miss, not an error)."""


def records_from_store(store) -> List[Record]:
    """Derive the CAS records for a finished job from its checkpoint
    store: the exact inverse of the ``b">" + name + b"\\n" + data +
    b"\\n"`` blob each commit wrote, in tid order so replay reproduces
    the committed stream byte for byte."""
    records: List[Record] = []
    for tid in sorted(store.committed):
        blob = store.read_emitted(tid)
        if blob is None:
            records.append((tid, None, b""))
        else:
            nl = blob.index(b"\n")
            records.append((tid, bytes(blob[1:nl]),
                            bytes(blob[nl + 1:-1])))
    return records


def replay_records(records: List[Record], emit=None, store=None) -> int:
    """Replay verified CAS records through the same emit-then-commit
    order polish_job uses, so streams, journals, and restart recovery
    see a cache hit exactly as they would a fresh run. Returns the
    number of emitted (non-dropped) records."""
    n = 0
    for tid, name, data in records:
        if name is None:
            if store is not None:
                store.commit_dropped(tid)
            continue
        if emit is not None:
            emit(b">" + name + b"\n" + data + b"\n")
        if store is not None:
            store.commit(tid, name, data)
        n += 1
    return n


def _encode(key: str, records: List[Record]) -> bytes:
    meta = [{"tid": tid,
             "name": None if name is None else name.decode("latin-1"),
             "len": len(data)} for tid, name, data in records]
    payload = b"".join(data for _, _, data in records)
    meta_json = json.dumps(meta, sort_keys=True,
                           separators=(",", ":"))
    digest = hashlib.sha256(meta_json.encode() + payload).hexdigest()
    header = json.dumps({"schema": _SCHEMA, "key": key,
                         "digest": digest, "records": meta},
                        sort_keys=True, separators=(",", ":"))
    return header.encode() + b"\n" + payload


def _decode_verify(key: str, raw: bytes) -> Optional[List[Record]]:
    """Parse and digest-check an object file; ``None`` on *any*
    defect — the caller treats that as a miss and quarantines."""
    try:
        nl = raw.index(b"\n")
        head = json.loads(raw[:nl].decode())
        if head.get("schema") != _SCHEMA or head.get("key") != key:
            return None
        meta = head["records"]
        payload = raw[nl + 1:]
        if len(payload) != sum(int(m["len"]) for m in meta):
            return None
        meta_json = json.dumps(meta, sort_keys=True,
                               separators=(",", ":"))
        if hashlib.sha256(meta_json.encode() +
                          payload).hexdigest() != head["digest"]:
            return None
        records: List[Record] = []
        off = 0
        for m in meta:
            ln = int(m["len"])
            name = m["name"]
            records.append((int(m["tid"]),
                            None if name is None
                            else name.encode("latin-1"),
                            payload[off:off + ln]))
            off += ln
        return records
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None


class ResultCache:
    """The on-disk CAS. Thread-safe: the daemon's worker pool stores
    and probes concurrently; all index state is guarded by one lock
    and published atomically."""

    def __init__(self, directory: str,
                 max_bytes: Optional[int] = None) -> None:
        self.directory = directory
        self.objects = os.path.join(directory, "objects")
        try:
            os.makedirs(self.objects, exist_ok=True)
        except OSError as exc:
            raise CacheError(
                f"[racon_tpu::cache] unusable cache root "
                f"{directory!r}: {exc}") from exc
        if max_bytes is None:
            max_bytes = int(envspec.read(ENV_CACHE_MAX_MB)) * 1024 * 1024
        self.max_bytes = max_bytes
        self._lock = threading.Lock()
        self._entries: Dict[str, Dict] = {}  # guarded-by: _lock
        self._seq = 0                        # guarded-by: _lock
        self._recover()

    # ------------------------------------------------------------ index

    def _index_path(self) -> str:
        return os.path.join(self.directory, _INDEX)

    def _object_path(self, key: str) -> str:
        return os.path.join(self.objects, key)

    def _recover(self) -> None:
        """Journal-aware recovery: the atomically-published index is
        complete-or-absent, so reload it wholesale, drop entries whose
        object file is gone, and trust payloads until a hit verifies
        them — a restart never re-hashes the world."""
        try:
            with open(self._index_path()) as fh:
                idx = json.load(fh)
        except (OSError, ValueError):
            return
        if not isinstance(idx, dict) or idx.get("schema") != _SCHEMA:
            return
        with self._lock:
            for key, ent in sorted(idx.get("entries", {}).items()):
                if os.path.isfile(self._object_path(key)):
                    self._entries[key] = {"bytes": int(ent["bytes"]),
                                          "seq": int(ent["seq"])}
            self._seq = max([int(idx.get("seq", 0))] +
                            [e["seq"] for e in self._entries.values()])

    def _publish_index_locked(self) -> None:
        atomic_write_text(self._index_path(), json.dumps(
            {"schema": _SCHEMA, "seq": self._seq,
             "entries": self._entries}, sort_keys=True))

    # ------------------------------------------------------- store/load

    def store(self, key: str, records: List[Record]) -> bool:
        """Write an entry, LRU-evict past the byte bound, republish
        the index. An injected ``cache/store`` fault skips the store
        and returns False — the caller's job result is never coupled
        to cache health."""
        try:
            maybe_fault("cache/store")
        except InjectedFault:
            return False
        blob = _encode(key, records)
        atomic_write_bytes(self._object_path(key), blob)
        evicted: List[Tuple[str, int]] = []
        with self._lock:
            self._seq += 1
            self._entries[key] = {"bytes": len(blob),
                                  "seq": self._seq}
            # Evict by ascending recency seq (integer, no wallclock —
            # DET001) until under the bound; the just-stored entry
            # always survives so an oversized single job degrades to
            # cache-of-one, not thrash.
            total = sum(e["bytes"] for e in self._entries.values())
            while total > self.max_bytes and len(self._entries) > 1:
                victim = min((k for k in self._entries if k != key),
                             key=lambda k: self._entries[k]["seq"],
                             default=None)
                if victim is None:
                    break
                ent = self._entries.pop(victim)
                total -= ent["bytes"]
                try:
                    os.remove(self._object_path(victim))
                except OSError:
                    pass
                evicted.append((victim, ent["bytes"]))
            self._publish_index_locked()
        record_cache("job", "store", nbytes=len(blob))
        for _, _nb in evicted:
            record_cache("job", "evict")
        return True

    def load(self, key: str) -> Optional[List[Record]]:
        """Probe for a verified entry. Misses, unreadable objects, and
        any verification defect return ``None``; defects additionally
        quarantine the object so it is never probed again."""
        with self._lock:
            ent = self._entries.get(key)
        if ent is None:
            record_cache("job", "miss")
            return None
        try:
            with open(self._object_path(key), "rb") as fh:
                raw = fh.read()
        except OSError:
            raw = b""
        if maybe_torn("cache/load"):
            # Poisoning drill: the reader sees a torn entry — keep
            # only a prefix so the digest cannot recompute clean.
            raw = raw[:max(0, len(raw) // 2)]
        records = _decode_verify(key, raw)
        if records is None:
            self._quarantine(key)
            record_cache("job", "verify_fail")
            record_cache("job", "miss")
            return None
        with self._lock:
            if key in self._entries:
                self._seq += 1
                self._entries[key]["seq"] = self._seq
                self._publish_index_locked()
        record_cache("job", "hit")
        return records

    def _quarantine(self, key: str) -> None:
        with self._lock:
            self._entries.pop(key, None)
            self._publish_index_locked()
        path = self._object_path(key)
        try:
            os.replace(path, path + ".quarantine")
        except OSError:
            pass

    # ------------------------------------------------------------ misc

    def window_spill_dir(self, scoring_key) -> str:
        """A per-scoring-config spill directory for Tier-2 memo
        eviction, namespaced by config digest so incompatible scoring
        runs can never cross-pollinate."""
        slug = hashlib.sha256(repr(scoring_key).encode()).hexdigest()
        return os.path.join(self.directory, "windows", slug[:12])

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries),
                    "bytes": sum(e["bytes"]
                                 for e in self._entries.values())}
