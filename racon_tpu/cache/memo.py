"""Tier 2: window-level consensus memoization for the batcher.

Consensus is a pure function of (window content, scoring config) —
the determinism invariant the serial/serve differential tests pin —
so a window's finished consensus can be keyed by a digest of exactly
those inputs and replayed for any later window with identical
content, whatever job or tenant it arrives from. The cross-request
batcher probes this store before packing windows into a dispatch:
hits skip the device entirely and splice straight into ordered
retirement, so a job that partially overlaps earlier work dispatches
only the delta.

The store is an in-memory LRU (``OrderedDict`` over an integer
recency order — no wallclock, DET001) bounded by entry count; evicted
entries spill to per-scoring-config files when a spill directory is
given (the daemon points it under the Tier-1 cache root). Spill files
carry their own sha256 and are verified on read — a torn or corrupt
spill demotes to a miss and is unlinked, mirroring the Tier-1
verify-on-hit contract. One :class:`WindowMemo` belongs to exactly
one batcher, i.e. one scoring config; the scoring key is folded into
every digest anyway, so even a misrouted spill directory cannot serve
a value computed under different scoring.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import List, Optional, Tuple

from racon_tpu.obs.metrics import record_cache
from racon_tpu.utils.atomicio import atomic_write_bytes

# Memo value: (consensus bytes, polished flag) exactly as
# Window.apply_consensus left them — post coverage-trim, so a hit
# never re-runs trimming.
Value = Tuple[bytes, bool]

_DEFAULT_MAX_ENTRIES = 4096


def _blob(x: Optional[bytes]) -> bytes:
    """Length-prefix with a None marker so (b"", None) and adjacent
    field boundaries cannot collide."""
    if x is None:
        return b"N"
    b = bytes(x)
    return b"B%d:" % len(b) + b


def window_digest(scoring: bytes, window) -> str:
    """The content digest that names a window's consensus: scoring
    config + window type + backbone (+quality) + every layer's
    (data, quality, begin, end) in insertion order."""
    h = hashlib.sha256()
    h.update(scoring)
    h.update(b"|t%d|" % int(window.type.value))
    h.update(_blob(window.backbone))
    h.update(_blob(window.backbone_quality))
    for i in range(len(window.layer_data)):
        h.update(b"|L|")
        h.update(_blob(window.layer_data[i]))
        h.update(_blob(window.layer_quality[i]))
        h.update(b"%d:%d" % (int(window.layer_begin[i]),
                             int(window.layer_end[i])))
    return h.hexdigest()


class WindowMemo:
    """Bounded, spillable consensus memo. Thread-safe; the batcher's
    staging thread and the submitting request threads both touch it."""

    def __init__(self, scoring_key, max_entries: Optional[int] = None,
                 spill_dir: Optional[str] = None) -> None:
        self._scoring = hashlib.sha256(
            repr(scoring_key).encode()).digest()
        self._max = max_entries or _DEFAULT_MAX_ENTRIES
        self._spill_dir = spill_dir
        if spill_dir is not None:
            os.makedirs(spill_dir, exist_ok=True)
        self._lock = threading.Lock()
        self._mem: "OrderedDict[str, Value]" = \
            OrderedDict()  # guarded-by: _lock

    def digest(self, window) -> str:
        return window_digest(self._scoring, window)

    # ------------------------------------------------------------ spill

    def _spill_path(self, key: str) -> str:
        return os.path.join(self._spill_dir, key)

    def _spill_read(self, key: str) -> Optional[Value]:
        """Verified spill read: sha256(flag + consensus) header; any
        mismatch (torn write survivor, bit rot) unlinks the file and
        reads as a miss."""
        if self._spill_dir is None:
            return None
        try:
            with open(self._spill_path(key), "rb") as fh:
                raw = fh.read()
        except OSError:
            return None
        if len(raw) < 33 or \
                hashlib.sha256(raw[32:]).digest() != raw[:32]:
            try:
                os.remove(self._spill_path(key))
            except OSError:
                pass
            record_cache("window", "verify_fail")
            return None
        return raw[33:], raw[32:33] == b"P"

    # -------------------------------------------------------- get / put

    def get(self, window) -> Optional[Value]:
        """Probe by content digest; refreshes recency on an in-memory
        hit and falls back to the spill tier. Returns None on miss —
        accounting is the batcher's job (it aggregates per chunk)."""
        key = self.digest(window)
        with self._lock:
            val = self._mem.get(key)
            if val is not None:
                self._mem.move_to_end(key)
                return val
        return self._spill_read(key)

    def put(self, window) -> Optional[int]:
        """Memoize a finished window's consensus. Returns the stored
        byte count, or None when there is nothing to store (consensus
        never produced). Overflow evicts the least-recently-used entry
        to the spill tier (or drops it when no spill dir is set)."""
        if window.consensus is None:
            return None
        key = self.digest(window)
        val = (bytes(window.consensus), bool(window.polished))
        spilled: List[Tuple[str, Value]] = []
        with self._lock:
            self._mem[key] = val
            self._mem.move_to_end(key)
            while len(self._mem) > self._max:
                old_key, old_val = self._mem.popitem(last=False)
                spilled.append((old_key, old_val))
        for old_key, (cons, polished) in spilled:
            if self._spill_dir is not None:
                body = (b"P" if polished else b"U") + cons
                atomic_write_bytes(
                    self._spill_path(old_key),
                    hashlib.sha256(body).digest() + body)
            record_cache("window", "evict")
        return len(val[0])

    def __len__(self) -> int:
        with self._lock:
            return len(self._mem)
