"""Two-tier content-addressed result cache (docs/CACHE.md).

A polishing service sees the same inputs repeatedly — re-polish after
an upstream tweak, shared reference datasets across tenants, retried or
resubmitted jobs — yet without this package every submission
redispatches every window. The identity machinery that makes caching
safe already exists and is trusted: :meth:`JobSpec.fingerprint`
(config + content digests of all three inputs, resilience/checkpoint.py
``run_fingerprint``) names a whole job's output, and window consensus
is a pure function of (window content, scoring config) — the
per-window determinism invariant the serial/streaming/serve
differential tests have pinned since PR 3.

Two tiers, both keyed purely by content:

- **Tier 1 — job-level CAS** (:class:`~racon_tpu.cache.cas.ResultCache`):
  an on-disk store of committed contig records keyed by the job
  fingerprint, verify-on-hit (a corrupt or torn entry demotes to a
  miss and is quarantined — it can never change output bytes),
  size-bounded LRU eviction over an atomically-published index, and
  journal-aware recovery (a daemon restart reloads the index without
  re-hashing payloads; verification happens per hit, where it pays).
- **Tier 2 — window memoization**
  (:class:`~racon_tpu.cache.memo.WindowMemo`): consensus memoization
  inside the cross-request batcher — each window is probed by its
  content digest before it is packed into a dispatch; hits skip the
  device entirely and splice into ordered retirement, so
  partially-overlapping jobs dispatch only the delta.

Gates: the cache is ON by default for the resident daemon and OFF for
the serial CLI unless ``--cache-dir`` is given; ``RACON_TPU_CACHE=0``
disables both tiers everywhere, falling back byte-identically to the
uncached path. Fault sites ``cache/load`` / ``cache/store`` drill the
poisoning and store-failure paths; ``cache_*`` registry metrics and
``cache`` trace points carry the accounting (docs/OBSERVABILITY.md).
"""

from __future__ import annotations

import os

from racon_tpu.cache.cas import (CacheError, ResultCache,
                                 records_from_store, replay_records)
from racon_tpu.cache.memo import WindowMemo, window_digest
from racon_tpu.utils import envspec

ENV_CACHE = "RACON_TPU_CACHE"
ENV_CACHE_DIR = "RACON_TPU_CACHE_DIR"
ENV_CACHE_WINDOWS = "RACON_TPU_CACHE_WINDOWS"

__all__ = ["CacheError", "ResultCache", "WindowMemo", "cache_enabled",
           "cache_dir_for", "records_from_store", "replay_records",
           "window_digest", "window_memo_enabled", "ENV_CACHE",
           "ENV_CACHE_DIR", "ENV_CACHE_WINDOWS"]


def cache_enabled() -> bool:
    """The global cache gate: on unless ``RACON_TPU_CACHE`` is
    explicitly 0/false. Frontends add their own arming condition on
    top (the daemon arms by default; the serial CLI only with
    ``--cache-dir``)."""
    return envspec.read(ENV_CACHE) not in ("0", "false")


def window_memo_enabled() -> bool:
    """Tier-2 gate: window memoization rides the main gate and can be
    turned off alone with ``RACON_TPU_CACHE_WINDOWS=0`` (Tier 1 keeps
    serving whole-job hits)."""
    return cache_enabled() and \
        envspec.read(ENV_CACHE_WINDOWS) not in ("0", "false")


def cache_dir_for(state_dir: str) -> str:
    """The daemon's cache root: ``RACON_TPU_CACHE_DIR`` when set, else
    ``<state-dir>/cache`` — co-located with the job journal so one
    volume carries the daemon's whole durable state."""
    return envspec.read(ENV_CACHE_DIR) or os.path.join(state_dir,
                                                       "cache")
