"""Bounded retry with exponential backoff around transfer choke points.

PROFILE.md measures this environment's axon tunnel at 1.4-7 MB/s
"depending on the hour"; a genome-scale run multiplies that flaky link
across thousands of chunk transfers. Every h2d/d2h/dispatch choke point
(parallel/dispatch.py, ops/device_poa.py, sched/scheduler.py) now runs
through :func:`call`, which

- re-attempts **transient** failures (injected faults, XLA runtime
  errors, OS/connection errors) up to ``RetryPolicy.attempts`` total
  tries with exponential backoff + deterministic jitter,
- propagates everything else (ValueError, programming bugs) on the
  first occurrence — a retry loop must never mask a logic error,
- raises :class:`RetryExhausted` when the budget runs out, which the
  engine catches to route the chunk's windows onto the host-fallback
  consensus path (graceful degradation — see PoaEngine._degrade and the
  streaming pipeline's h2d/compute stages).

The backoff schedule is a pure function of (policy, site, attempt): the
jitter derives from a seeded hash, not the wall clock, so schedules are
reproducible (tested in tests/test_resilience.py) and two processes
retrying the same site do not thundering-herd in phase.

Every retried attempt increments ``res_retry_total`` /
``res_retry_site_*`` and emits a ``retry`` trace span
(obs/metrics.py::record_retry); exhaustion increments
``res_retry_exhausted``. docs/RESILIENCE.md documents the knobs.
"""

from __future__ import annotations

import hashlib
import os
from racon_tpu.utils import envspec
import time
from typing import Callable, Optional, Tuple

ENV_RETRY = "RACON_TPU_RETRY"


class RetryExhausted(RuntimeError):
    """A retry-wrapped call site failed ``attempts`` times in a row.

    ``__cause__`` chains the last underlying error; ``site`` names the
    choke point. The consensus engine treats this as the signal to
    degrade the affected chunk to the host path rather than abort the
    run.
    """

    def __init__(self, site: str, attempts: int, last: BaseException):
        super().__init__(
            f"[racon_tpu::resilience] {site} failed after {attempts} "
            f"attempt(s): {last!r}")
        self.site = site
        self.attempts = attempts


def _transient_classes() -> Tuple[type, ...]:
    """Exception classes worth retrying. XlaRuntimeError covers device /
    runtime / transfer failures surfacing through jax; OSError covers
    the tunnel's socket layer; InjectedFault is the test harness."""
    from racon_tpu.resilience.faults import InjectedFault
    classes = [InjectedFault, ConnectionError, TimeoutError, OSError]
    try:  # jaxlib is present wherever the device paths run
        from jax.errors import JaxRuntimeError
        classes.append(JaxRuntimeError)
    except Exception:
        try:
            from jaxlib.xla_extension import XlaRuntimeError
            classes.append(XlaRuntimeError)
        except Exception:
            pass
    return tuple(classes)


_TRANSIENT: Optional[Tuple[type, ...]] = None


def is_transient(exc: BaseException) -> bool:
    global _TRANSIENT
    if _TRANSIENT is None:
        _TRANSIENT = _transient_classes()
    return isinstance(exc, _TRANSIENT)


class RetryPolicy:
    """Bounded exponential backoff with deterministic jitter.

    ``attempts`` is the TOTAL try budget (attempts=1 means no retries).
    The delay before retry ``k`` (k = 1 for the first retry) is::

        min(base * multiplier**(k-1), max_delay) * (1 + jitter * u)

    where ``u`` in [-1, 1) derives from sha256(seed, site, k) — pure,
    so schedules are reproducible and testable.
    """

    __slots__ = ("attempts", "base", "multiplier", "max_delay", "jitter",
                 "seed")

    def __init__(self, attempts: int = 4, base: float = 0.05,
                 multiplier: float = 2.0, max_delay: float = 2.0,
                 jitter: float = 0.1, seed: int = 0):
        if attempts < 1:
            raise ValueError(
                f"[racon_tpu::resilience] invalid attempts {attempts}")
        self.attempts = int(attempts)
        self.base = float(base)
        self.multiplier = float(multiplier)
        self.max_delay = float(max_delay)
        self.jitter = float(jitter)
        self.seed = int(seed)

    def retryable(self, exc: BaseException) -> bool:
        return is_transient(exc)

    def delay(self, attempt: int, site: str = "") -> float:
        """Backoff before retry ``attempt`` (1-based), in seconds."""
        d = min(self.base * self.multiplier ** (attempt - 1),
                self.max_delay)
        if self.jitter:
            h = hashlib.sha256(
                f"{self.seed}:{site}:{attempt}".encode()).digest()
            u = int.from_bytes(h[:8], "big") / 2 ** 63 - 1.0  # [-1, 1)
            d *= 1.0 + self.jitter * u
        return max(d, 0.0)

    def schedule(self, site: str = "") -> Tuple[float, ...]:
        """The full deterministic delay sequence (attempts-1 entries)."""
        return tuple(self.delay(k, site)
                     for k in range(1, self.attempts))


_DEFAULT: Optional[RetryPolicy] = None


def default_policy() -> RetryPolicy:
    """Process default, configurable via ``RACON_TPU_RETRY`` as a comma
    list of key=value pairs (attempts/base/multiplier/max_delay/jitter/
    seed), e.g. ``RACON_TPU_RETRY=attempts=6,base=0.2``. ``attempts=1``
    disables retrying while keeping the degradation path."""
    global _DEFAULT
    if _DEFAULT is None:
        kw = {}
        spec = envspec.read(ENV_RETRY)
        for part in filter(None, (p.strip() for p in spec.split(","))):
            try:
                key, val = part.split("=", 1)
                kw[key] = int(val) if key in ("attempts", "seed") \
                    else float(val)
            except ValueError as exc:
                raise ValueError(
                    f"[racon_tpu::resilience] invalid {ENV_RETRY} "
                    f"clause {part!r}") from exc
        _DEFAULT = RetryPolicy(**kw)
    return _DEFAULT


def configure(policy: Optional[RetryPolicy]) -> None:
    """Install (or with None, drop back to env-derived) the process
    default policy — test hook."""
    global _DEFAULT
    _DEFAULT = policy


def call(site: str, fn: Callable, *args,
         policy: Optional[RetryPolicy] = None,
         deadline_s: Optional[float] = None, **kwargs):
    """Run ``fn(*args, **kwargs)`` under the retry policy, with the
    fault injector's hook for ``site`` armed before every try.

    The injection point sits INSIDE the retried body, so a fault spec
    like ``h2d/chunk:0,1`` exercises the real recovery path: try 1 and
    2 raise, try 3 (call index 2 at that site) succeeds.

    Every attempt additionally runs under the fail-slow watchdog
    (resilience/watchdog.py): ``deadline_s=None`` resolves the site's
    geometry-free class default, callers with chunk geometry in hand
    pass a derived deadline, and <= 0 disables the guard. A breach
    raises DispatchTimeout — a TimeoutError, so it is transient and
    lands in this very retry loop; crossing the terminal breach budget
    raises WatchdogTerminal, which is NOT transient and propagates.
    The fault hook sits inside the guarded body so an injected ``hang``
    is bounded by the same deadline as an organic one.
    """
    from racon_tpu.obs.metrics import (record_retry,
                                       record_retry_exhausted)
    from racon_tpu.resilience.faults import maybe_fault
    from racon_tpu.resilience.watchdog import guard, site_deadline

    if deadline_s is None:
        deadline_s = site_deadline(site)

    def _attempt():
        maybe_fault(site)
        return fn(*args, **kwargs)

    pol = policy if policy is not None else default_policy()
    attempt = 0
    while True:
        try:
            if deadline_s and deadline_s > 0:
                return guard(site, deadline_s, _attempt)
            return _attempt()
        except BaseException as exc:  # noqa: BLE001 — filtered below
            if not pol.retryable(exc):
                raise
            attempt += 1
            if attempt >= pol.attempts:
                record_retry_exhausted(site, attempt)
                raise RetryExhausted(site, attempt, exc) from exc
            d = pol.delay(attempt, site)
            record_retry(site, attempt, d, type(exc).__name__,
                         getattr(exc, "injected", False))
            if d > 0:
                time.sleep(d)
