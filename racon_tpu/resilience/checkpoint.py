"""Contig-granular checkpoint/resume for preemption-safe polishing.

A polishing run's unit of durable progress is the **contig**: the
polisher retires targets in input order (serial loop and SliceTracker
pipeline alike), so "contigs 0..k committed" fully describes a partial
run. The store keeps three files in ``--checkpoint-dir``:

``meta.json``
    ``{"schema": 1, "fingerprint": "<hex>"}`` — written atomically
    (utils/atomicio) when the store is created. The fingerprint hashes
    every output-affecting CLI setting plus the sha256 of each input
    file, so ``--resume`` refuses to splice contigs from a different
    run configuration into this one.

``contigs.fasta``
    The shard: each committed contig's exact emitted bytes
    (``>name\\ndata\\n``) appended and fsync'd. Re-emission on resume
    slices this file, so resumed stdout is byte-identical by
    construction, not by re-serialization.

``manifest.jsonl``
    A begin header ``{"ev": "begin", "schema": 1, "fingerprint": ...}``
    then one record per committed target:
    ``{"ev": "contig", "tid": N, "name": ..., "offset": O, "length": L}``
    or ``{"ev": "contig", "tid": N, "emitted": false}`` for targets the
    run dropped (--drop-unpolished semantics must survive resume too).

**Segmented manifests (v2).** An ava run (docs/AVA.md) commits
millions of read-sized targets; one fsync'd manifest record per target
is exactly the cost that cannot survive that scale. A store created
with ``segment_targets > 0`` writes a v2 manifest: the header gains
``"manifest": 2, "seg_targets": N`` and commits amortize into
run-length **segment** records —
``{"ev": "seg", "start": A, "end": B, "offset": O, "lengths": [...]}``
covering targets ``[A, B)`` whose blobs sit contiguously at shard
offset ``O`` (a zero length marks a dropped target; emitted blobs are
never shorter than 3 bytes, so zero is unambiguous). Commits buffer:
each shard write is flushed (``read_emitted`` still slices live bytes)
but the fsync-pair — shard fsync, then one manifest append — happens
once per **seal** (buffer full, a target-id discontinuity, or close).
Every ``RACON_TPU_AVA_COMPACT`` seals the manifest is compacted:
adjacent contiguous segments merge and the file is atomically
rewritten, so manifest size is O(segments), not O(targets). The torn
recovery contract is unchanged — the longest valid manifest prefix
wins, a crash forfeits at most the one unsealed segment (recomputed on
resume), and v2 code resumes v1 stores as before (``resume`` takes the
mode from the manifest header, not from the caller).

Crash consistency is ordering, not locking: the shard append is fsync'd
**before** its manifest record is appended (also fsync'd), so a
manifest record always points at durable shard bytes. The first append
after creating the store also fsyncs the *directory* — file fsync
alone does not make a fresh file's directory entry durable, so without
it a power loss could erase the whole store, committed contigs
included. On resume the store takes the longest valid manifest prefix
(a torn tail line — a partially-written final record — is dropped and
the manifest rewritten atomically), then truncates the shard to the
last referenced byte — orphaned shard bytes from a crash between the
two appends are discarded and that contig recomputes.

Commits pass through the ``ckpt/commit`` fault site (before the shard
append) and the ``ckpt/manifest`` site (between the shard and manifest
appends — the mid-commit eviction window; a ``torn`` action there
writes half the manifest record and hard-exits), so the kill-mid-commit
and torn-manifest scenarios (scripts/resilience_smoke.py,
scripts/preemption_smoke.py) are reproducible.

Shard fingerprints: the distributed layer (racon_tpu/distributed/)
opens one store per work-ledger shard under
``shard_fingerprint = sha256(run_fingerprint + shard id)``, so a
stolen shard resumes from its victim's committed prefix but a store
can never be spliced into the wrong shard or run.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, IO, Iterable, Optional

from racon_tpu.utils import envspec
from racon_tpu.utils.atomicio import (append_fsync, atomic_write_text,
                                      fsync_dir, load_jsonl_prefix)

SCHEMA = 1
MANIFEST_V2 = 2
META_NAME = "meta.json"
SHARD_NAME = "contigs.fasta"
MANIFEST_NAME = "manifest.jsonl"

ENV_AVA_COMPACT = "RACON_TPU_AVA_COMPACT"
DEFAULT_COMPACT_EVERY = 64


def compact_every() -> int:
    """Sealed segments between v2 manifest compaction rewrites
    (``0`` disables compaction; malformed values disable it too —
    compaction is an optimization, never a correctness lever)."""
    raw = envspec.read(ENV_AVA_COMPACT).strip()
    if not raw:
        return DEFAULT_COMPACT_EVERY
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


class CheckpointError(ValueError):
    """Unusable checkpoint directory: fingerprint mismatch, missing or
    corrupt metadata. Deliberately a hard error — silently recomputing
    would mask operator mistakes (wrong dir, changed inputs)."""


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def run_fingerprint(config: Dict, paths: Iterable[str]) -> str:
    """Hash of the output-affecting run identity.

    ``config`` holds every CLI setting that changes emitted bytes
    (scores, window length, rounds, quality/trimming flags...);
    ``paths`` are the input files, digested by content so a re-sorted
    or edited FASTQ invalidates old checkpoints even under the same
    filename.
    """
    ident = {
        "schema": SCHEMA,
        "config": config,
        "inputs": [{"path": os.path.basename(p),
                    "sha256": file_digest(p)} for p in paths],
    }
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def shard_fingerprint(run_fp: str, shard) -> str:
    """Fingerprint of one work-ledger shard: the run identity plus the
    shard key, so per-shard stores are mutually unspliceable. Base
    shards key by partition index (int); dynamically split child
    shards key by their lineage name suffix (str, e.g. "1s1_1"), so a
    parent store can never be adopted as its child's even though their
    target ranges are adjacent."""
    key = int(shard) if not isinstance(shard, str) else shard
    return hashlib.sha256(f"{run_fp}:shard:{key}"
                          .encode()).hexdigest()


class CheckpointStore:
    """Append-only contig store bound to one run fingerprint.

    Use :meth:`create` for a fresh run (``--checkpoint-dir``) and
    :meth:`resume` to continue one (``--resume``). ``committed`` maps
    target index → manifest record for everything durably stored.
    """

    def __init__(self, directory: str, fingerprint: str):
        self.directory = directory
        self.fingerprint = fingerprint
        self.committed: Dict[int, Dict] = {}
        self._shard: Optional[IO[bytes]] = None
        self._manifest: Optional[IO[bytes]] = None
        # The first commit after open fsyncs the directory so the
        # shard/manifest *entries* are durable, not just their bytes.
        self._dir_synced = False
        #: Targets per v2 manifest segment; 0 = v1 per-target records.
        self.segment_targets = 0
        # Open-segment state (v2): buffered (tid, blob_len) pairs —
        # contiguous by construction (a discontinuity seals first) —
        # the shard offset where the segment starts, and the shard end
        # including flushed-but-unsealed bytes (the file handle's
        # position is not consulted after open).
        self._seg: list = []
        self._seg_offset = 0
        self._shard_pos = 0
        # Sealed segment records since the last compaction rewrite.
        self._seg_log: list = []
        self._sealed_since_compact = 0
        self._compact_every = 0

    # -------------------------------------------------- construction
    @property
    def meta_path(self) -> str:
        return os.path.join(self.directory, META_NAME)

    @property
    def shard_path(self) -> str:
        return os.path.join(self.directory, SHARD_NAME)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    @classmethod
    def create(cls, directory: str, fingerprint: str, *,
               segment_targets: int = 0) -> "CheckpointStore":
        """Start a fresh store, replacing any previous contents.
        ``segment_targets > 0`` selects the v2 segmented manifest
        (``ava.seg_targets_for`` picks it for fragment-correction
        runs); the mode is recorded in the manifest header, so resume
        never needs to be told."""
        os.makedirs(directory, exist_ok=True)
        store = cls(directory, fingerprint)
        store.segment_targets = max(0, int(segment_targets))
        for path in (store.shard_path, store.manifest_path):
            if os.path.exists(path):
                os.remove(path)
        atomic_write_text(store.meta_path, json.dumps(
            {"schema": SCHEMA, "fingerprint": fingerprint},
            sort_keys=True) + "\n")
        store._shard = open(store.shard_path, "ab")
        store._manifest = open(store.manifest_path, "ab")
        header = {"ev": "begin", "schema": SCHEMA,
                  "fingerprint": fingerprint}
        if store.segment_targets:
            header["manifest"] = MANIFEST_V2
            header["seg_targets"] = store.segment_targets
            store._compact_every = compact_every()
        append_fsync(store._manifest, (json.dumps(
            header, sort_keys=True) + "\n").encode(),
            sync_dir=directory)
        return store

    @classmethod
    def resume(cls, directory: str,
               fingerprint: str) -> "CheckpointStore":
        """Open an existing store, refusing on any identity mismatch."""
        store = cls(directory, fingerprint)
        try:
            with open(store.meta_path, "r", encoding="utf-8") as fh:
                meta = json.load(fh)
        except (OSError, ValueError) as exc:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume from "
                f"{directory!r}: unreadable {META_NAME} ({exc})") from exc
        if meta.get("schema") != SCHEMA:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] {directory!r} has schema "
                f"{meta.get('schema')!r}, this build writes {SCHEMA}")
        if meta.get("fingerprint") != fingerprint:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] refusing to resume: "
                f"checkpoint fingerprint {meta.get('fingerprint')!r} "
                f"does not match this run ({fingerprint!r}) — inputs "
                "or output-affecting options changed")
        store._recover()
        return store

    def _recover(self) -> None:
        """Longest-valid-prefix manifest recovery + shard truncation.

        Tolerates a final partially-written JSONL line (a torn append
        from a mid-commit crash) by truncating to the last valid
        record instead of raising — the shared
        ``atomicio.load_jsonl_prefix`` discipline."""
        def _check(rec):
            if rec.get("ev") == "contig":
                if "offset" in rec:
                    _ = (int(rec["tid"]), int(rec["offset"]),
                         int(rec["length"]), rec["name"])
                else:
                    _ = (int(rec["tid"]), rec["emitted"])
            elif rec.get("ev") == "seg":
                start, end = int(rec["start"]), int(rec["end"])
                lengths = rec["lengths"]
                if (not isinstance(lengths, list)
                        or len(lengths) != end - start
                        or end <= start):
                    raise ValueError("malformed seg record")
                _ = (int(rec["offset"]), [int(x) for x in lengths])

        try:
            records, clean = load_jsonl_prefix(self.manifest_path,
                                               validate=_check)
        except OSError as exc:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume: unreadable "
                f"{MANIFEST_NAME} ({exc})") from exc
        torn = not clean
        if not records or records[0].get("ev") != "begin":
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume: "
                f"{MANIFEST_NAME} missing begin header")
        if records[0].get("fingerprint") != self.fingerprint:
            raise CheckpointError(
                "[racon_tpu::checkpoint] refusing to resume: manifest "
                "header fingerprint does not match this run")

        if records[0].get("manifest") == MANIFEST_V2:
            # The store's mode travels in its header, not in caller
            # arguments — resume paths stay signature-compatible.
            self.segment_targets = max(
                1, int(records[0].get("seg_targets", 1)))
            self._compact_every = compact_every()

        shard_size = os.path.getsize(self.shard_path) \
            if os.path.exists(self.shard_path) else 0
        shard_end = 0
        valid = [records[0]]
        for rec in records[1:]:
            ev = rec.get("ev")
            if ev == "contig":
                if "offset" in rec:
                    end = int(rec["offset"]) + int(rec["length"])
                    if end > shard_size:
                        # Manifest record without its shard bytes: only
                        # possible with external tampering (the write
                        # order forbids it) — stop trusting from here
                        # on.
                        break
                    shard_end = max(shard_end, end)
            elif ev == "seg":
                end = int(rec["offset"]) + sum(
                    int(x) for x in rec["lengths"])
                if end > shard_size:
                    break
                shard_end = max(shard_end, end)
            else:
                continue
            valid.append(rec)

        if torn or len(valid) != len(records):
            data = b"".join(json.dumps(r, sort_keys=True).encode()
                            + b"\n" for r in valid)
            from racon_tpu.utils.atomicio import atomic_write_bytes
            atomic_write_bytes(self.manifest_path, data)
        if shard_size > shard_end:
            # Orphaned tail from a crash between shard append and
            # manifest append (v1) or an unsealed segment's flushed
            # blobs (v2): discard, those targets recompute.
            with open(self.shard_path, "r+b") as fh:
                fh.truncate(shard_end)
                fh.flush()
                os.fsync(fh.fileno())
            fsync_dir(self.directory)

        for rec in valid[1:]:
            if rec.get("ev") == "seg":
                # Expand the run-length segment into the same
                # per-target records a v1 manifest would have held —
                # nothing downstream (read_emitted, the CAS replay,
                # the merge) knows which manifest flavor fed it.
                off = int(rec["offset"])
                for i, ln in enumerate(rec["lengths"]):
                    tid = int(rec["start"]) + i
                    ln = int(ln)
                    if ln == 0:
                        self.committed[tid] = {
                            "ev": "contig", "tid": tid,
                            "emitted": False}
                    else:
                        self.committed[tid] = {
                            "ev": "contig", "tid": tid,
                            "offset": off, "length": ln}
                        off += ln
                self._seg_log.append(rec)
            else:
                self.committed[int(rec["tid"])] = rec

        from racon_tpu.obs.metrics import record_ckpt
        record_ckpt("resume", len(self.committed), shard_end)

        self._shard = open(self.shard_path, "ab")
        self._manifest = open(self.manifest_path, "ab")
        self._shard_pos = shard_end
        self._seg_offset = shard_end

    # ---------------------------------------------------- operations
    def _append_manifest(self, rec: Dict) -> None:
        """The committing write. ``ckpt/manifest`` is the mid-commit
        eviction window (after the shard append, before this one); a
        ``torn`` fault there makes half the record durable and
        hard-exits — exactly the partially-written final line
        :func:`_recover` must drop."""
        from racon_tpu.resilience.faults import hard_exit, maybe_torn
        data = (json.dumps(rec, sort_keys=True) + "\n").encode()
        sync = None if self._dir_synced else self.directory
        if maybe_torn("ckpt/manifest"):
            append_fsync(self._manifest, data[:max(1, len(data) // 2)],
                         sync_dir=sync)
            hard_exit(137)
        append_fsync(self._manifest, data, sync_dir=sync)
        self._dir_synced = True

    def _buffer_commit(self, tid: int, off: int,
                       blob_len: int) -> None:
        """Add one committed target to the open v2 segment, sealing
        first on a target-id discontinuity (segments are run-length
        encodings — they must stay contiguous) and after when the
        buffer reaches the segment size. ``off`` is where the target's
        blob landed in the shard: a segment's offset is its FIRST
        blob's offset, anchored here rather than at seal time because
        a discontinuity seal runs after the new blob was already
        written past the sealed segment's end."""
        tid = int(tid)
        if self._seg and tid != self._seg[-1][0] + 1:
            self._seal_segment()
        if not self._seg:
            self._seg_offset = int(off)
        self._seg.append((tid, blob_len))
        if len(self._seg) >= self.segment_targets:
            self._seal_segment()

    def _seal_segment(self) -> None:
        """Make the open segment durable: one shard fsync covering
        every buffered blob, then one manifest append — the same
        shard-before-manifest ordering as a v1 commit, amortized over
        ``segment_targets`` targets. ``ckpt/manifest`` faults fire
        here, so the torn-manifest drill lands exactly on a segment
        boundary."""
        if not self._seg:
            return
        from racon_tpu.obs.metrics import record_ckpt
        self._shard.flush()
        os.fsync(self._shard.fileno())
        lengths = [ln for _, ln in self._seg]
        rec = {"ev": "seg", "start": self._seg[0][0],
               "end": self._seg[-1][0] + 1,
               "offset": self._seg_offset, "lengths": lengths}
        self._append_manifest(rec)
        self._seg_log.append(rec)
        self._seg = []
        record_ckpt("seal", rec["start"], sum(lengths))
        self._sealed_since_compact += 1
        if (self._compact_every
                and self._sealed_since_compact >= self._compact_every):
            self._compact()

    def _compact(self) -> None:
        """Rewrite the v2 manifest with adjacent contiguous segments
        merged — amortized O(segments) manifest size no matter how
        long the run. The rewrite is atomic (write-temp + rename), so
        a crash mid-compaction leaves the previous manifest intact;
        byte-identity of recovery before and after is the compaction
        test's contract."""
        merged: list = []
        for rec in self._seg_log:
            prev = merged[-1] if merged else None
            if (prev is not None
                    and int(prev["end"]) == int(rec["start"])
                    and int(prev["offset"])
                    + sum(int(x) for x in prev["lengths"])
                    == int(rec["offset"])):
                prev["lengths"] = list(prev["lengths"]) \
                    + list(rec["lengths"])
                prev["end"] = rec["end"]
            else:
                merged.append(dict(rec))
        header = {"ev": "begin", "schema": SCHEMA,
                  "fingerprint": self.fingerprint,
                  "manifest": MANIFEST_V2,
                  "seg_targets": self.segment_targets}
        data = b"".join(json.dumps(r, sort_keys=True).encode() + b"\n"
                        for r in [header] + merged)
        from racon_tpu.obs.metrics import record_ckpt
        from racon_tpu.utils.atomicio import atomic_write_bytes
        self._manifest.close()
        atomic_write_bytes(self.manifest_path, data)
        self._manifest = open(self.manifest_path, "ab")
        self._seg_log = merged
        self._sealed_since_compact = 0
        record_ckpt("compaction", 0, len(data))

    def commit(self, tid: int, name: bytes, data: bytes) -> None:
        """Durably store target ``tid``'s emitted FASTA record.

        Write order is the crash-consistency contract: shard bytes
        reach disk before the manifest record that references them, and
        the first commit also fsyncs the directory so the files'
        entries survive power loss. A v2 store flushes the shard write
        immediately (so ``read_emitted`` serves live bytes) but defers
        the fsync-pair to the segment seal — the target is durable only
        once its segment is."""
        if self._shard is None or self._manifest is None:
            raise CheckpointError(
                "[racon_tpu::checkpoint] commit on a closed store")
        from racon_tpu.obs.metrics import record_ckpt
        from racon_tpu.resilience.faults import maybe_fault
        maybe_fault("ckpt/commit")
        blob = b">" + name + b"\n" + data + b"\n"
        if self.segment_targets:
            off = self._shard_pos
            self._shard.write(blob)
            self._shard.flush()
            self._shard_pos = off + len(blob)
            rec = {"ev": "contig", "tid": int(tid),
                   "offset": off, "length": len(blob)}
            self.committed[int(tid)] = rec
            record_ckpt("commit", tid, len(blob))
            self._buffer_commit(tid, off, len(blob))
            return
        off = append_fsync(self._shard, blob,
                           sync_dir=None if self._dir_synced
                           else self.directory)
        self._shard_pos = off + len(blob)
        rec = {"ev": "contig", "tid": int(tid),
               "name": name.decode("utf-8", "replace"),
               "offset": off, "length": len(blob)}
        self._append_manifest(rec)
        self.committed[int(tid)] = rec
        record_ckpt("commit", tid, len(blob))

    def commit_dropped(self, tid: int) -> None:
        """Record that ``tid`` completed but emits nothing (a dropped
        unpolished target) — resume must skip its compute too."""
        if self._manifest is None:
            raise CheckpointError(
                "[racon_tpu::checkpoint] commit on a closed store")
        from racon_tpu.obs.metrics import record_ckpt
        from racon_tpu.resilience.faults import maybe_fault
        maybe_fault("ckpt/commit")
        rec = {"ev": "contig", "tid": int(tid), "emitted": False}
        if self.segment_targets:
            self.committed[int(tid)] = rec
            record_ckpt("commit", tid, 0)
            self._buffer_commit(tid, self._shard_pos, 0)
            return
        self._append_manifest(rec)
        self.committed[int(tid)] = rec
        record_ckpt("commit", tid, 0)

    def read_emitted(self, tid: int) -> Optional[bytes]:
        """The exact bytes originally emitted for ``tid`` (None for a
        dropped target) — sliced from the shard, not re-serialized."""
        rec = self.committed[int(tid)]
        if "offset" not in rec:
            return None
        with open(self.shard_path, "rb") as fh:
            fh.seek(int(rec["offset"]))
            blob = fh.read(int(rec["length"]))
        if len(blob) != int(rec["length"]):
            raise CheckpointError(
                f"[racon_tpu::checkpoint] shard truncated under "
                f"manifest record for target {tid}")
        return blob

    def close(self) -> None:
        if self._seg and self._shard is not None \
                and self._manifest is not None:
            # A v2 store seals its partial tail segment on the way
            # out: the worker closes its store before marking the
            # shard done (distributed/worker._polish_shard), so a done
            # marker always implies a fully sealed manifest.
            self._seal_segment()
        for fh in (self._shard, self._manifest):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        self._shard = self._manifest = None

    def __enter__(self) -> "CheckpointStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
