"""Contig-granular checkpoint/resume for preemption-safe polishing.

A polishing run's unit of durable progress is the **contig**: the
polisher retires targets in input order (serial loop and SliceTracker
pipeline alike), so "contigs 0..k committed" fully describes a partial
run. The store keeps three files in ``--checkpoint-dir``:

``meta.json``
    ``{"schema": 1, "fingerprint": "<hex>"}`` — written atomically
    (utils/atomicio) when the store is created. The fingerprint hashes
    every output-affecting CLI setting plus the sha256 of each input
    file, so ``--resume`` refuses to splice contigs from a different
    run configuration into this one.

``contigs.fasta``
    The shard: each committed contig's exact emitted bytes
    (``>name\\ndata\\n``) appended and fsync'd. Re-emission on resume
    slices this file, so resumed stdout is byte-identical by
    construction, not by re-serialization.

``manifest.jsonl``
    A begin header ``{"ev": "begin", "schema": 1, "fingerprint": ...}``
    then one record per committed target:
    ``{"ev": "contig", "tid": N, "name": ..., "offset": O, "length": L}``
    or ``{"ev": "contig", "tid": N, "emitted": false}`` for targets the
    run dropped (--drop-unpolished semantics must survive resume too).

Crash consistency is ordering, not locking: the shard append is fsync'd
**before** its manifest record is appended (also fsync'd), so a
manifest record always points at durable shard bytes. The first append
after creating the store also fsyncs the *directory* — file fsync
alone does not make a fresh file's directory entry durable, so without
it a power loss could erase the whole store, committed contigs
included. On resume the store takes the longest valid manifest prefix
(a torn tail line — a partially-written final record — is dropped and
the manifest rewritten atomically), then truncates the shard to the
last referenced byte — orphaned shard bytes from a crash between the
two appends are discarded and that contig recomputes.

Commits pass through the ``ckpt/commit`` fault site (before the shard
append) and the ``ckpt/manifest`` site (between the shard and manifest
appends — the mid-commit eviction window; a ``torn`` action there
writes half the manifest record and hard-exits), so the kill-mid-commit
and torn-manifest scenarios (scripts/resilience_smoke.py,
scripts/preemption_smoke.py) are reproducible.

Shard fingerprints: the distributed layer (racon_tpu/distributed/)
opens one store per work-ledger shard under
``shard_fingerprint = sha256(run_fingerprint + shard id)``, so a
stolen shard resumes from its victim's committed prefix but a store
can never be spliced into the wrong shard or run.
"""

from __future__ import annotations

import hashlib
import json
import os
from typing import Dict, IO, Iterable, Optional

from racon_tpu.utils.atomicio import (append_fsync, atomic_write_text,
                                      fsync_dir, load_jsonl_prefix)

SCHEMA = 1
META_NAME = "meta.json"
SHARD_NAME = "contigs.fasta"
MANIFEST_NAME = "manifest.jsonl"


class CheckpointError(ValueError):
    """Unusable checkpoint directory: fingerprint mismatch, missing or
    corrupt metadata. Deliberately a hard error — silently recomputing
    would mask operator mistakes (wrong dir, changed inputs)."""


def file_digest(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as fh:
        for block in iter(lambda: fh.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def run_fingerprint(config: Dict, paths: Iterable[str]) -> str:
    """Hash of the output-affecting run identity.

    ``config`` holds every CLI setting that changes emitted bytes
    (scores, window length, rounds, quality/trimming flags...);
    ``paths`` are the input files, digested by content so a re-sorted
    or edited FASTQ invalidates old checkpoints even under the same
    filename.
    """
    ident = {
        "schema": SCHEMA,
        "config": config,
        "inputs": [{"path": os.path.basename(p),
                    "sha256": file_digest(p)} for p in paths],
    }
    blob = json.dumps(ident, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def shard_fingerprint(run_fp: str, shard) -> str:
    """Fingerprint of one work-ledger shard: the run identity plus the
    shard key, so per-shard stores are mutually unspliceable. Base
    shards key by partition index (int); dynamically split child
    shards key by their lineage name suffix (str, e.g. "1s1_1"), so a
    parent store can never be adopted as its child's even though their
    target ranges are adjacent."""
    key = int(shard) if not isinstance(shard, str) else shard
    return hashlib.sha256(f"{run_fp}:shard:{key}"
                          .encode()).hexdigest()


class CheckpointStore:
    """Append-only contig store bound to one run fingerprint.

    Use :meth:`create` for a fresh run (``--checkpoint-dir``) and
    :meth:`resume` to continue one (``--resume``). ``committed`` maps
    target index → manifest record for everything durably stored.
    """

    def __init__(self, directory: str, fingerprint: str):
        self.directory = directory
        self.fingerprint = fingerprint
        self.committed: Dict[int, Dict] = {}
        self._shard: Optional[IO[bytes]] = None
        self._manifest: Optional[IO[bytes]] = None
        # The first commit after open fsyncs the directory so the
        # shard/manifest *entries* are durable, not just their bytes.
        self._dir_synced = False

    # -------------------------------------------------- construction
    @property
    def meta_path(self) -> str:
        return os.path.join(self.directory, META_NAME)

    @property
    def shard_path(self) -> str:
        return os.path.join(self.directory, SHARD_NAME)

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST_NAME)

    @classmethod
    def create(cls, directory: str,
               fingerprint: str) -> "CheckpointStore":
        """Start a fresh store, replacing any previous contents."""
        os.makedirs(directory, exist_ok=True)
        store = cls(directory, fingerprint)
        for path in (store.shard_path, store.manifest_path):
            if os.path.exists(path):
                os.remove(path)
        atomic_write_text(store.meta_path, json.dumps(
            {"schema": SCHEMA, "fingerprint": fingerprint},
            sort_keys=True) + "\n")
        store._shard = open(store.shard_path, "ab")
        store._manifest = open(store.manifest_path, "ab")
        header = {"ev": "begin", "schema": SCHEMA,
                  "fingerprint": fingerprint}
        append_fsync(store._manifest, (json.dumps(
            header, sort_keys=True) + "\n").encode(),
            sync_dir=directory)
        return store

    @classmethod
    def resume(cls, directory: str,
               fingerprint: str) -> "CheckpointStore":
        """Open an existing store, refusing on any identity mismatch."""
        store = cls(directory, fingerprint)
        try:
            with open(store.meta_path, "r", encoding="utf-8") as fh:
                meta = json.load(fh)
        except (OSError, ValueError) as exc:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume from "
                f"{directory!r}: unreadable {META_NAME} ({exc})") from exc
        if meta.get("schema") != SCHEMA:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] {directory!r} has schema "
                f"{meta.get('schema')!r}, this build writes {SCHEMA}")
        if meta.get("fingerprint") != fingerprint:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] refusing to resume: "
                f"checkpoint fingerprint {meta.get('fingerprint')!r} "
                f"does not match this run ({fingerprint!r}) — inputs "
                "or output-affecting options changed")
        store._recover()
        return store

    def _recover(self) -> None:
        """Longest-valid-prefix manifest recovery + shard truncation.

        Tolerates a final partially-written JSONL line (a torn append
        from a mid-commit crash) by truncating to the last valid
        record instead of raising — the shared
        ``atomicio.load_jsonl_prefix`` discipline."""
        def _check(rec):
            if rec.get("ev") == "contig":
                if "offset" in rec:
                    _ = (int(rec["tid"]), int(rec["offset"]),
                         int(rec["length"]), rec["name"])
                else:
                    _ = (int(rec["tid"]), rec["emitted"])

        try:
            records, clean = load_jsonl_prefix(self.manifest_path,
                                               validate=_check)
        except OSError as exc:
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume: unreadable "
                f"{MANIFEST_NAME} ({exc})") from exc
        torn = not clean
        if not records or records[0].get("ev") != "begin":
            raise CheckpointError(
                f"[racon_tpu::checkpoint] cannot resume: "
                f"{MANIFEST_NAME} missing begin header")
        if records[0].get("fingerprint") != self.fingerprint:
            raise CheckpointError(
                "[racon_tpu::checkpoint] refusing to resume: manifest "
                "header fingerprint does not match this run")

        shard_size = os.path.getsize(self.shard_path) \
            if os.path.exists(self.shard_path) else 0
        shard_end = 0
        valid = [records[0]]
        for rec in records[1:]:
            if rec.get("ev") != "contig":
                continue
            if "offset" in rec:
                end = int(rec["offset"]) + int(rec["length"])
                if end > shard_size:
                    # Manifest record without its shard bytes: only
                    # possible with external tampering (the write order
                    # forbids it) — stop trusting from here on.
                    break
                shard_end = max(shard_end, end)
            valid.append(rec)

        if torn or len(valid) != len(records):
            data = b"".join(json.dumps(r, sort_keys=True).encode()
                            + b"\n" for r in valid)
            from racon_tpu.utils.atomicio import atomic_write_bytes
            atomic_write_bytes(self.manifest_path, data)
        if shard_size > shard_end:
            # Orphaned tail from a crash between shard append and
            # manifest append: discard, that contig recomputes.
            with open(self.shard_path, "r+b") as fh:
                fh.truncate(shard_end)
                fh.flush()
                os.fsync(fh.fileno())
            fsync_dir(self.directory)

        for rec in valid[1:]:
            self.committed[int(rec["tid"])] = rec

        from racon_tpu.obs.metrics import record_ckpt
        record_ckpt("resume", len(self.committed), shard_end)

        self._shard = open(self.shard_path, "ab")
        self._manifest = open(self.manifest_path, "ab")

    # ---------------------------------------------------- operations
    def _append_manifest(self, rec: Dict) -> None:
        """The committing write. ``ckpt/manifest`` is the mid-commit
        eviction window (after the shard append, before this one); a
        ``torn`` fault there makes half the record durable and
        hard-exits — exactly the partially-written final line
        :func:`_recover` must drop."""
        from racon_tpu.resilience.faults import hard_exit, maybe_torn
        data = (json.dumps(rec, sort_keys=True) + "\n").encode()
        sync = None if self._dir_synced else self.directory
        if maybe_torn("ckpt/manifest"):
            append_fsync(self._manifest, data[:max(1, len(data) // 2)],
                         sync_dir=sync)
            hard_exit(137)
        append_fsync(self._manifest, data, sync_dir=sync)
        self._dir_synced = True

    def commit(self, tid: int, name: bytes, data: bytes) -> None:
        """Durably store target ``tid``'s emitted FASTA record.

        Write order is the crash-consistency contract: shard bytes
        reach disk before the manifest record that references them, and
        the first commit also fsyncs the directory so the files'
        entries survive power loss.
        """
        if self._shard is None or self._manifest is None:
            raise CheckpointError(
                "[racon_tpu::checkpoint] commit on a closed store")
        from racon_tpu.obs.metrics import record_ckpt
        from racon_tpu.resilience.faults import maybe_fault
        maybe_fault("ckpt/commit")
        blob = b">" + name + b"\n" + data + b"\n"
        off = append_fsync(self._shard, blob,
                           sync_dir=None if self._dir_synced
                           else self.directory)
        rec = {"ev": "contig", "tid": int(tid),
               "name": name.decode("utf-8", "replace"),
               "offset": off, "length": len(blob)}
        self._append_manifest(rec)
        self.committed[int(tid)] = rec
        record_ckpt("commit", tid, len(blob))

    def commit_dropped(self, tid: int) -> None:
        """Record that ``tid`` completed but emits nothing (a dropped
        unpolished target) — resume must skip its compute too."""
        if self._manifest is None:
            raise CheckpointError(
                "[racon_tpu::checkpoint] commit on a closed store")
        from racon_tpu.obs.metrics import record_ckpt
        from racon_tpu.resilience.faults import maybe_fault
        maybe_fault("ckpt/commit")
        rec = {"ev": "contig", "tid": int(tid), "emitted": False}
        self._append_manifest(rec)
        self.committed[int(tid)] = rec
        record_ckpt("commit", tid, 0)

    def read_emitted(self, tid: int) -> Optional[bytes]:
        """The exact bytes originally emitted for ``tid`` (None for a
        dropped target) — sliced from the shard, not re-serialized."""
        rec = self.committed[int(tid)]
        if "offset" not in rec:
            return None
        with open(self.shard_path, "rb") as fh:
            fh.seek(int(rec["offset"]))
            blob = fh.read(int(rec["length"]))
        if len(blob) != int(rec["length"]):
            raise CheckpointError(
                f"[racon_tpu::checkpoint] shard truncated under "
                f"manifest record for target {tid}")
        return blob

    def close(self) -> None:
        for fh in (self._shard, self._manifest):
            if fh is not None:
                try:
                    fh.close()
                except OSError:
                    pass
        self._shard = self._manifest = None

    def __enter__(self) -> "CheckpointStore":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
