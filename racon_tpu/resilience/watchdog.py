"""Monotonic-clock deadline watchdog for the fail-slow failure class.

The retry ladder (retry.py) only fires when a choke point *raises*; a
wedged dispatch — a tunnel socket that neither delivers nor errors, an
XLA executable that never returns — hangs the process forever, and in a
ledger fleet the wedged worker keeps renewing nothing while its lease
stays unstealable until the term runs out. :func:`guard` converts that
silence into an exception within a bounded deadline:

- the guarded body runs in a reusable daemon worker thread; the caller
  waits at most ``deadline_s`` on the monotonic clock;
- a breach raises :class:`DispatchTimeout` (a ``TimeoutError``, so
  retry.call's transient filter accepts it unchanged) into the existing
  retry → redo → degrade ladder: a slow dispatch is retried, then
  host-degraded, never waited on forever;
- when ``RACON_TPU_WATCHDOG_TERMINAL=N`` (default 0 = never) is set and
  the process-wide breach count reaches N, the breach raises
  :class:`WatchdogTerminal` instead — non-transient, so it propagates
  to the worker loop, which releases its ledger lease (an explicit
  ``release`` event — thieves do not wait out the lease term), flushes
  a final obs snapshot, and exits :data:`EXIT_SELF_EVICT`.

Per-site deadlines derive from chunk geometry in ops/budget.py
(``transfer_deadline_s`` / ``dispatch_deadline_s``, env-tunable via
``RACON_TPU_DEADLINE_*``); :func:`site_deadline` supplies the
geometry-free class default for sites that pass none. A deadline of 0
disables the guard (the body runs inline on the caller thread).

The abandoned worker thread keeps running its wedged body (there is no
safe cross-thread kill in CPython); it is daemonic, flagged so it
retires instead of rejoining the free pool, and the process never waits
on it — which is exactly the property the injected ``hang`` fault
action (faults.py) proves on CPU: the sleep outlives the deadline, the
caller does not.
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
import threading
import time
from typing import Callable, Dict, List, Optional

ENV_TERMINAL = "RACON_TPU_WATCHDOG_TERMINAL"

#: Exit code of a worker that self-evicted on a terminal watchdog
#: breach (EX_TEMPFAIL: the shard is fine, this host is not — retry
#: elsewhere). Distinct from 130/143 (signals) and 137 (hard kill).
EXIT_SELF_EVICT = 75


class DispatchTimeout(TimeoutError):
    """A guarded call site exceeded its deadline.

    Subclasses ``TimeoutError`` so retry.py's transient filter treats a
    breach exactly like a tunnel timeout: retried, then degraded.
    """

    def __init__(self, site: str, deadline_s: float, waited_s: float):
        super().__init__(
            f"[racon_tpu::watchdog] {site} exceeded its {deadline_s:.3f}s "
            f"deadline (waited {waited_s:.3f}s); the call keeps running "
            "on an abandoned thread")
        self.site = site
        self.deadline_s = deadline_s
        self.waited_s = waited_s


class WatchdogTerminal(RuntimeError):
    """The process crossed its terminal breach budget — this host is
    considered wedged. Deliberately NOT transient: it must reach the
    worker loop (self-eviction) or the CLI (exit 75), not the retry
    loop."""

    def __init__(self, site: str, breaches: int, limit: int):
        super().__init__(
            f"[racon_tpu::watchdog] terminal: {breaches} deadline "
            f"breach(es) (limit {limit}, last at {site}) — this worker "
            "is wedged and should hand its work back")
        self.site = site
        self.breaches = breaches
        self.limit = limit


def terminal_limit() -> int:
    """Breach count at which a breach becomes terminal; 0 disables."""
    txt = envspec.read(ENV_TERMINAL)
    if not txt:
        return 0
    try:
        v = int(txt)
    except ValueError:
        raise ValueError(
            f"[racon_tpu::watchdog] invalid {ENV_TERMINAL}={txt!r} "
            "(expected an integer breach count, 0 to disable)")
    if v < 0:
        raise ValueError(
            f"[racon_tpu::watchdog] invalid {ENV_TERMINAL}={v} "
            "(must be >= 0)")
    return v


def is_terminal(exc: BaseException) -> bool:
    """True when ``exc`` is (or was caused by, at any chain depth) a
    :class:`WatchdogTerminal` — a pipeline stage wraps it in StageError,
    so the worker loop checks the cause chain, not the type."""
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen:
        if isinstance(cur, WatchdogTerminal):
            return True
        seen.add(id(cur))
        cur = cur.__cause__ or cur.__context__
    return False


# ----------------------------------------------------------- guard pool

class _Job:
    __slots__ = ("fn", "args", "kwargs", "done", "result", "exc",
                 "stack", "deadline_s")

    def __init__(self, fn, args, kwargs, stack, deadline_s):
        self.fn = fn
        self.args = args
        self.kwargs = kwargs
        self.done = threading.Event()
        self.result = None
        self.exc: Optional[BaseException] = None
        self.stack = stack          # caller's tracer span stack (copy)
        self.deadline_s = deadline_s


class _GuardWorker(threading.Thread):
    """One reusable guard thread: jobs arrive via a condition variable,
    results ride on the job object (never on the worker, so a late
    result from an abandoned job cannot be confused with a new one)."""

    def __init__(self):
        super().__init__(name="racon-watchdog", daemon=True)
        self.cv = threading.Condition()
        self.job: Optional[_Job] = None
        self.abandoned = False
        self.start()

    def submit(self, job: _Job) -> None:
        with self.cv:
            self.job = job
            self.cv.notify()

    def run(self) -> None:
        from racon_tpu.obs.trace import get_tracer
        while True:
            with self.cv:
                while self.job is None:
                    self.cv.wait()
                job = self.job
            tracer = get_tracer()
            # Bridge the caller's span stack (a COPY — an abandoned
            # worker finishing late must not corrupt the caller's) so
            # spans emitted inside the guarded body keep their parents.
            tracer.install_stack(job.stack)
            _local.deadline = job.deadline_s
            try:
                job.result = job.fn(*job.args, **job.kwargs)
            except BaseException as exc:  # noqa: BLE001 — re-raised by guard()
                job.exc = exc
            finally:
                _local.deadline = 0.0
                tracer.install_stack([])
            with self.cv:
                self.job = None
                retire = self.abandoned
            job.done.set()
            if retire:
                return              # never rejoin the pool
            with _pool_lock:
                _pool.append(self)


_pool_lock = threading.Lock()
_pool: List[_GuardWorker] = []
_local = threading.local()

_state_lock = threading.Lock()
_breaches: Dict[str, int] = {}
_breach_total = 0
_terminal_total = 0
_last_breach: Optional[Dict[str, object]] = None
_stall_total = 0


def ambient_deadline() -> float:
    """The deadline armed on the CURRENT thread (a guarded body sees its
    own deadline; everything else sees 0). The ``hang`` fault action
    uses this to sleep provably past whatever deadline is watching."""
    return getattr(_local, "deadline", 0.0)


def site_deadline(site: str) -> float:
    """Geometry-free class default for a retry site, by prefix. Sites
    outside the transfer/dispatch families get no deadline (0)."""
    from racon_tpu.ops.budget import (dispatch_deadline_s,
                                      transfer_deadline_s)
    if site.startswith("h2d/"):
        return transfer_deadline_s(0, "h2d")
    if site.startswith("d2h/"):
        return transfer_deadline_s(0, "d2h")
    if site.startswith(("dispatch/", "sched/")):
        # Flag pulls sync on compute, so they share the dispatch budget.
        return dispatch_deadline_s(0)
    return 0.0


def _checkout() -> _GuardWorker:
    with _pool_lock:
        if _pool:
            return _pool.pop()
    return _GuardWorker()


def _record_breach(site: str, deadline_s: float, waited_s: float,
                   terminal: bool) -> int:
    global _breach_total, _terminal_total, _last_breach
    with _state_lock:
        _breach_total += 1
        _breaches[site] = _breaches.get(site, 0) + 1
        if terminal:
            _terminal_total += 1
        _last_breach = {"site": site, "deadline_s": deadline_s,
                        "waited_s": round(waited_s, 3),
                        "unix_time": time.time()}
        total = _breach_total
    from racon_tpu.obs.metrics import record_watchdog_breach
    record_watchdog_breach(site, deadline_s, waited_s, terminal=terminal)
    return total


def guard(site: str, deadline_s: Optional[float], fn: Callable,
          *args, **kwargs):
    """Run ``fn(*args, **kwargs)`` under a monotonic deadline.

    ``deadline_s=None`` resolves :func:`site_deadline`; a resolved
    deadline <= 0 runs the body inline (guard disabled). On a breach the
    worker thread is abandoned (flagged to retire, never reused) and
    :class:`DispatchTimeout` — or :class:`WatchdogTerminal` once the
    process-wide breach count reaches ``RACON_TPU_WATCHDOG_TERMINAL`` —
    is raised on the caller.
    """
    if deadline_s is None:
        deadline_s = site_deadline(site)
    if not deadline_s or deadline_s <= 0:
        return fn(*args, **kwargs)
    from racon_tpu.obs.trace import get_tracer
    job = _Job(fn, args, kwargs, get_tracer().snapshot_stack(),
               float(deadline_s))
    worker = _checkout()
    t0 = time.monotonic()
    worker.submit(job)
    if not job.done.wait(deadline_s):
        waited = time.monotonic() - t0
        completed = False
        with worker.cv:
            if worker.job is job:
                worker.abandoned = True     # retires after the late job
            else:
                completed = True            # finished a hair past deadline
        if not completed:
            limit = terminal_limit()
            # Peek whether THIS breach crosses the limit before
            # recording, so the terminal flag lands on the right record.
            with _state_lock:
                will_be = _breach_total + 1
            terminal = bool(limit) and will_be >= limit
            total = _record_breach(site, deadline_s, waited, terminal)
            if terminal:
                raise WatchdogTerminal(site, total, limit)
            raise DispatchTimeout(site, deadline_s, waited)
        job.done.wait()
    if job.exc is not None:
        raise job.exc
    return job.result


# -------------------------------------------------------------- health

def note_stall(n_stages: int) -> None:
    """Pipeline stall detector callback — folds stall state into
    :func:`health_snapshot`."""
    global _stall_total
    with _state_lock:
        _stall_total += 1


def health_snapshot() -> Dict[str, object]:
    """Liveness view for the ``/healthz`` endpoint: ``status`` is
    ``"ok"`` until a terminal breach or a pipeline stall has been seen
    (the conditions under which an operator should reschedule this
    worker); breach counters ride along for dashboards."""
    with _state_lock:
        status = "ok"
        if _terminal_total:
            status = "terminal"
        elif _stall_total:
            status = "stalled"
        return {
            "status": status,
            "watchdog_breaches": _breach_total,
            "watchdog_terminal": _terminal_total,
            "pipeline_stalls": _stall_total,
            "breaches_by_site": dict(_breaches),
            "last_breach": dict(_last_breach) if _last_breach else None,
        }


def reset() -> None:
    """Clear process-wide breach/stall state (test isolation hook)."""
    global _breach_total, _terminal_total, _last_breach, _stall_total
    with _state_lock:
        _breaches.clear()
        _breach_total = 0
        _terminal_total = 0
        _last_breach = None
        _stall_total = 0
