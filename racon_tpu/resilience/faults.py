"""Deterministic, env-gated fault injection for the transfer paths.

The retry/degradation/abort machinery in this package is only credible
if it can be exercised on CPU in tier-1, where real TPU transfer faults
never happen. ``RACON_TPU_FAULTS=<spec>`` arms :func:`maybe_fault`
hooks that retry.call() places inside every retried attempt, raising
synthetic :class:`InjectedFault` errors (or killing the process) at
chosen per-site call indices.

Spec grammar (clauses joined by ``;``)::

    spec    := clause (';' clause)*
    clause  := site ':' selector ['!' action]
             | 'seed=' int
             | 'skew=' float               # lease-clock skew, seconds
    selector:= index (',' index)*          # explicit call indices
             | 'p=' float                  # per-call probability
    action  := 'raise'                     # default: InjectedFault
             | 'kill'                      # hard exit 137, no cleanup
             | 'term' | 'int'             # signal self (SIGTERM/SIGINT)
             | 'torn'                      # tear the write in progress
             | 'hang' ['=' float]         # sleep past the armed watchdog
                                          #   deadline (or S seconds)
             | 'stall' ['=' float]        # sleep S seconds, then proceed

Examples::

    RACON_TPU_FAULTS='h2d/chunk:0,1,2'        # first 3 chunk uploads fail
    RACON_TPU_FAULTS='d2h/chunk:p=0.05;seed=7'  # 5% of pulls, seeded
    RACON_TPU_FAULTS='ckpt/commit:1!kill'     # die during 2nd commit
    RACON_TPU_FAULTS='dist/contig:1!kill'     # evict worker mid-shard
    RACON_TPU_FAULTS='ckpt/manifest:0!torn'   # half-written manifest line
    RACON_TPU_FAULTS='skew=9999'              # every lease looks expired

Site names match the transfer labels in obs (``h2d/chunk``,
``d2h/chunk``, ``h2d/align``, ``d2h/align``, ``d2h/sp``,
``h2d/repack``, ``sched/flags``) plus ``dispatch/chunk``,
``ckpt/commit``, ``ckpt/manifest`` (between the checkpoint's shard and
manifest appends — the mid-commit eviction window), and the distributed
worker's eviction points ``dist/claim`` / ``dist/shard`` /
``dist/contig`` / ``dist/merge`` and the split-publication window
``dist/split`` (a ``torn`` there leaves a half-written child .range
that every reader must treat as "no split happened";
racon_tpu/distributed/), and the ingest plane's read sites
``io/read`` (one consult per parsed line on the streaming readers,
per *record* on the mmap index-first readers — there are no lines
there) and ``io/inflate`` (once per gzip block/member handed to the
parallel inflate pool; a ``raise``/``torn`` there models a torn or
short compressed read and must surface as the offset-bearing
ParseError contract). Arming any ``io/*`` site disables ingest
*prefetch concurrency* (io/ingest.prefetch_ok) so explicit call
indices stay deterministic. Call indices
are 0-based and advance once per *attempt* at that site (each retry
re-consults the injector), so ``site:0,1`` verifies genuine two-failure
recovery.

Eviction-class extensions (preemption drills, docs/DISTRIBUTED.md):

- ``kill`` routes through :func:`hard_exit` (still ``os._exit``, no
  cleanup) so in-process tests can intercept the death;
- ``torn`` is consumed by write sites that support tearing
  (:func:`maybe_torn`): the site writes a *partial* record, makes it
  durable, and hard-exits — the canonical torn-manifest crash. At a
  site that only calls :func:`maybe_fault` a ``torn`` rule degrades to
  ``raise``;
- ``skew=S`` shifts the distributed ledger's lease clock by S seconds
  (:func:`clock_skew`), so lease expiry — normally a wall-clock wait —
  is provable instantly in tier-1.

Determinism: explicit-index decisions are pure functions of the per-site
call counter; probability decisions hash ``(seed, site, index)`` — the
wall clock and thread interleaving never influence whether a given call
faults, only which thread observes it. Counters are process-wide and
thread-safe. Every fired fault is recorded (``res_fault_*`` metrics and
a ``fault`` trace span) via obs/metrics.py::record_fault.

When the env var is unset the hook is a single None check.
"""

from __future__ import annotations

import hashlib
import os
from racon_tpu.utils import envspec
import signal
import threading
from typing import Dict, List, Optional, Tuple

ENV_FAULTS = "RACON_TPU_FAULTS"

_ACTIONS = ("raise", "kill", "term", "int", "torn", "hang", "stall")

#: Declared fault-site table — the ground truth the fault-site lint
#: rule (racon_tpu/analysis, FLT001/FLT002) checks both ways: every
#: literal passed to maybe_fault/maybe_torn/retry.call must be listed
#: here, and every listed site must be exercised by at least one test
#: or smoke script. Keep alphabetical.
SITES = (
    "cache/load", "cache/store",
    "ckpt/commit", "ckpt/manifest",
    "d2h/align", "d2h/chunk", "d2h/sp",
    "dispatch/chunk", "dispatch/walk",
    "dist/claim", "dist/contig", "dist/merge", "dist/merge_write",
    "dist/shard", "dist/split",
    "gate/adopt", "gate/route",
    "h2d/align", "h2d/chunk", "h2d/repack",
    "io/inflate", "io/read",
    "obs/flight", "obs/snapshot",
    "sched/flags",
    "serve/commit", "serve/dispatch", "serve/submit",
)

#: Dynamic site families: one entry per prefix; the concrete site is
#: prefix + a runtime name (pipeline stage names, pipe/<stage>).
SITE_PREFIXES = ("pipe/",)

#: Fallback sleep for ``stall`` with no explicit duration, seconds.
ENV_STALL_S = "RACON_TPU_FAULT_STALL_S"
_STALL_DEFAULT_S = 1.0
#: Fallback sleep for ``hang`` when no watchdog deadline is armed on
#: the current thread and no explicit duration was given, seconds.
ENV_HANG_S = "RACON_TPU_FAULT_HANG_S"
_HANG_DEFAULT_S = 30.0


def hard_exit(code: int) -> None:
    """Simulated hard crash: no atexit, no flushes — exactly the
    scenario the checkpoint store's fsync ordering protects. A seam so
    in-process tests can intercept the death; production faults really
    do ``os._exit``."""
    os._exit(code)


class InjectedFault(RuntimeError):
    """A synthetic transfer/dispatch failure raised by the injector.

    ``injected`` marks the error so retry accounting can distinguish
    synthetic from organic failures.
    """

    injected = True

    def __init__(self, site: str, index: int):
        super().__init__(
            f"[racon_tpu::faults] injected fault at {site} call {index}")
        self.site = site
        self.index = index


class FaultSpecError(ValueError):
    pass


class _SiteRule:
    __slots__ = ("indices", "prob", "action", "duration")

    def __init__(self, indices: Optional[frozenset], prob: float,
                 action: str, duration: Optional[float] = None):
        self.indices = indices   # frozenset of call indices, or None
        self.prob = prob         # used when indices is None
        self.action = action
        self.duration = duration  # hang=S / stall=S sleep, seconds


def _parse(spec: str) -> Tuple[Dict[str, _SiteRule], int, float]:
    rules: Dict[str, _SiteRule] = {}
    seed = 0
    skew = 0.0
    for clause in filter(None, (c.strip() for c in spec.split(";"))):
        if clause.startswith("seed="):
            try:
                seed = int(clause[5:])
            except ValueError:
                raise FaultSpecError(
                    f"[racon_tpu::faults] bad seed clause {clause!r}")
            continue
        if clause.startswith("skew="):
            try:
                skew = float(clause[5:])
            except ValueError:
                raise FaultSpecError(
                    f"[racon_tpu::faults] bad skew clause {clause!r}")
            continue
        if ":" not in clause:
            raise FaultSpecError(
                f"[racon_tpu::faults] clause {clause!r} is not "
                "'site:selector' or 'seed=N'")
        site, sel = clause.split(":", 1)
        action = "raise"
        duration: Optional[float] = None
        if "!" in sel:
            sel, action = sel.split("!", 1)
            if "=" in action:
                # hang=S / stall=S: explicit sleep duration, seconds.
                action, dur_txt = action.split("=", 1)
                if action not in ("hang", "stall"):
                    raise FaultSpecError(
                        f"[racon_tpu::faults] action {action!r} takes "
                        f"no '=' argument in clause {clause!r}")
                try:
                    duration = float(dur_txt)
                    if duration < 0:
                        raise ValueError
                except ValueError:
                    raise FaultSpecError(
                        f"[racon_tpu::faults] bad duration {dur_txt!r} "
                        f"in clause {clause!r}")
            if action not in _ACTIONS:
                raise FaultSpecError(
                    f"[racon_tpu::faults] unknown action {action!r} "
                    f"(expected one of {', '.join(_ACTIONS)})")
        site = site.strip()
        if not site:
            raise FaultSpecError(
                f"[racon_tpu::faults] empty site in clause {clause!r}")
        try:
            if sel.startswith("p="):
                prob = float(sel[2:])
                if not 0.0 <= prob <= 1.0:
                    raise ValueError
                rules[site] = _SiteRule(None, prob, action, duration)
            else:
                idx = frozenset(int(p) for p in sel.split(","))
                if any(i < 0 for i in idx):
                    raise ValueError
                rules[site] = _SiteRule(idx, 0.0, action, duration)
        except ValueError:
            raise FaultSpecError(
                f"[racon_tpu::faults] bad selector {sel!r} in clause "
                f"{clause!r}")
    return rules, seed, skew


class FaultInjector:
    """Parsed fault plan + per-site call counters."""

    def __init__(self, spec: str, seed: Optional[int] = None):
        self._rules, parsed_seed, self.skew = _parse(spec)
        self.seed = parsed_seed if seed is None else int(seed)
        self.spec = spec
        self._lock = threading.Lock()
        self._counts: Dict[str, int] = {}   # guarded-by: _lock
        self.fired: List[Tuple[str, int, str]] = []  # guarded-by: _lock

    def sites(self) -> Tuple[str, ...]:
        return tuple(sorted(self._rules))

    def _decide(self, site: str, index: int) -> Optional[str]:
        rule = self._rules.get(site)
        if rule is None:
            return None
        if rule.indices is not None:
            return rule.action if index in rule.indices else None
        h = hashlib.sha256(
            f"{self.seed}:{site}:{index}".encode()).digest()
        u = int.from_bytes(h[:8], "big") / 2 ** 64
        return rule.action if u < rule.prob else None

    def check(self, site: str, torn_ok: bool = False) -> bool:
        """Advance ``site``'s call counter; fire if the plan says so.

        ``torn_ok``: the caller is a write site that supports torn
        writes — a ``torn`` action returns True (the caller tears its
        write and hard-exits) instead of raising. Returns False when
        nothing fired.
        """
        with self._lock:
            index = self._counts.get(site, 0)
            self._counts[site] = index + 1
            action = self._decide(site, index)
            if action is not None:
                self.fired.append((site, index, action))
                duration = self._rules[site].duration
        if action is None:
            return False
        from racon_tpu.obs.metrics import record_fault
        record_fault(site, index, action)
        if action in ("hang", "stall"):
            self._sleep(action, duration)
            return False
        if action == "torn" and torn_ok:
            return True
        if action in ("raise", "torn"):
            # A torn rule at a site with no write to tear degrades to a
            # plain synthetic failure.
            raise InjectedFault(site, index)
        if action == "kill":
            hard_exit(137)
        os.kill(os.getpid(), signal.SIGTERM if action == "term"
                else signal.SIGINT)
        return False

    @staticmethod
    def _sleep(action: str, duration: Optional[float]) -> None:
        """Fail-slow actions: block, then PROCEED normally.

        ``stall`` sleeps a bounded duration (explicit ``=S`` or
        RACON_TPU_FAULT_STALL_S, default 1s) — a transient slowdown
        that must NOT trip anything by itself. ``hang`` sleeps provably
        past whatever watchdog deadline is armed on the current thread
        (2x the ambient deadline), falling back to an explicit ``=S``
        or RACON_TPU_FAULT_HANG_S (default 30s) when unguarded — e.g.
        at a pipeline-stage site, where the stall *detector*, not a
        call deadline, is the recovery under test. Returning (rather
        than sleeping forever) lets abandoned guard threads terminate
        deterministically, so tests never leak busy threads."""
        import time as _time
        if action == "stall":
            if duration is None:
                duration = float(envspec.read(ENV_STALL_S) or
                                 _STALL_DEFAULT_S)
            _time.sleep(duration)
            return
        if duration is None:
            from racon_tpu.resilience.watchdog import ambient_deadline
            armed = ambient_deadline()
            duration = 2.0 * armed if armed > 0 else \
                float(envspec.read(ENV_HANG_S) or _HANG_DEFAULT_S)
        _time.sleep(duration)

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counts)


_INJECTOR: Optional[FaultInjector] = None
_ARMED = False


def configure(spec: Optional[str], seed: Optional[int] = None) -> \
        Optional[FaultInjector]:
    """Install a fault plan programmatically (tests), or clear it with
    ``spec=None``. Returns the installed injector."""
    global _INJECTOR, _ARMED
    _INJECTOR = FaultInjector(spec, seed) if spec else None
    _ARMED = True
    return _INJECTOR


def get_injector() -> Optional[FaultInjector]:
    """The active injector, arming lazily from ``RACON_TPU_FAULTS``."""
    global _INJECTOR, _ARMED
    if not _ARMED:
        spec = envspec.read(ENV_FAULTS)
        _INJECTOR = FaultInjector(spec) if spec else None
        _ARMED = True
    return _INJECTOR


def maybe_fault(site: str) -> None:
    """The hook retry.call() runs before every attempt. Near-free when
    no fault plan is configured."""
    inj = get_injector()
    if inj is not None:
        inj.check(site)


def maybe_torn(site: str) -> bool:
    """The hook a tear-capable write site runs before its append.

    Returns True when a ``torn`` rule fires there — the caller must
    then write a *partial* record, fsync it, and :func:`hard_exit`
    (a torn write only matters if the process dies before finishing
    it). Other actions at the site behave exactly as in
    :func:`maybe_fault`.
    """
    inj = get_injector()
    return inj.check(site, torn_ok=True) if inj is not None else False


def clock_skew() -> float:
    """Seconds the distributed ledger shifts its lease clock by
    (``skew=S`` spec clause) — makes live leases look expired so steal
    paths are provable without wall-clock waits. 0.0 when unarmed."""
    inj = get_injector()
    return inj.skew if inj is not None else 0.0
