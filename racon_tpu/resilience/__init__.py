"""Resilience: transfer retry/backoff, fault injection, checkpoint/resume.

Long polishing runs over a flaky accelerator link need the same three
safety nets as any production training stack:

- :mod:`racon_tpu.resilience.retry` — bounded exponential backoff
  around every h2d/d2h/dispatch choke point, degrading a chunk to the
  host consensus path when the budget is exhausted
  (``RACON_TPU_RETRY``).
- :mod:`racon_tpu.resilience.faults` — deterministic, env-gated fault
  injector that proves those paths on CPU (``RACON_TPU_FAULTS``).
- :mod:`racon_tpu.resilience.checkpoint` — contig-granular
  checkpoint/resume keyed by a run fingerprint
  (``--checkpoint-dir`` / ``--resume``).
- :mod:`racon_tpu.resilience.watchdog` — monotonic-clock deadlines
  around the same choke points for the *fail-slow* class (a wedged
  call that never raises), escalating to worker self-eviction at the
  terminal breach budget (``RACON_TPU_DEADLINE_*`` /
  ``RACON_TPU_WATCHDOG_TERMINAL``).

docs/RESILIENCE.md is the operator-facing reference.
"""

from racon_tpu.resilience.checkpoint import (CheckpointError,
                                             CheckpointStore,
                                             run_fingerprint)
from racon_tpu.resilience.faults import (ENV_FAULTS, FaultInjector,
                                         FaultSpecError, InjectedFault,
                                         maybe_fault)
from racon_tpu.resilience.retry import (ENV_RETRY, RetryExhausted,
                                        RetryPolicy, call as with_retry,
                                        default_policy)
from racon_tpu.resilience.watchdog import (EXIT_SELF_EVICT,
                                           DispatchTimeout,
                                           WatchdogTerminal, guard,
                                           is_terminal)

__all__ = [
    "CheckpointError", "CheckpointStore", "run_fingerprint",
    "ENV_FAULTS", "FaultInjector", "FaultSpecError", "InjectedFault",
    "maybe_fault",
    "ENV_RETRY", "RetryExhausted", "RetryPolicy", "with_retry",
    "default_policy",
    "EXIT_SELF_EVICT", "DispatchTimeout", "WatchdogTerminal", "guard",
    "is_terminal",
]
