"""racon_wrapper equivalent: subsample reads / split targets / polish
per chunk.

Mirrors scripts/racon_wrapper.py:53-135: optionally subsample the reads
(rampler subsample), optionally split the targets into byte-bounded
chunks (rampler split), then polish each chunk **sequentially** with
identical options, streaming the combined FASTA to stdout.

The target-chunk granularity is the framework's memory-bounding AND
checkpoint/resume unit (the reference has no checkpointing; its wrapper's
sequential chunks are the de-facto restart point — SURVEY.md §5). Here
each chunk's output is written to ``<workdir>/chunk_<i>.fasta`` first and
``--resume`` skips chunks whose output already exists, so an interrupted
genome-scale run continues where it stopped. On multi-host deployments
each host takes a disjoint slice of chunks (``--num-shards``/
``--shard-id``) — no cross-host communication is needed, exactly like
the reference's process-per-chunk model over DCN.
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import time
from typing import List, Optional

from racon_tpu.tools import rampler


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(prog="racon_tpu_wrapper")
    ap.add_argument("sequences")
    ap.add_argument("overlaps")
    ap.add_argument("target_sequences")
    ap.add_argument("--split", type=int, metavar="CHUNK_SIZE",
                    help="split target sequences into chunks of the given "
                         "size in bytes")
    ap.add_argument("--subsample", type=int, nargs=2,
                    metavar=("REF_LEN", "COVERAGE"),
                    help="subsample sequences to the given coverage of the "
                         "given reference length")
    ap.add_argument("--work-directory", default=None)
    ap.add_argument("--resume", action="store_true",
                    help="reuse chunk outputs already present in the work "
                         "directory")
    ap.add_argument("--num-shards", type=int, default=1,
                    help="total hosts polishing disjoint chunk slices")
    ap.add_argument("--shard-id", type=int, default=0)
    # polishing options forwarded to the Polisher (reference wrapper
    # forwards the same set, scripts/racon_wrapper.py:150-180).
    ap.add_argument("-u", "--include-unpolished", action="store_true")
    ap.add_argument("-f", "--fragment-correction", action="store_true")
    ap.add_argument("-w", "--window-length", type=int, default=500)
    ap.add_argument("-q", "--quality-threshold", type=float, default=10.0)
    ap.add_argument("-e", "--error-threshold", type=float, default=0.3)
    ap.add_argument("-m", "--match", type=int, default=5)
    ap.add_argument("-x", "--mismatch", type=int, default=-4)
    ap.add_argument("-g", "--gap", type=int, default=-8)
    ap.add_argument("-t", "--threads", type=int, default=1)
    ap.add_argument("--backend", default="auto")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)

    from racon_tpu.io.parsers import ParseError
    from racon_tpu.models.overlap import PolisherError
    from racon_tpu.models.polisher import PolisherType, create_polisher

    work = args.work_directory or f"racon_tpu_work_directory_{int(time.time())}"
    own_workdir = args.work_directory is None
    os.makedirs(work, exist_ok=True)
    try:
        sequences = args.sequences
        if args.subsample:
            sequences = rampler.subsample(
                sequences, args.subsample[0], args.subsample[1], work)

        if args.split:
            targets = rampler.split(args.target_sequences, args.split, work)
        else:
            targets = [args.target_sequences]

        my_chunks = [(i, t) for i, t in enumerate(targets)
                     if i % args.num_shards == args.shard_id]

        out = sys.stdout.buffer
        for i, target in my_chunks:
            chunk_out = os.path.join(work, f"chunk_{i}.fasta")
            if not (args.resume and os.path.isfile(chunk_out)):
                polisher = create_polisher(
                    sequences, args.overlaps, target,
                    PolisherType.kF if args.fragment_correction
                    else PolisherType.kC,
                    args.window_length, args.quality_threshold,
                    args.error_threshold, args.match, args.mismatch,
                    args.gap, backend=args.backend, threads=args.threads)
                polisher.initialize()
                polished = polisher.polish(not args.include_unpolished)
                tmp = chunk_out + ".tmp"
                with open(tmp, "wb") as f:
                    for seq in polished:
                        f.write(b">" + seq.name.encode() + b"\n" +
                                seq.data + b"\n")
                os.replace(tmp, chunk_out)  # atomic checkpoint
            with open(chunk_out, "rb") as f:
                shutil.copyfileobj(f, out)
        out.flush()
    except (PolisherError, ParseError, ValueError) as exc:
        print(str(exc), file=sys.stderr)
        return 1
    finally:
        if own_workdir:
            shutil.rmtree(work, ignore_errors=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
