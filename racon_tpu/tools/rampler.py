"""rampler equivalent: subsample / split sequence files.

CLI contract mirrors the reference wrapper's use of the vendored rampler
(scripts/racon_wrapper.py:58-109):

  rampler -o <outdir> subsample <sequences> <reference_length> <coverage> ...
      -> <base>_<coverage>x.fasta[.fastq] per requested coverage
  rampler -o <outdir> split <sequences> <chunk_size_bytes>
      -> <base>_<i>.fasta[.fastq], i = 0..

Both stream records (constant memory) and preserve FASTA/FASTQ flavour.
Subsampling keeps each read with probability ref_length * coverage /
total_bases, using a fixed seed for reproducibility.
"""

from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

import numpy as np

from racon_tpu.io.parsers import (FastaParser, FastqParser, ParseError,
                                  create_sequence_parser, _FASTQ_EXTS)


def _base_and_flavour(path: str):
    base = os.path.basename(path)
    for ext in (".fasta.gz", ".fastq.gz", ".fa.gz", ".fq.gz", ".fasta",
                ".fastq", ".fa", ".fq", ".gz"):
        if base.endswith(ext):
            base = base[:-len(ext)]
            break
    fastq = path.endswith(_FASTQ_EXTS)
    return base, fastq


def _write_record(f, seq, fastq: bool) -> None:
    name = seq.name.encode()
    if fastq and seq.quality is not None:
        f.write(b"@" + name + b"\n" + seq.data + b"\n+\n" + seq.quality
                + b"\n")
    else:
        f.write(b">" + name + b"\n" + seq.data + b"\n")


_STREAM_CHUNK = 64 * 1024 * 1024  # bounded-memory streaming budget


def _stream(parser):
    """Iterate records with bounded memory (parse in 64 MiB chunks)."""
    parser.reset()
    while True:
        chunk, more = parser.parse(_STREAM_CHUNK)
        yield from chunk
        if not more:
            return


def subsample(sequences_path: str, reference_length: int, coverage: int,
              out_dir: str, seed: int = 1623) -> str:
    """Randomly subsample to ~coverage x reference_length bases."""
    parser = create_sequence_parser(sequences_path)
    total = 0
    for seq in _stream(parser):
        total += len(seq.data)
    if total == 0:
        raise ParseError(
            f"[racon_tpu::rampler] error: empty sequences file "
            f"{sequences_path}")
    p_keep = min(1.0, reference_length * coverage / total)

    base, fastq = _base_and_flavour(sequences_path)
    ext = ".fastq" if fastq else ".fasta"
    out_path = os.path.join(out_dir, f"{base}_{coverage}x{ext}")
    rng = np.random.default_rng(seed)
    with open(out_path, "wb") as f:
        for seq in _stream(parser):
            if rng.random() <= p_keep:
                _write_record(f, seq, fastq)
    return out_path


def split(sequences_path: str, chunk_size: int, out_dir: str) -> List[str]:
    """Split into chunks of ~chunk_size bases (sum of sequence lengths)."""
    if chunk_size <= 0:
        raise ParseError(
            "[racon_tpu::rampler] error: invalid chunk size!")
    base, fastq = _base_and_flavour(sequences_path)
    ext = ".fastq" if fastq else ".fasta"
    parser = create_sequence_parser(sequences_path)
    paths: List[str] = []
    f = None
    used = 0
    try:
        for seq in _stream(parser):
            if f is None or (used and used + len(seq.data) > chunk_size):
                if f is not None:
                    f.close()
                path = os.path.join(out_dir, f"{base}_{len(paths)}{ext}")
                paths.append(path)
                f = open(path, "wb")
                used = 0
            _write_record(f, seq, fastq)
            used += len(seq.data)
    finally:
        if f is not None:
            f.close()
    return paths


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="rampler_tpu")
    ap.add_argument("-o", "--out-directory", default=".")
    sub = ap.add_subparsers(dest="mode", required=True)
    ss = sub.add_parser("subsample")
    ss.add_argument("sequences")
    ss.add_argument("reference_length", type=int)
    ss.add_argument("coverage", type=int, nargs="+")
    sp = sub.add_parser("split")
    sp.add_argument("sequences")
    sp.add_argument("chunk_size", type=int)
    args = ap.parse_args(argv)

    os.makedirs(args.out_directory, exist_ok=True)
    try:
        if args.mode == "subsample":
            for cov in args.coverage:
                subsample(args.sequences, args.reference_length, cov,
                          args.out_directory)
        else:
            split(args.sequences, args.chunk_size, args.out_directory)
    except ParseError as exc:
        print(str(exc), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
