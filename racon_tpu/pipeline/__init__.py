"""Streaming execution pipeline: overlapped parse/pack -> device compute
-> decode/write with bounded queues and double buffering.

The reference polisher is a strictly serial phase machine (parse
everything, align everything, emit everything — src/polisher.cpp
``initialize()``/``polish()``), and BENCH_r05 shows what that costs on a
device backend: 321.5 compute-only windows/s/chip but only 184.6 end to
end — the TPU idles ~43% of wall time while the host encodes, packs,
and writes. This package is the classic input-pipeline answer from
training/inference stacks, applied to polishing:

- :mod:`racon_tpu.pipeline.queues` — bounded MPMC queues with
  backpressure, depth gauges, and blocked-time accounting;
- :mod:`racon_tpu.pipeline.stages` — single-thread stages wired by
  queues, with clean shutdown and exception propagation (a stage
  failure aborts every queue and re-raises at the consumer);
- :mod:`racon_tpu.pipeline.streaming` — the polish-specific executor:
  window chunks flow through pack (host encode) -> h2d (async
  device_put, double-buffered) -> compute (device rounds + d2h decode),
  while ordered retirement releases contiguous window ranges for
  streaming FASTA emission even when chunks retire out of order.

Gating: the pipeline is OFF by default. ``RACON_TPU_PIPELINE=1`` (or
the CLI's ``--pipeline-depth N`` with N > 0) turns it on;
``RACON_TPU_PIPELINE=0`` forces the serial path regardless of the CLI
knob, and the two paths are bit-identical on the polished FASTA
(differential tests in tests/test_pipeline.py; docs/PIPELINE.md has the
stage diagram and failure semantics).
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
from typing import Optional

ENV_PIPELINE = "RACON_TPU_PIPELINE"
ENV_DEPTH = "RACON_TPU_PIPELINE_DEPTH"
ENV_WALK_ASYNC = "RACON_TPU_WALK_ASYNC"

#: Default bound on in-flight chunks per queue: depth 2 = classic double
#: buffering (chunk N computes while chunk N+1's buffers sit in HBM).
DEFAULT_DEPTH = 2

# CLI override (configure()); None = environment decides.
_cli_depth: Optional[int] = None


def configure(depth: Optional[int]) -> None:
    """Install the CLI's --pipeline-depth knob for this process.

    ``depth > 0`` enables the pipeline with that bound; ``depth == 0``
    disables it; ``None`` leaves the decision to the environment.
    ``RACON_TPU_PIPELINE=0`` always wins (the serial-path escape hatch
    must not be maskable from the command line).
    """
    global _cli_depth
    if depth is not None and depth < 0:
        raise ValueError(
            f"[racon_tpu::pipeline] invalid pipeline depth {depth}")
    _cli_depth = depth


def pipeline_enabled() -> bool:
    """Streaming pipeline gate (module docstring has the truth table)."""
    env = envspec.read(ENV_PIPELINE)
    if env in ("0", "false"):
        return False
    if _cli_depth is not None:
        return _cli_depth > 0
    return env not in ("",)


def walk_async_enabled() -> bool:
    """Decoupled-walk gate (default ON): when the streaming pipeline
    runs on the fixed-round single-device jax path, each chunk's FINAL
    traceback walk dispatches as its own executable in a dedicated walk
    stage, overlapping the next chunk's forward rounds.
    ``RACON_TPU_WALK_ASYNC=0`` forces the fused forward+walk dispatch
    everywhere; the executor also falls back automatically where
    overlap is impossible (pipeline off, scheduler path, dp mesh, last
    chunk, over-budget queue — docs/KERNELS.md lists the conditions).
    Both paths are bit-identical (tests/test_walk_async.py)."""
    return envspec.read(ENV_WALK_ASYNC) not in ("0", "false")


def pipeline_depth() -> int:
    """Bounded-queue capacity (in-flight chunks per stage edge)."""
    if _cli_depth is not None and _cli_depth > 0:
        return _cli_depth
    env = envspec.read(ENV_DEPTH)
    if env:
        try:
            d = int(env)
        except ValueError as exc:
            raise ValueError(
                f"[racon_tpu::pipeline] invalid {ENV_DEPTH}={env!r}"
            ) from exc
        if d > 0:
            return d
    return DEFAULT_DEPTH


from racon_tpu.pipeline.queues import (BoundedQueue, PipelineAborted,  # noqa: E402
                                       QueueClosed)
from racon_tpu.pipeline.stages import (ENV_STALL, Pipeline,  # noqa: E402
                                       PipelineStalled, StageError,
                                       stall_window_s)

__all__ = [
    "BoundedQueue", "DEFAULT_DEPTH", "ENV_DEPTH", "ENV_PIPELINE",
    "ENV_STALL", "ENV_WALK_ASYNC", "Pipeline", "PipelineAborted",
    "PipelineStalled", "QueueClosed", "StageError", "configure",
    "pipeline_depth", "pipeline_enabled", "stall_window_s",
    "walk_async_enabled",
]
