"""The polish-specific streaming executor.

:func:`stream_consensus` runs a window list through the same per-slice
decomposition the serial engine uses (PoaEngine._plan_device_slice — the
two paths share the planning code, so chunk composition and therefore
output are identical by construction), but spread over four overlapped
stages:

    build ──q──▶ pack ──q──▶ h2d ──q──▶ compute ──q──▶ walk ──q──▶ (drain)
                   │                                            ▲
                   └── host-path items ─────────────────────────┘

- **build** (producer): slice the window list by ``chunk``, polish
  trivial windows (backbone consensus) inline, partition the rest into
  device chunk groups + host-fallback windows.
- **pack** encodes the next chunk's :class:`ChunkPlan` byte buffers
  while the device runs the current one, and polishes host-fallback
  windows — host consensus rides here precisely so it overlaps device
  compute. Host items then skip straight to the done queue, which is
  where out-of-order retirement comes from (a later slice's host item
  can finish while an earlier slice's chunks still compute).
- **h2d** starts the asynchronous ``device_put``
  (device_poa.put_chunk_bufs); the ``run`` queue's capacity (=depth)
  bounds how many chunks' input buffers sit in HBM — depth 2 is classic
  double buffering.
- **compute** runs the rounds (ConvergenceScheduler.run_chunk when
  sched is on, dispatch_chunk/collect_chunk otherwise), decodes the d2h
  pull, applies consensus to the windows, and re-polishes truncated
  windows on the host path. On the decoupled-walk path (fixed rounds,
  single device, RACON_TPU_WALK_ASYNC on) it instead dispatches only
  the forward/refinement half (dispatch_chunk_fwd) and forwards the
  in-flight plane tuple downstream.
- **walk** finishes decoupled chunks — the standalone final-round walk
  dispatch (ops/colwalk.py::dispatch_walk), d2h decode, consensus
  apply — so chunk N's serialized traceback overlaps chunk N+1's
  forward dispatch in the compute stage. Its queue of in-flight walk
  inputs is budget-bounded (ops/budget.py walk_queue_depth) so parked
  planes never breach the device buffer caps; fused items pass through
  untouched. Fallbacks to the fused path: gate off, sched path, dp
  mesh, last chunk, over-budget geometry, and degraded items.

The caller drains completed items; :class:`SliceTracker` releases
contiguous leading slices in input order, so downstream FASTA emission
streams in order no matter how items retire. All engine host-path work
(which temporarily flips ``engine.backend``) is serialized by one lock,
and the build stage uses a backend snapshot taken before threads start,
so the flip can never misroute a slice.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Tuple

from racon_tpu.pipeline import pipeline_depth
from racon_tpu.pipeline.queues import BoundedQueue, PipelineAborted, QueueClosed
from racon_tpu.pipeline.stages import Pipeline, StageError


class IngestPrefetcher:
    """The ingest stage: background-parse a file's chunks ahead of
    consumption so parsing of chunk N+1 hides under chunk N's device
    rounds (and, at polisher startup, the three input files parse
    concurrently instead of serially).

    One producer thread runs ``parser.reset()`` then chunked
    ``parser.parse(max_bytes)`` into a bounded queue (depth =
    pipeline depth — same backpressure discipline as the polish
    pipeline, so a slow consumer caps parsed-ahead memory).
    The consumer iterates :meth:`chunks`; only its *blocked* time books
    as ``ingest_wait_s`` (the critical-path term), while the producer's
    parse wall books as ``ingest_parse_s`` — when overlap works,
    wait ≪ parse. A producer-side :class:`ParseError` re-raises in the
    consumer, preserving the serial error contract.

    Always ``close()`` in a finally: an abandoned consumer aborts the
    queue, which unblocks and retires the producer thread.
    """

    def __init__(self, parser, max_bytes: int, label: str = "ingest",
                 depth: Optional[int] = None):
        self._parser = parser
        self._max_bytes = max_bytes
        self._q = BoundedQueue(f"ingest_{label}",
                               depth if depth is not None
                               else max(pipeline_depth(), 2))
        self._err: List[BaseException] = []
        self._parse_s = 0.0
        self._records = 0
        self._thread = threading.Thread(
            target=self._produce, name=f"racon-ingest-{label}",
            daemon=True)
        self._thread.start()

    def _produce(self) -> None:
        try:
            self._parser.reset()
            while True:
                t0 = time.perf_counter()
                chunk, more = self._parser.parse(self._max_bytes)
                self._parse_s += time.perf_counter() - t0
                self._records += len(chunk)
                self._q.put((chunk, more))
                if not more:
                    break
            self._q.close()
        except PipelineAborted:
            pass                    # consumer went away first
        except BaseException as exc:
            self._err.append(exc)
            self._q.abort()

    def chunks(self) -> Iterator[Tuple[List, bool]]:
        """Yield ``(records, more)`` chunks in parse order; blocked time
        accounts as ingest wait."""
        from racon_tpu.obs.metrics import record_ingest_wait
        while True:
            t0 = time.perf_counter()
            try:
                chunk, more = self._q.get()
            except QueueClosed:
                return
            except PipelineAborted:
                if self._err:
                    raise self._err[0]
                raise
            finally:
                record_ingest_wait(time.perf_counter() - t0)
            yield chunk, more
            if not more:
                return

    def close(self) -> None:
        """Tear down (idempotent): abort the queue, join the producer,
        flush this file's parse accounting."""
        from racon_tpu.obs.metrics import record_ingest_parse
        self._q.abort()
        self._thread.join(timeout=30.0)
        if self._records or self._parse_s:
            record_ingest_parse("prefetch", self._parse_s, self._records,
                                self._parser._pos)
            self._records = 0
            self._parse_s = 0.0


def serial_chunks(parser, max_bytes: int) -> Iterator[Tuple[List, bool]]:
    """The non-overlapped ingest path (prefetch unavailable: gate off,
    or an io/* fault drill needs single-threaded determinism): same
    ``(records, more)`` chunk protocol, parse wall booked as BOTH parse
    and wait seconds — serial ingest is all critical path."""
    from racon_tpu.obs.metrics import record_ingest_parse, record_ingest_wait
    parser.reset()
    parse_s = 0.0
    records = 0
    try:
        while True:
            t0 = time.perf_counter()
            chunk, more = parser.parse(max_bytes)
            parse_s += time.perf_counter() - t0
            records += len(chunk)
            yield chunk, more
            if not more:
                return
    finally:
        if records or parse_s:
            record_ingest_parse("serial", parse_s, records, parser._pos)
            record_ingest_wait(parse_s)


class _Item:
    """One unit of pipeline work: a device chunk group or a host batch."""
    __slots__ = ("kind", "sid", "gid", "windows", "sp", "plan", "bufs",
                 "fwd", "last")

    def __init__(self, kind: str, sid: int, windows, sp=None, gid: int = 0):
        self.kind = kind        # "chunk" | "host"
        self.sid = sid          # slice index (retirement unit)
        self.gid = gid          # chunk group index within the slice
        self.windows = windows
        self.sp = sp            # _DeviceSlicePlan (chunk items)
        self.plan = None        # ChunkPlan, set by the pack stage
        self.bufs = None        # device buffers, set by the h2d stage
        self.fwd = None         # (fwd_out, meta) from a decoupled
        #                         forward dispatch (compute stage); None
        #                         means the item took the fused path.
        self.last = False       # final chunk item of the stream — no
        #                         following forward to overlap with, so
        #                         it always dispatches fused.


class _WalkOverlapMeter:
    """Accounts how much decoupled-walk time was actually HIDDEN.

    A chunk's forward is "in flight" from its fwd dispatch until its own
    walk begins; while a walk runs, every second during which at least
    one OTHER chunk's forward is in flight is overlap — latency the
    fused path would have paid serially. The walk stage is single-
    threaded, so no forward leaves the in-flight set during a walk
    window; the set only grows (new fwd dispatches), which makes the
    overlap window exactly [first moment others exist, walk end].
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._inflight: set = set()
        self._cur_key = None
        self._cur_start: Optional[float] = None
        self._cur_overlap_from: Optional[float] = None
        self.walk_s = 0.0
        self.overlap_s = 0.0
        self.dispatches = 0
        self.fused = 0

    def fwd_dispatched(self, key) -> None:
        with self._lock:
            self._inflight.add(key)
            if (self._cur_start is not None
                    and self._cur_overlap_from is None
                    and self._inflight - {self._cur_key}):
                self._cur_overlap_from = time.perf_counter()

    def note_fused(self) -> None:
        with self._lock:
            self.fused += 1

    def walk_begin(self, key) -> None:
        with self._lock:
            self._inflight.discard(key)
            self._cur_key = key
            self._cur_start = time.perf_counter()
            self._cur_overlap_from = \
                self._cur_start if self._inflight else None

    def walk_end(self, key) -> None:
        with self._lock:
            now = time.perf_counter()
            if self._cur_start is not None:
                self.walk_s += now - self._cur_start
                if self._cur_overlap_from is not None:
                    self.overlap_s += now - self._cur_overlap_from
            self._cur_key = None
            self._cur_start = self._cur_overlap_from = None
            self.dispatches += 1


class SliceTracker:
    """Orders retirement: slices complete out of order, ranges release
    in input order.

    The build stage registers each slice (window range + item count)
    before emitting its items; the drain loop retires items as they
    complete. ``retire``/``flush`` return the newly releasable
    ``(slice_id, start, end)`` ranges — always the contiguous leading
    run of completed slices, so a consumer writing ranges as they come
    out preserves input order unconditionally.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._left: Dict[int, int] = {}
        self._bounds: Dict[int, Tuple[int, int]] = {}
        self._next = 0

    def register(self, sid: int, start: int, end: int,
                 n_items: int) -> None:
        with self._lock:
            self._bounds[sid] = (start, end)
            self._left[sid] = n_items

    def retire(self, sid: int) -> List[Tuple[int, int, int]]:
        with self._lock:
            left = self._left.get(sid, 0) - 1
            if left < 0:
                raise RuntimeError(
                    f"[racon_tpu::pipeline] slice {sid} retired more "
                    "items than it registered")
            self._left[sid] = left
            return self._release()

    def flush(self) -> List[Tuple[int, int, int]]:
        """Release whatever completed after the stream drained cleanly;
        a leftover incomplete slice means an item was lost — an
        executor bug that must fail loudly, not truncate output."""
        with self._lock:
            out = self._release()
            if self._bounds:
                raise RuntimeError(
                    f"[racon_tpu::pipeline] {len(self._bounds)} slice(s) "
                    "never completed (lost pipeline item)")
            return out

    def _release(self) -> List[Tuple[int, int, int]]:
        out = []
        while self._next in self._bounds and self._left[self._next] == 0:
            s, e = self._bounds.pop(self._next)
            del self._left[self._next]
            out.append((self._next, s, e))
            self._next += 1
        return out


def stream_consensus(engine, windows, chunk: int = 8192,
                     depth: Optional[int] = None,
                     tick=None) -> Iterator[Tuple[int, int]]:
    """Polish ``windows`` through the streaming pipeline.

    Generator yielding ``(start, end)`` index ranges (ascending,
    contiguous, covering ``range(len(windows))``) as windows finalize —
    every window in a yielded range has its consensus applied.
    ``depth`` bounds in-flight chunks per queue (None reads the
    RACON_TPU_PIPELINE_DEPTH / --pipeline-depth configuration);
    ``tick`` is called once per completed slice (progress reporting).

    Abandoning the generator early tears the pipeline down cleanly
    (queues abort, stage threads join). A stage failure re-raises here
    as :class:`~racon_tpu.pipeline.stages.StageError`.
    """
    n = len(windows)
    if n == 0:
        return
    if depth is None:
        depth = pipeline_depth()
    depth = max(1, int(depth))
    chunk = max(1, int(chunk))

    from racon_tpu.obs.metrics import (record_pipeline_wall, record_walk,
                                       record_windows)
    from racon_tpu.obs.trace import get_tracer
    from racon_tpu.pipeline import walk_async_enabled
    from racon_tpu.sched import sched_enabled
    tracer = get_tracer()

    # Snapshot the backend before any thread can flip it (the host path
    # temporarily forces "native"); all host-path work below serializes
    # on one lock so the flip is atomic w.r.t. every reader.
    backend_is_jax = engine.backend == "jax"
    host_lock = threading.Lock()
    sched = engine._make_scheduler() \
        if backend_is_jax and sched_enabled() else None

    # Decoupled-walk gate: fixed-round single-device jax path only. The
    # scheduler consumes every round's walk on the host (per-round flag
    # pulls), and under a dp mesh the walk-side psum would need the mesh
    # threaded through a second executable for no overlap win — both
    # keep the fused dispatch. want_q = 0 (RACON_TPU_WALK_QUEUE=0) is
    # the queue-knob spelling of "off".
    walk_async = (backend_is_jax and sched is None
                  and engine.mesh is None and walk_async_enabled())
    if walk_async:
        from racon_tpu.ops.budget import walk_queue_env
        want_q = walk_queue_env(depth)
        walk_async = want_q > 0
    else:
        want_q = 0
    meter = _WalkOverlapMeter()

    tracker = SliceTracker()
    pipe = Pipeline("polish")
    q_pack = pipe.queue("pack", depth)
    q_put = pipe.queue("put", depth)
    q_run = pipe.queue("run", depth)
    # The walk stage is always in the graph (fused items pass through);
    # its queue capacity bounds in-flight walk inputs — the per-item
    # admission check below additionally clamps by plane bytes.
    q_walk = pipe.queue("walk", max(want_q, 1))
    q_done = pipe.queue("done", max(2 * depth, 4))

    n_slices = (n + chunk - 1) // chunk

    def build():
        for sid, s in enumerate(range(0, n, chunk)):
            sl = windows[s:s + chunk]
            active = []
            for w in sl:
                if w.n_layers < 2:
                    w.set_backbone_consensus()
                else:
                    active.append(w)
            items: List[_Item] = []
            if active and backend_is_jax:
                dev, host, lq_max, la_max = engine._partition_device(
                    active)
                if dev:
                    sp = engine._plan_device_slice(dev, lq_max, la_max)
                    if sp.overflow_msg:
                        print(sp.overflow_msg, file=engine.log)
                    host = host + sp.host
                    for gi, ws in enumerate(sp.groups):
                        items.append(_Item("chunk", sid, ws, sp=sp,
                                           gid=gi))
                if host:
                    items.append(_Item("host", sid, host))
            elif active:
                items.append(_Item("host", sid, active))
            # The stream's final chunk item has no following forward to
            # hide behind — it dispatches fused. (A chunk-free final
            # slice merely costs the PREVIOUS chunk its overlap: the
            # meter just never sees another fwd in flight.)
            if sid == n_slices - 1:
                for it in reversed(items):
                    if it.kind == "chunk":
                        it.last = True
                        break
            # Register BEFORE emitting: an item can only retire after
            # its slice is known to the tracker.
            tracker.register(sid, s, min(s + chunk, n), len(items))
            for it in items:
                yield it

    def pack(item: _Item) -> Optional[_Item]:
        if item.kind == "host":
            # Host consensus runs here so it overlaps device compute;
            # the item then bypasses h2d/compute straight to done —
            # the source of out-of-order retirement.
            with host_lock:
                engine._consensus_host(item.windows, force_native=True)
            q_done.put(item)
            return None
        item.plan = engine._make_chunk_plan(item.sp, item.windows)
        return item

    def degrade(item: _Item, exc) -> None:
        # Retry budget exhausted at a transfer/dispatch choke point:
        # the chunk's windows polish on the (bit-identical) host path
        # and the item retires normally — degradation must never lose
        # a slice or change emitted bytes.
        with host_lock:
            engine._degrade(item.windows, exc)
        item.plan = item.bufs = item.fwd = None

    def h2d(item: _Item) -> Optional[_Item]:
        from racon_tpu.ops.device_poa import put_chunk_bufs
        from racon_tpu.resilience.retry import RetryExhausted
        # Async device_put: returns immediately, transfer overlaps the
        # current chunk's compute. q_run's capacity (= depth) bounds how
        # many chunks' input buffers are resident in HBM.
        try:
            item.bufs = put_chunk_bufs(item.plan, mesh=engine.mesh)
        except RetryExhausted as exc:
            degrade(item, exc)
            q_done.put(item)        # bypass compute, retire directly
            return None
        return item

    def admit_async(item: _Item) -> bool:
        # Per-item decoupling decision: never the last chunk, and the
        # queued planes of want_q chunks PLUS the one being walked must
        # fit the aggregate walk-queue budget at this geometry.
        if not walk_async or item.last:
            return False
        from racon_tpu.ops.budget import walk_queue_depth
        from racon_tpu.ops.device_poa import walk_plane_bytes_for
        pb = walk_plane_bytes_for(
            item.plan, ins_scale=engine._round_scales(
                engine.refine_rounds + 1),
            rounds=engine.refine_rounds + 1)
        return walk_queue_depth(pb, want_q + 1) >= want_q + 1

    def compute(item: _Item) -> _Item:
        from racon_tpu.ops.device_poa import (collect_chunk,
                                              dispatch_chunk,
                                              dispatch_chunk_fwd)
        from racon_tpu.resilience.retry import RetryExhausted
        trunc: List = []
        if admit_async(item):
            # Decoupled path: dispatch the forward half only and hand
            # the in-flight planes to the walk stage — this thread is
            # immediately free to dispatch the NEXT chunk's forward
            # while the walk stage synchronizes on this one's walk.
            try:
                with tracer.span("chunk", f"chunk{item.sid}.{item.gid}",
                                 windows=len(item.windows),
                                 lanes=item.plan.B,
                                 jobs=item.plan.n_jobs):
                    item.fwd = dispatch_chunk_fwd(
                        item.plan, match=engine.match,
                        mismatch=engine.mismatch, gap=engine.gap,
                        ins_scale=engine._round_scales(
                            engine.refine_rounds + 1),
                        rounds=engine.refine_rounds + 1,
                        bufs=item.bufs)
            except RetryExhausted as exc:
                degrade(item, exc)
                return item
            meter.fwd_dispatched((item.sid, item.gid))
            return item
        try:
            with tracer.span("chunk", f"chunk{item.sid}.{item.gid}",
                             windows=len(item.windows),
                             lanes=item.plan.B, jobs=item.plan.n_jobs):
                if sched is not None:
                    codes, covs = sched.run_chunk(item.plan,
                                                  bufs=item.bufs)
                else:
                    packed = dispatch_chunk(
                        item.plan, match=engine.match,
                        mismatch=engine.mismatch, gap=engine.gap,
                        ins_scale=engine._round_scales(
                            engine.refine_rounds + 1),
                        rounds=engine.refine_rounds + 1,
                        mesh=engine.mesh, bufs=item.bufs)
                    codes, covs = collect_chunk(item.plan, packed)
        except RetryExhausted as exc:
            degrade(item, exc)
            return item
        meter.note_fused()
        engine._apply_group(item.windows, codes, covs, trunc)
        if trunc:
            with host_lock:
                engine._redo_trunc(trunc)
        item.plan = item.bufs = None    # drop HBM references promptly
        return item

    def walk(item: _Item) -> _Item:
        # Fused/host/degraded items pass through untouched — the stage
        # only finishes chunks whose forward went out decoupled.
        if item.fwd is None:
            return item
        from racon_tpu.ops.colwalk import dispatch_walk
        from racon_tpu.ops.device_poa import collect_chunk
        from racon_tpu.resilience.retry import RetryExhausted
        key = (item.sid, item.gid)
        trunc: List = []
        try:
            meter.walk_begin(key)
            try:
                with tracer.span("walk", f"walk{item.sid}.{item.gid}",
                                 lanes=item.plan.B,
                                 windows=len(item.windows)):
                    fwd_out, fmeta = item.fwd
                    packed = dispatch_walk(item.plan, fwd_out, fmeta)
                    codes, covs = collect_chunk(item.plan, packed)
            finally:
                meter.walk_end(key)
        except RetryExhausted as exc:
            degrade(item, exc)
            return item
        engine._apply_group(item.windows, codes, covs, trunc)
        if trunc:
            with host_lock:
                engine._redo_trunc(trunc)
        item.plan = item.bufs = item.fwd = None
        return item

    pipe.source("build", build, q_pack)
    pipe.stage("pack", pack, q_pack, q_put)
    pipe.stage("h2d", h2d, q_put, q_run)
    pipe.stage("compute", compute, q_run, q_walk)
    pipe.stage("walk", walk, q_walk, q_done)

    t0 = time.perf_counter()
    last_end = 0
    try:
        with tracer.span("pipeline", "stream_consensus", windows=n,
                         depth=depth, chunk=chunk):
            try:
                with pipe:
                    for item in pipe.drain(q_done):
                        # Same counter the serial path bumps in
                        # consensus_windows: active windows only,
                        # counted after their consensus is applied.
                        record_windows(len(item.windows))
                        for _sid, s, e in tracker.retire(item.sid):
                            if tick is not None:
                                tick()
                            last_end = e
                            yield (s, e)
                    for _sid, s, e in tracker.flush():
                        if tick is not None:
                            tick()
                        last_end = e
                        yield (s, e)
            except StageError as err:
                from racon_tpu.pipeline.stages import PipelineStalled
                if not isinstance(err.__cause__, PipelineStalled):
                    raise
                # Stall recovery: the abort cascade already tore the
                # pipeline down (the with-block joined every stage), so
                # in-flight items are lost — but _consensus_host is
                # idempotent and bit-identical, so re-polishing every
                # window past the last retired slice on the host path
                # preserves the output bytes. The pipe/<stage> hang
                # fires BEFORE the stage body touches host_lock, so the
                # lock is free here.
                active = []
                for w in windows[last_end:]:
                    if w.n_layers < 2:
                        w.set_backbone_consensus()
                    else:
                        active.append(w)
                if active:
                    with host_lock:
                        engine._degrade(active, err.__cause__)
                    record_windows(len(active))
                if last_end < n:
                    if tick is not None:
                        tick()
                    yield (last_end, n)
    finally:
        record_pipeline_wall(time.perf_counter() - t0)
        if backend_is_jax:
            record_walk(meter.walk_s, meter.overlap_s, meter.dispatches,
                        meter.fused, q_walk.peak_depth, walk_async)
