"""Bounded queues with backpressure and blocked-time accounting.

The pipeline's queues are its flow control: a full queue blocks the
producer (backpressure — a slow FASTA writer eventually stalls the
parser instead of buffering the whole genome in RAM), an empty one
blocks the consumer. Both blocked durations are accounted per queue
(``put_wait_s`` / ``get_wait_s``) along with the peak depth, so the obs
registry can say *which* stage starves and which one chokes.

Shutdown protocol:

- ``close()`` — no more puts; getters drain the remaining items, then
  :class:`QueueClosed` tells them the stream ended. This is the normal
  end-of-stream path, cascaded stage by stage.
- ``abort()`` — a failure elsewhere; every blocked or future put/get
  raises :class:`PipelineAborted` immediately, remaining items are
  dropped. The pipeline driver aborts every queue when any stage fails,
  so no thread can hang on a peer that died.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Optional


class QueueClosed(Exception):
    """End of stream: the queue was closed and fully drained."""


class QueueTimeout(Exception):
    """``get(timeout=...)`` expired with the queue still empty and
    open — the caller's cue to act on what it already holds (the
    server's cross-request batcher flushes a partial batch here)."""


class PipelineAborted(RuntimeError):
    """The pipeline failed elsewhere; this queue was torn down."""


class BoundedQueue:
    """FIFO with a hard capacity, blocking put/get, and stall metrics."""

    def __init__(self, name: str, capacity: int):
        if capacity < 1:
            raise ValueError(
                f"[racon_tpu::pipeline] queue {name!r}: capacity must be "
                f">= 1, got {capacity}")
        self.name = name
        self.capacity = capacity
        self._items: deque = deque()
        self._lock = threading.Lock()
        self._not_full = threading.Condition(self._lock)
        self._not_empty = threading.Condition(self._lock)
        self._closed = False
        self._aborted = False
        self.peak_depth = 0
        self.put_wait_s = 0.0
        self.get_wait_s = 0.0
        self.n_items = 0

    # ------------------------------------------------------------- data path

    def put(self, item) -> None:
        """Enqueue; blocks while the queue is at capacity."""
        t0 = time.perf_counter()
        with self._not_full:
            while (len(self._items) >= self.capacity
                   and not self._aborted and not self._closed):
                self._not_full.wait(0.1)
            self.put_wait_s += time.perf_counter() - t0
            if self._aborted:
                raise PipelineAborted(self.name)
            if self._closed:
                raise RuntimeError(
                    f"[racon_tpu::pipeline] put on closed queue {self.name!r}")
            self._items.append(item)
            self.n_items += 1
            if len(self._items) > self.peak_depth:
                self.peak_depth = len(self._items)
            self._not_empty.notify()

    def get(self, timeout: Optional[float] = None):
        """Dequeue; blocks while empty. Raises QueueClosed at end of
        stream, PipelineAborted on teardown (pending items dropped),
        QueueTimeout when ``timeout`` seconds pass with the queue
        still empty and open (``timeout=None`` waits forever)."""
        t0 = time.perf_counter()
        deadline = None if timeout is None else t0 + max(timeout, 0.0)
        with self._not_empty:
            while (not self._items and not self._closed
                   and not self._aborted):
                if deadline is not None:
                    left = deadline - time.perf_counter()
                    if left <= 0:
                        break
                    self._not_empty.wait(min(0.1, left))
                else:
                    self._not_empty.wait(0.1)
            self.get_wait_s += time.perf_counter() - t0
            if self._aborted:
                raise PipelineAborted(self.name)
            if self._items:
                item = self._items.popleft()
                self._not_full.notify()
                return item
            if self._closed:
                raise QueueClosed(self.name)
            raise QueueTimeout(self.name)

    # ------------------------------------------------------------- lifecycle

    def close(self) -> None:
        """End of stream: getters drain, then see QueueClosed."""
        with self._lock:
            self._closed = True
            self._not_empty.notify_all()
            self._not_full.notify_all()

    def abort(self) -> None:
        """Failure teardown: wake and fail every blocked put/get."""
        with self._lock:
            self._aborted = True
            self._items.clear()
            self._not_empty.notify_all()
            self._not_full.notify_all()

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def metrics(self) -> Dict[str, object]:
        """Gauge snapshot for the obs registry / trace footer."""
        with self._lock:
            return {
                "peak": self.peak_depth,
                "capacity": self.capacity,
                "items": self.n_items,
                "put_wait_s": round(self.put_wait_s, 6),
                "get_wait_s": round(self.get_wait_s, 6),
            }
