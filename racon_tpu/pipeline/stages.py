"""Thread stages and the pipeline driver.

A :class:`Pipeline` is a linear chain of single-thread stages connected
by :class:`~racon_tpu.pipeline.queues.BoundedQueue` edges. One thread
per stage keeps per-stage work strictly ordered (the streaming polish
path needs deterministic chunk planning and a single JAX dispatch
stream); overlap comes from *different* stages running concurrently,
bounded by the queue capacities.

Failure semantics — the part serial code gets for free and threaded
code must earn:

- A stage that raises reports the exception to the driver, which aborts
  every queue; all other stages unblock, observe the abort, and exit.
- The consumer's :meth:`Pipeline.drain` re-raises the first failure as
  :class:`StageError` with the original exception chained (``raise ...
  from exc``), so tracebacks survive the thread hop.
- ``with pipeline:`` guarantees every stage thread is joined on exit —
  including when the consumer abandons the drain loop early (generator
  close), in which case the driver aborts the queues first so no
  producer can hang on a full edge.

Accounting: every stage records busy seconds (time in its work
function), stall seconds (blocked on its input or output queue), and an
item count into the obs metrics registry (``pipe_stage_*`` keys), and
emits a ``stage`` span when it exits; every queue records peak depth
and blocked time (``pipe_queue_*`` keys, ``queue`` spans). Overlap
efficiency — device-busy over wall — falls out of these numbers
(obs/metrics.py::pipeline_extras).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, List, Optional, Tuple

from racon_tpu.pipeline.queues import (BoundedQueue, PipelineAborted,
                                       QueueClosed)


class StageError(RuntimeError):
    """A pipeline stage failed; ``__cause__`` is the original exception."""

    def __init__(self, stage: str, exc: BaseException):
        super().__init__(
            f"[racon_tpu::pipeline] stage {stage!r} failed: {exc!r}")
        self.stage = stage


class _Stage(threading.Thread):
    """One worker thread: pull from ``inq`` (or iterate ``source``),
    apply ``fn``, push to ``outq``; close ``outq`` on clean exit."""

    def __init__(self, pipe: "Pipeline", name: str,
                 fn: Optional[Callable] = None,
                 source: Optional[Callable[[], Iterable]] = None,
                 inq: Optional[BoundedQueue] = None,
                 outq: Optional[BoundedQueue] = None):
        super().__init__(name=f"racon-pipe-{name}", daemon=True)
        self.pipe = pipe
        self.stage_name = name
        self.fn = fn
        self.source = source
        self.inq = inq
        self.outq = outq
        self.busy_s = 0.0
        self.stall_in_s = 0.0
        self.stall_out_s = 0.0
        self.items = 0

    def run(self) -> None:
        t_start = time.perf_counter()
        failed = False
        try:
            if self.source is not None:
                self._run_source()
            else:
                self._run_worker()
        except (QueueClosed, PipelineAborted):
            pass  # a peer ended the stream or tore the pipeline down
        except BaseException as exc:  # noqa: BLE001 — must cross threads
            failed = True
            self.pipe._fail(self.stage_name, exc)
        finally:
            if self.outq is not None and not failed:
                self.outq.close()
            self._publish(t_start)

    def _run_source(self) -> None:
        it = iter(self.source())
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                self.busy_s += time.perf_counter() - t0
                return
            self.busy_s += time.perf_counter() - t0
            t1 = time.perf_counter()
            self.outq.put(item)
            self.stall_out_s += time.perf_counter() - t1
            self.items += 1

    def _run_worker(self) -> None:
        while True:
            t0 = time.perf_counter()
            item = self.inq.get()            # QueueClosed ends the loop
            self.stall_in_s += time.perf_counter() - t0
            t1 = time.perf_counter()
            out = self.fn(item)
            self.busy_s += time.perf_counter() - t1
            if self.outq is not None and out is not None:
                t2 = time.perf_counter()
                self.outq.put(out)
                self.stall_out_s += time.perf_counter() - t2
            self.items += 1

    def _publish(self, t_start: float) -> None:
        from racon_tpu.obs.metrics import record_stage
        from racon_tpu.obs.trace import get_tracer
        record_stage(self.stage_name, self.busy_s, self.stall_in_s,
                     self.stall_out_s, self.items)
        get_tracer().emit(
            "stage", self.stage_name, t_start,
            time.perf_counter() - t_start, items=self.items,
            busy_s=round(self.busy_s, 6),
            stall_s=round(self.stall_in_s + self.stall_out_s, 6))


class Pipeline:
    """Linear stage chain; see the module docstring for semantics."""

    def __init__(self, name: str):
        self.name = name
        self._queues: List[BoundedQueue] = []
        self._stages: List[_Stage] = []
        self._error: Optional[Tuple[str, BaseException]] = None
        self._error_lock = threading.Lock()
        self._started = False

    # ----------------------------------------------------------- assembly

    def queue(self, name: str, capacity: int) -> BoundedQueue:
        q = BoundedQueue(name, capacity)
        self._queues.append(q)
        return q

    def source(self, name: str, gen_fn: Callable[[], Iterable],
               outq: BoundedQueue) -> None:
        """First stage: iterate ``gen_fn()`` into ``outq``."""
        self._stages.append(_Stage(self, name, source=gen_fn, outq=outq))

    def stage(self, name: str, fn: Callable, inq: BoundedQueue,
              outq: Optional[BoundedQueue] = None) -> None:
        """Worker stage: ``outq.put(fn(item))`` per ``inq`` item. A fn
        returning None consumes the item (nothing is forwarded — e.g.
        after routing it to a side queue itself)."""
        self._stages.append(_Stage(self, name, fn=fn, inq=inq, outq=outq))

    # ---------------------------------------------------------- execution

    def _fail(self, stage: str, exc: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = (stage, exc)
        for q in self._queues:
            q.abort()

    def raise_if_failed(self) -> None:
        with self._error_lock:
            err = self._error
        if err is not None:
            stage, exc = err
            raise StageError(stage, exc) from exc

    def start(self) -> "Pipeline":
        if self._started:
            raise RuntimeError(
                f"[racon_tpu::pipeline] pipeline {self.name!r} already "
                "started")
        self._started = True
        for s in self._stages:
            s.start()
        return self

    def drain(self, q: BoundedQueue):
        """Yield items from the terminal queue until the stream ends;
        re-raise the first stage failure (if any) when it does."""
        while True:
            try:
                item = q.get()
            except (QueueClosed, PipelineAborted):
                break
            yield item
        self.raise_if_failed()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Abort queues (no-op after a clean drain — every stage already
        exited) and join all stage threads; publishes queue gauges."""
        for q in self._queues:
            q.abort()
        for s in self._stages:
            s.join(timeout=timeout)
        from racon_tpu.obs.metrics import record_queue
        from racon_tpu.obs.trace import get_tracer
        tracer = get_tracer()
        for q in self._queues:
            m = q.metrics()
            record_queue(q.name, m["peak"], float(m["put_wait_s"]),
                         float(m["get_wait_s"]))
            tracer.point("queue", q.name, peak=m["peak"],
                         capacity=m["capacity"], items=m["items"],
                         put_wait_s=m["put_wait_s"],
                         get_wait_s=m["get_wait_s"])

    def __enter__(self) -> "Pipeline":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False

    @property
    def alive(self) -> bool:
        return any(s.is_alive() for s in self._stages)
