"""Thread stages and the pipeline driver.

A :class:`Pipeline` is a linear chain of single-thread stages connected
by :class:`~racon_tpu.pipeline.queues.BoundedQueue` edges. One thread
per stage keeps per-stage work strictly ordered (the streaming polish
path needs deterministic chunk planning and a single JAX dispatch
stream); overlap comes from *different* stages running concurrently,
bounded by the queue capacities.

Failure semantics — the part serial code gets for free and threaded
code must earn:

- A stage that raises reports the exception to the driver, which aborts
  every queue; all other stages unblock, observe the abort, and exit.
- The consumer's :meth:`Pipeline.drain` re-raises the first failure as
  :class:`StageError` with the original exception chained (``raise ...
  from exc``), so tracebacks survive the thread hop.
- ``with pipeline:`` guarantees every stage thread is joined on exit —
  including when the consumer abandons the drain loop early (generator
  close), in which case the driver aborts the queues first so no
  producer can hang on a full edge.

Accounting: every stage records busy seconds (time in its work
function), stall seconds (blocked on its input or output queue), and an
item count into the obs metrics registry (``pipe_stage_*`` keys), and
emits a ``stage`` span when it exits; every queue records peak depth
and blocked time (``pipe_queue_*`` keys, ``queue`` spans). Overlap
efficiency — device-busy over wall — falls out of these numbers
(obs/metrics.py::pipeline_extras).
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
import sys
import threading
import time
from typing import Callable, Iterable, List, Optional, Tuple

from racon_tpu.pipeline.queues import (BoundedQueue, PipelineAborted,
                                       QueueClosed)

#: Stall-detector window, seconds: no stage progressing AND no item
#: drained for this long converts a silent deadlock into an abort
#: cascade with a diagnostic dump. 0 disables the detector.
ENV_STALL = "RACON_TPU_STALL_S"
_STALL_DEFAULT_S = 300.0


def stall_window_s() -> float:
    txt = envspec.read(ENV_STALL).strip()
    if not txt:
        return _STALL_DEFAULT_S
    try:
        return float(txt)
    except ValueError:
        raise ValueError(
            f"[racon_tpu::pipeline] invalid {ENV_STALL}={txt!r} "
            "(expected a number of seconds, 0 to disable)")


class StageError(RuntimeError):
    """A pipeline stage failed; ``__cause__`` is the original exception."""

    def __init__(self, stage: str, exc: BaseException):
        super().__init__(
            f"[racon_tpu::pipeline] stage {stage!r} failed: {exc!r}")
        self.stage = stage


class PipelineStalled(RuntimeError):
    """The stall detector fired: every live stage sat silent for a full
    window while the consumer drained nothing — a deadlock or a wedged
    body that no per-call deadline covers. ``dump`` carries the
    per-stage/per-queue diagnostic the detector printed to stderr."""

    def __init__(self, window_s: float, dump: str):
        super().__init__(
            f"[racon_tpu::pipeline] no stage progressed for "
            f"{window_s:g}s — pipeline stalled\n{dump}")
        self.window_s = window_s
        self.dump = dump


class _Stage(threading.Thread):
    """One worker thread: pull from ``inq`` (or iterate ``source``),
    apply ``fn``, push to ``outq``; close ``outq`` on clean exit."""

    def __init__(self, pipe: "Pipeline", name: str,
                 fn: Optional[Callable] = None,
                 source: Optional[Callable[[], Iterable]] = None,
                 inq: Optional[BoundedQueue] = None,
                 outq: Optional[BoundedQueue] = None):
        super().__init__(name=f"racon-pipe-{name}", daemon=True)
        self.pipe = pipe
        self.stage_name = name
        self.fn = fn
        self.source = source
        self.inq = inq
        self.outq = outq
        self.busy_s = 0.0
        self.stall_in_s = 0.0
        self.stall_out_s = 0.0
        self.items = 0
        # Heartbeat for the stall detector: monotonic time of the last
        # loop transition, plus what the stage is doing right now.
        # Written by this thread only; torn reads are harmless (the
        # detector re-polls).
        self.last_progress = time.monotonic()
        self.state = "init"

    def _beat(self, state: str) -> None:
        self.last_progress = time.monotonic()
        self.state = state

    def run(self) -> None:
        t_start = time.perf_counter()
        failed = False
        try:
            if self.source is not None:
                self._run_source()
            else:
                self._run_worker()
        except (QueueClosed, PipelineAborted):
            pass  # a peer ended the stream or tore the pipeline down
        except BaseException as exc:  # noqa: BLE001 — must cross threads
            failed = True
            self.pipe._fail(self.stage_name, exc)
        finally:
            if self.outq is not None and not failed:
                self.outq.close()
            self._publish(t_start)

    def _run_source(self) -> None:
        from racon_tpu.resilience.faults import maybe_fault
        it = iter(self.source())
        while True:
            self._beat("run")
            t0 = time.perf_counter()
            try:
                maybe_fault(f"pipe/{self.stage_name}")
                item = next(it)
            except StopIteration:
                self.busy_s += time.perf_counter() - t0
                return
            self.busy_s += time.perf_counter() - t0
            self._beat("put")
            t1 = time.perf_counter()
            self.outq.put(item)
            self.stall_out_s += time.perf_counter() - t1
            self.items += 1

    def _run_worker(self) -> None:
        from racon_tpu.resilience.faults import maybe_fault
        while True:
            self._beat("get")
            t0 = time.perf_counter()
            item = self.inq.get()            # QueueClosed ends the loop
            self.stall_in_s += time.perf_counter() - t0
            self._beat("run")
            t1 = time.perf_counter()
            # The fault site fires BEFORE the work function, so a
            # ``hang`` here models a wedged stage body while the item
            # itself is still unprocessed — the stall detector, not a
            # call deadline, is the recovery under test.
            maybe_fault(f"pipe/{self.stage_name}")
            out = self.fn(item)
            self.busy_s += time.perf_counter() - t1
            if self.outq is not None and out is not None:
                self._beat("put")
                t2 = time.perf_counter()
                self.outq.put(out)
                self.stall_out_s += time.perf_counter() - t2
            self.items += 1

    def _publish(self, t_start: float) -> None:
        from racon_tpu.obs.metrics import record_stage
        from racon_tpu.obs.trace import get_tracer
        record_stage(self.stage_name, self.busy_s, self.stall_in_s,
                     self.stall_out_s, self.items)
        get_tracer().emit(
            "stage", self.stage_name, t_start,
            time.perf_counter() - t_start, items=self.items,
            busy_s=round(self.busy_s, 6),
            stall_s=round(self.stall_in_s + self.stall_out_s, 6))


class Pipeline:
    """Linear stage chain; see the module docstring for semantics."""

    def __init__(self, name: str):
        self.name = name
        self._queues: List[BoundedQueue] = []
        self._stages: List[_Stage] = []
        self._error: Optional[Tuple[str, BaseException]] = None
        self._error_lock = threading.Lock()
        self._started = False
        self._last_drain = time.monotonic()
        self._detector: Optional[_StallDetector] = None

    # ----------------------------------------------------------- assembly

    def queue(self, name: str, capacity: int) -> BoundedQueue:
        q = BoundedQueue(name, capacity)
        self._queues.append(q)
        return q

    def source(self, name: str, gen_fn: Callable[[], Iterable],
               outq: BoundedQueue) -> None:
        """First stage: iterate ``gen_fn()`` into ``outq``."""
        self._stages.append(_Stage(self, name, source=gen_fn, outq=outq))

    def stage(self, name: str, fn: Callable, inq: BoundedQueue,
              outq: Optional[BoundedQueue] = None) -> None:
        """Worker stage: ``outq.put(fn(item))`` per ``inq`` item. A fn
        returning None consumes the item (nothing is forwarded — e.g.
        after routing it to a side queue itself)."""
        self._stages.append(_Stage(self, name, fn=fn, inq=inq, outq=outq))

    # ---------------------------------------------------------- execution

    def _fail(self, stage: str, exc: BaseException) -> None:
        with self._error_lock:
            if self._error is None:
                self._error = (stage, exc)
        for q in self._queues:
            q.abort()

    def raise_if_failed(self) -> None:
        with self._error_lock:
            err = self._error
        if err is not None:
            stage, exc = err
            raise StageError(stage, exc) from exc

    def start(self) -> "Pipeline":
        if self._started:
            raise RuntimeError(
                f"[racon_tpu::pipeline] pipeline {self.name!r} already "
                "started")
        self._started = True
        self._last_drain = time.monotonic()
        for s in self._stages:
            s.start()
        window = stall_window_s()
        if window > 0:
            self._detector = _StallDetector(self, window)
            self._detector.start()
        return self

    def drain(self, q: BoundedQueue):
        """Yield items from the terminal queue until the stream ends;
        re-raise the first stage failure (if any) when it does."""
        while True:
            try:
                item = q.get()
            except (QueueClosed, PipelineAborted):
                break
            self._last_drain = time.monotonic()
            yield item
        self.raise_if_failed()

    def shutdown(self, timeout: float = 30.0) -> None:
        """Abort queues (no-op after a clean drain — every stage already
        exited) and join all stage threads; publishes queue gauges."""
        if self._detector is not None:
            self._detector.stop()
        for q in self._queues:
            q.abort()
        for s in self._stages:
            s.join(timeout=timeout)
        from racon_tpu.obs.metrics import record_queue
        from racon_tpu.obs.trace import get_tracer
        tracer = get_tracer()
        for q in self._queues:
            m = q.metrics()
            record_queue(q.name, m["peak"], float(m["put_wait_s"]),
                         float(m["get_wait_s"]))
            tracer.point("queue", q.name, peak=m["peak"],
                         capacity=m["capacity"], items=m["items"],
                         put_wait_s=m["put_wait_s"],
                         get_wait_s=m["get_wait_s"])

    def __enter__(self) -> "Pipeline":
        return self.start()

    def __exit__(self, *exc) -> bool:
        self.shutdown()
        return False

    @property
    def alive(self) -> bool:
        return any(s.is_alive() for s in self._stages)

    # ------------------------------------------------------ stall dump

    def _stall_dump(self) -> str:
        now = time.monotonic()
        lines = ["stage dump (name alive items busy_s state age_s):"]
        for s in self._stages:
            lines.append(
                f"  {s.stage_name:<10} alive={int(s.is_alive())} "
                f"items={s.items} busy={s.busy_s:.2f}s "
                f"state={s.state:<4} "
                f"age={now - s.last_progress:.1f}s")
        lines.append("queue dump (name depth/capacity):")
        for q in self._queues:
            lines.append(f"  {q.name:<10} {q.depth}/{q.capacity}")
        return "\n".join(lines)


class _StallDetector(threading.Thread):
    """Converts a silent pipeline deadlock into a fail-fast abort.

    Polls stage heartbeats and the consumer's drain timestamp; when the
    pipeline has live stages yet NOTHING — no stage loop transition, no
    drained item — moved for a full window, it dumps per-stage/per-queue
    state to stderr, records ``pipe_stall_events`` + a ``stall`` span,
    and fails the pipeline with :class:`PipelineStalled` so the abort
    cascade unblocks every queue instead of hanging forever.
    """

    def __init__(self, pipe: Pipeline, window_s: float):
        super().__init__(name=f"racon-stall-{pipe.name}", daemon=True)
        self.pipe = pipe
        self.window_s = window_s
        self._stop = threading.Event()

    def stop(self) -> None:
        self._stop.set()

    def run(self) -> None:
        poll = min(self.window_s / 4.0, 0.5)
        while not self._stop.wait(poll):
            pipe = self.pipe
            if not pipe.alive:
                continue
            now = time.monotonic()
            newest = max([s.last_progress for s in pipe._stages]
                         + [pipe._last_drain])
            if now - newest < self.window_s:
                continue
            dump = pipe._stall_dump()
            print(f"[racon_tpu::pipeline] stall detected: no progress "
                  f"for {now - newest:.1f}s (window {self.window_s:g}s)"
                  f"\n{dump}", file=sys.stderr, flush=True)
            from racon_tpu.obs.metrics import record_stall
            from racon_tpu.resilience import watchdog
            record_stall(self.window_s, len(pipe._stages))
            watchdog.note_stall(len(pipe._stages))
            pipe._fail("stall", PipelineStalled(self.window_s, dump))
            return
