"""racon-tpu: a TPU-native consensus / polishing framework.

A from-scratch re-design of the capabilities of racon (Vaser et al., Genome
Research 2017; reference implementation: open-estuary/racon, C++/CPU) for
TPU hardware using JAX/XLA/Pallas.

Architecture (vs. reference layers, see SURVEY.md):

  reference (C++/CPU, thread pool)        racon-tpu (JAX/TPU)
  --------------------------------        --------------------------------
  bioparser (streaming format IO)     ->  racon_tpu.io.parsers
  Sequence/Overlap/Window domain      ->  racon_tpu.models.{sequence,overlap,window}
  edlib NW alignment (per overlap)    ->  racon_tpu.native banded-NW (C++/ctypes)
                                          + racon_tpu.ops.align batched device NW
  spoa POA engine (per window,        ->  racon_tpu.ops.poa: batched
    per-thread engines)                   backbone-anchored POA with iterative
                                          refinement; windows/layers are the
                                          batch dimension
  thread_pool task parallelism        ->  batch parallelism: alignment jobs are
                                          the batch dim; chips via shard_map
                                          Mesh (racon_tpu.parallel); hosts via
                                          target shards (racon_tpu.tools)
  Polisher orchestration              ->  racon_tpu.models.polisher
  logger (phase timing/progress)      ->  racon_tpu.utils.logger
"""

__version__ = "0.2.0"

from racon_tpu.models.sequence import Sequence  # noqa: F401
from racon_tpu.models.overlap import Overlap  # noqa: F401
