"""racon-tpu: a TPU-native consensus / polishing framework.

A from-scratch re-design of the capabilities of racon (Vaser et al., Genome
Research 2017; reference implementation: open-estuary/racon, C++/CPU) for
TPU hardware using JAX/XLA/Pallas.

Architecture (vs. reference layers, see SURVEY.md):

  reference (C++/CPU, thread pool)        racon-tpu (JAX/TPU)
  --------------------------------        --------------------------------
  bioparser (streaming format IO)     ->  racon_tpu.io (Python + C++ native)
  Sequence/Overlap/Window domain      ->  racon_tpu.models.{sequence,overlap,window}
  edlib NW alignment (per overlap)    ->  racon_tpu.native banded-NW (C++),
                                          racon_tpu.ops.nw batched TPU kernel
  spoa POA engine (per window,        ->  racon_tpu.ops.poa_jax: batched POA,
    per-thread engines)                   vmapped over windows, sharded over
                                          chips via racon_tpu.parallel
  thread_pool task parallelism        ->  batch parallelism: windows are the
                                          batch dim; chips via shard_map Mesh;
                                          hosts via target shards (wrapper)
  Polisher orchestration              ->  racon_tpu.models.polisher
  CLI (racon)                         ->  racon_tpu.cli (racon_tpu -m / console)
"""

__version__ = "0.1.0"

from racon_tpu.models.sequence import Sequence  # noqa: F401
from racon_tpu.models.overlap import Overlap  # noqa: F401
