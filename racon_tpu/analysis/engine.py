"""Rule engine for the contract linter.

Plain AST walking over the repo's own sources: file discovery, cached
parse trees, a pragma convention for blessed exceptions, a finding
model with file:line + rule id + severity, baseline suppression for
grandfathered findings, and byte-stable text/JSON reports (sorted,
fixed separators — two runs on the same tree produce identical bytes,
so the CI gate can diff them).

Pragmas: a rule-named tag in a ``# lint: <tag> (...)`` comment on the
flagged line (or the line directly above it) suppresses that rule at
that site — e.g. ``# lint: atomic-ok (torn-write drill)``. The tag
spelling each rule honours is part of the rule catalog in
docs/ANALYSIS.md.
"""

from __future__ import annotations

import ast
import json
import os
import re
from typing import Callable, Dict, Iterable, List, NamedTuple, Optional


class Finding(NamedTuple):
    rule: str      # rule id, e.g. "ENV001"
    severity: str  # "error" | "warn"
    path: str      # repo-relative posix path
    line: int      # 1-indexed
    message: str

    @property
    def fingerprint(self) -> str:
        """Baseline identity: stable across line drift (a finding that
        merely moves does not escape its suppression, and a new finding
        with the same shape elsewhere in the file is still new only if
        its message differs)."""
        return f"{self.rule}:{self.path}:{self.message}"


class Rule(NamedTuple):
    name: str            # e.g. "env-contract"
    ids: tuple           # finding ids this rule can emit
    severity: str
    summary: str         # one-liner for the catalog / reports
    check: Callable      # check(ctx) -> Iterable[Finding]


_SKIP_DIRS = {"__pycache__", ".git", ".pytest_cache"}


def discover_files(root: str) -> List[str]:
    """Default lint corpus: every .py under racon_tpu/ and scripts/,
    plus bench.py. tests/ are deliberately out — fixtures under
    tests/fixtures/analysis/ carry seeded violations."""
    out: List[str] = []
    for top in ("racon_tpu", "scripts"):
        base = os.path.join(root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = sorted(d for d in dirnames
                                 if d not in _SKIP_DIRS)
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    bench = os.path.join(root, "bench.py")
    if os.path.exists(bench):
        out.append(bench)
    return out


class Context:
    """Shared state for one lint run.

    ``full`` distinguishes the repo-wide run (registry<->code<->docs
    direction checks enabled, rule path scopes applied) from a fixture
    run (``full=False``: only the per-file directions fire, and every
    supplied file is in scope regardless of its path — that is how the
    seeded-violation fixtures under tests/fixtures/analysis/ exercise
    rules whose repo scope they live outside of).

    The ``*_override`` kwargs let tests inject synthetic registries to
    exercise the registry-direction findings (dead declaration,
    undocumented gate, ...) without mutating the real tables.
    """

    def __init__(self, root: str, files: Optional[List[str]] = None,
                 full: bool = True, *,
                 env_registry: Optional[Dict] = None,
                 metric_specs: Optional[tuple] = None,
                 fault_sites: Optional[tuple] = None,
                 fault_prefixes: Optional[tuple] = None,
                 span_required: Optional[Dict] = None,
                 span_attr_free: Optional[tuple] = None,
                 hist_buckets: Optional[Dict] = None,
                 docs_override: Optional[Dict[str, str]] = None):
        self.root = os.path.abspath(root)
        self.files = files if files is not None else \
            discover_files(self.root)
        self.full = full
        self._src: Dict[str, str] = {}
        self._tree: Dict[str, Optional[ast.Module]] = {}
        self._consts: Optional[Dict[str, str]] = None
        self._env_registry = env_registry
        self._metric_specs = metric_specs
        self._fault_sites = fault_sites
        self._fault_prefixes = fault_prefixes
        self._span_required = span_required
        self._span_attr_free = span_attr_free
        self._hist_buckets = hist_buckets
        self._docs_override = docs_override

    # ------------------------------------------------------- file access

    def rel(self, path: str) -> str:
        p = os.path.abspath(path)
        if p.startswith(self.root + os.sep):
            p = p[len(self.root) + 1:]
        return p.replace(os.sep, "/")

    def source(self, path: str) -> str:
        if path not in self._src:
            try:
                with open(path, "r", encoding="utf-8") as fh:
                    self._src[path] = fh.read()
            except OSError:
                self._src[path] = ""
        return self._src[path]

    def lines(self, path: str) -> List[str]:
        return self.source(path).splitlines()

    def tree(self, path: str) -> Optional[ast.Module]:
        if path not in self._tree:
            try:
                self._tree[path] = ast.parse(self.source(path))
            except SyntaxError:
                self._tree[path] = None
        return self._tree[path]

    def scoped(self, *prefixes: str) -> List[str]:
        """Files under any of the repo-relative prefixes. In fixture
        mode every supplied file is in scope."""
        if not self.full:
            return list(self.files)
        out = []
        for f in self.files:
            r = self.rel(f)
            if any(r == p or r.startswith(p) for p in prefixes):
                out.append(f)
        return out

    def pragma(self, path: str, lineno: int, tag: str) -> bool:
        """True when ``# lint: <tag>`` annotates ``lineno`` or the line
        directly above it."""
        lines = self.lines(path)
        pat = re.compile(r"#\s*lint:\s*" + re.escape(tag) + r"\b")
        for ln in (lineno, lineno - 1):
            if 1 <= ln <= len(lines) and pat.search(lines[ln - 1]):
                return True
        return False

    # ----------------------------------------------------- shared lookups

    def module_consts(self) -> Dict[str, str]:
        """Repo-wide map of top-level UPPER_CASE string constants
        (``ENV_FAULTS = "RACON_TPU_FAULTS"``) by bare name, used to
        resolve Name/Attribute arguments of env reads and
        ``envspec.read`` calls."""
        if self._consts is None:
            consts: Dict[str, str] = {}
            for f in self.files:
                t = self.tree(f)
                if t is None:
                    continue
                for node in t.body:
                    if isinstance(node, ast.Assign) and \
                       isinstance(node.value, ast.Constant) and \
                       isinstance(node.value.value, str):
                        for tgt in node.targets:
                            if isinstance(tgt, ast.Name) and \
                               tgt.id.isupper():
                                consts[tgt.id] = node.value.value
            self._consts = consts
        return self._consts

    def doc_text(self, name: str) -> str:
        if self._docs_override is not None:
            return self._docs_override.get(name, "")
        try:
            with open(os.path.join(self.root, "docs", name), "r",
                      encoding="utf-8") as fh:
                return fh.read()
        except OSError:
            return ""

    def doc_files(self) -> Dict[str, str]:
        """name -> text for every docs/*.md (plus README.md)."""
        if self._docs_override is not None:
            return dict(self._docs_override)
        out: Dict[str, str] = {}
        docs = os.path.join(self.root, "docs")
        if os.path.isdir(docs):
            for fn in sorted(os.listdir(docs)):
                if fn.endswith(".md"):
                    out[fn] = self.doc_text(fn)
        readme = os.path.join(self.root, "README.md")
        if os.path.exists(readme):
            with open(readme, "r", encoding="utf-8") as fh:
                out["README.md"] = fh.read()
        return out

    # Registry loaders: the real tables unless a test injected fakes.

    def env_registry(self) -> Dict:
        if self._env_registry is not None:
            return self._env_registry
        from racon_tpu.utils import envspec
        return envspec.REGISTRY

    def metric_specs(self) -> tuple:
        if self._metric_specs is not None:
            return self._metric_specs
        from racon_tpu.obs import metrics
        return metrics.METRIC_SPECS

    def fault_sites(self) -> tuple:
        if self._fault_sites is not None:
            return self._fault_sites
        from racon_tpu.resilience import faults
        return faults.SITES

    def fault_prefixes(self) -> tuple:
        if self._fault_prefixes is not None:
            return self._fault_prefixes
        from racon_tpu.resilience import faults
        return faults.SITE_PREFIXES

    def hist_buckets(self) -> Dict:
        if self._hist_buckets is not None:
            return self._hist_buckets
        from racon_tpu.obs import metrics
        return metrics.HIST_BUCKETS

    def _span_tables(self):
        """(KIND_REQUIRED_ATTRS, ATTR_FREE_KINDS) parsed statically out
        of scripts/obs_report.py — the validator is a script, not a
        package, and the linter must not execute it."""
        path = os.path.join(self.root, "scripts", "obs_report.py")
        required: Dict[str, tuple] = {}
        free: tuple = ()
        try:
            tree = ast.parse(open(path, "r", encoding="utf-8").read())
        except (OSError, SyntaxError):
            return required, free
        for node in tree.body:
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not isinstance(tgt, ast.Name):
                    continue
                if tgt.id == "KIND_REQUIRED_ATTRS":
                    try:
                        required = {k: tuple(v) for k, v in
                                    ast.literal_eval(node.value).items()}
                    except ValueError:
                        pass
                elif tgt.id == "ATTR_FREE_KINDS":
                    try:
                        free = tuple(ast.literal_eval(node.value))
                    except ValueError:
                        pass
        return required, free

    def span_required(self) -> Dict[str, tuple]:
        if self._span_required is not None:
            return self._span_required
        return self._span_tables()[0]

    def span_attr_free(self) -> tuple:
        if self._span_attr_free is not None:
            return self._span_attr_free
        return self._span_tables()[1]


def run_rules(rules: Iterable[Rule], ctx: Context) -> List[Finding]:
    findings: List[Finding] = []
    for rule in rules:
        findings.extend(rule.check(ctx))
    # Byte-stable order; dedup (two rules re-walking one tree may
    # reproduce an identical finding).
    return sorted(set(findings),
                  key=lambda f: (f.path, f.line, f.rule, f.message))


# -------------------------------------------------------------- baseline

def load_baseline(path: str) -> List[str]:
    """Grandfathered finding fingerprints (JSON list). Missing file =
    empty baseline: the repo lints clean or CI fails."""
    try:
        with open(path, "r", encoding="utf-8") as fh:
            data = json.load(fh)
    except OSError:
        return []
    if not isinstance(data, list):
        raise ValueError(f"[racon_tpu::analysis] baseline {path!r} "
                         "must be a JSON list of fingerprints")
    return [str(x) for x in data]


def split_findings(findings: List[Finding], baseline: List[str]):
    """(active, suppressed) partition by baseline fingerprint."""
    allowed = set(baseline)
    active = [f for f in findings if f.fingerprint not in allowed]
    suppressed = [f for f in findings if f.fingerprint in allowed]
    return active, suppressed


# --------------------------------------------------------------- reports

def render_text(findings: List[Finding],
                suppressed: Optional[List[Finding]] = None) -> str:
    out = []
    for f in findings:
        out.append(f"{f.path}:{f.line}: {f.rule} [{f.severity}] "
                   f"{f.message}")
    for f in suppressed or []:
        out.append(f"{f.path}:{f.line}: {f.rule} [baselined] "
                   f"{f.message}")
    return "\n".join(out) + ("\n" if out else "")


def render_json(findings: List[Finding],
                suppressed: Optional[List[Finding]] = None) -> str:
    def row(f: Finding, base: bool):
        return {"rule": f.rule, "severity": f.severity, "path": f.path,
                "line": f.line, "message": f.message,
                "baselined": base}
    rows = [row(f, False) for f in findings] + \
           [row(f, True) for f in suppressed or []]
    return json.dumps(rows, indent=2, sort_keys=True) + "\n"


def summary_line(findings: List[Finding], suppressed: List[Finding],
                 n_rules: int, n_files: int) -> str:
    """The burn-down line ci.sh logs grep for."""
    total = len(findings) + len(suppressed)
    return (f"lint_findings_total={total} active={len(findings)} "
            f"baselined={len(suppressed)} rules={n_rules} "
            f"files={n_files}")
