"""Contract linter: static-analysis rules machine-checking the repo's
cross-cutting invariants (env gates, fault sites, metrics, spans,
atomic writes, lock discipline, choke points, determinism).

The engine (racon_tpu/analysis/engine.py) walks Python ASTs and emits
findings; the rules (racon_tpu/analysis/rules.py) each cross-check one
hand-maintained contract against its machine-readable registry —
utils/envspec.py, resilience/faults.py SITES, obs/metrics.py
METRIC_SPECS, scripts/obs_report.py span tables. Driven by
scripts/lint.py (``--ci`` gates in ci.sh); docs/ANALYSIS.md is the
rule catalog.
"""

from racon_tpu.analysis.engine import (Context, Finding, Rule,
                                       load_baseline, render_json,
                                       render_text, run_rules,
                                       split_findings, summary_line)
from racon_tpu.analysis.rules import ALL_RULES

__all__ = ["ALL_RULES", "Context", "Finding", "Rule", "load_baseline",
           "render_json", "render_text", "run_rules", "split_findings",
           "summary_line"]
