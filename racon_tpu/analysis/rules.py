"""The contract rules.

Each rule cross-checks one hand-maintained invariant against its
machine-readable registry (see docs/ANALYSIS.md for the catalog):

  env-contract   ENV001-ENV005  code <-> utils/envspec.py <-> docs
  fault-site     FLT001-FLT002  hook literals <-> faults.SITES <-> tests
  metrics        MET001-MET004  recorded keys <-> metrics.METRIC_SPECS
                                <-> merge_kind <-> docs/OBSERVABILITY.md
  span-schema    SPAN001-SPAN003 Tracer emissions <-> obs_report tables
  atomic-write   ATM001         no bare open(w) in durable-output dirs
  lock-discipline LCK001        # guarded-by: attrs mutate under lock
  choke-point    CHK001         device_put inside retry.call closures
  determinism    DET001         no wallclock/PRNG in identity paths
  histogram      HIS001         record_hist <-> HIST_BUCKETS <->
                                METRIC_SPECS 'hist' rows <-> exporter

Registry-direction checks (dead declarations, doc drift, coverage)
only run in full-repo mode (``ctx.full``); per-file directions also
fire on single fixture files.
"""

from __future__ import annotations

import ast
import fnmatch
import re
from typing import Dict, Iterator, List, Optional, Tuple

from racon_tpu.analysis.engine import Context, Finding, Rule

_ENV_PREFIX = "RACON_TPU_"


# ----------------------------------------------------------- ast helpers

def _unparse(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:
        return ""


def _str_key(node: ast.AST) -> Optional[str]:
    """Static text of a string expression; dynamic f-string pieces
    become ``*``. IfExp is resolved per branch by the callers."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.JoinedStr):
        out = []
        for part in node.values:
            if isinstance(part, ast.Constant):
                out.append(str(part.value))
            else:
                out.append("*")
        return "".join(out)
    return None


def _str_keys(node: ast.AST) -> List[str]:
    """Like _str_key but flattens conditional expressions (the
    ``f"res_ckpt_{e}s" if ... else "res_ckpt_resumes"`` idiom)."""
    if isinstance(node, ast.IfExp):
        return _str_keys(node.body) + _str_keys(node.orelse)
    k = _str_key(node)
    return [k] if k is not None else []


def _resolve_name(node: ast.AST, consts: Dict[str, str]) -> \
        Optional[str]:
    """Resolve an env-name argument: string literal, module constant
    (``ENV_FAULTS``), or attribute constant (``fleet.ENV_OBS_DIR``).
    None when not statically resolvable (function parameters)."""
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    if isinstance(node, ast.Name):
        return consts.get(node.id)
    if isinstance(node, ast.Attribute):
        return consts.get(node.attr)
    return None


def _func_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f.attr
    return ""


# ========================================================== env-contract

def _iter_env_reads(tree: ast.Module) -> Iterator[Tuple[int, ast.AST]]:
    """(lineno, name-expression) for every os.environ read:
    ``environ.get(X, ...)``, ``os.getenv(X, ...)``, ``environ[X]``."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute) and f.attr == "get" and \
                    _unparse(f.value).endswith("environ") and node.args:
                yield node.lineno, node.args[0]
            elif _func_name(node) == "getenv" and node.args:
                yield node.lineno, node.args[0]
        elif isinstance(node, ast.Subscript) and \
                isinstance(node.ctx, ast.Load) and \
                _unparse(node.value).endswith("environ"):
            yield node.lineno, node.slice


def check_env_contract(ctx: Context) -> Iterator[Finding]:
    consts = ctx.module_consts()
    registry = ctx.env_registry()

    # ENV001/ENV002: every read resolves through a declared spec.
    for path in ctx.scoped("racon_tpu/", "scripts/", "bench.py"):
        rel = ctx.rel(path)
        if rel == "racon_tpu/utils/envspec.py":
            continue
        tree = ctx.tree(path)
        if tree is None:
            continue
        for lineno, arg in _iter_env_reads(tree):
            name = _resolve_name(arg, consts)
            if name is None or not name.startswith(_ENV_PREFIX):
                continue
            if ctx.pragma(path, lineno, "env-ok"):
                continue
            yield Finding(
                "ENV001", "error", rel, lineno,
                f"raw environment read of {name}: route it through "
                f"racon_tpu.utils.envspec.read")
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and \
                    _func_name(node) == "read" and \
                    isinstance(node.func, ast.Attribute) and \
                    _unparse(node.func.value).endswith("envspec") and \
                    node.args:
                name = _resolve_name(node.args[0], consts)
                if name is not None and name not in registry:
                    yield Finding(
                        "ENV002", "error", rel, node.lineno,
                        f"envspec.read of undeclared gate {name}: "
                        f"declare it in racon_tpu/utils/envspec.py")

    if not ctx.full:
        return

    # Declaration line numbers for registry-direction findings.
    spec_rel = "racon_tpu/utils/envspec.py"
    spec_path = None
    for f in ctx.files:
        if ctx.rel(f) == spec_rel:
            spec_path = f
    spec_lines = ctx.lines(spec_path) if spec_path else []

    def decl_line(name: str) -> int:
        for i, ln in enumerate(spec_lines, 1):
            if f'"{name}"' in ln:
                return i
        return 1

    # Name -> is it read anywhere (textual: the code keeps ENV_*
    # constants, so the full name appears at its declaration site).
    corpus = {ctx.rel(f): ctx.source(f)
              for f in ctx.scoped("racon_tpu/", "scripts/", "bench.py")
              if ctx.rel(f) != spec_rel}
    blob = "\n".join(corpus.values())
    docs = ctx.doc_files()

    for name, spec in sorted(registry.items()):
        # ENV003: declared but never read.
        if name not in blob:
            yield Finding(
                "ENV003", "error", spec_rel, decl_line(name),
                f"declared gate {name} is read nowhere in racon_tpu/, "
                f"scripts/, or bench.py: delete the declaration")
        # ENV004: declared but missing from its doc file.
        doc = getattr(spec, "doc", None) or (
            spec.get("doc") if isinstance(spec, dict) else None)
        if doc is not None and name not in docs.get(doc, ""):
            yield Finding(
                "ENV004", "error", spec_rel, decl_line(name),
                f"declared gate {name} has no row in docs/{doc}")

    # ENV005: documented names that no declaration covers. A token
    # ending in ``_`` is a family mention (RACON_TPU_AUTOSCALE_*) and
    # matches by prefix.
    tok_re = re.compile(r"RACON_TPU_[A-Z0-9_]*")
    for doc_name, text in sorted(docs.items()):
        for i, ln in enumerate(text.splitlines(), 1):
            for tok in tok_re.findall(ln):
                if tok in registry:
                    continue
                if tok.endswith("_") and any(
                        n.startswith(tok) for n in registry):
                    continue
                yield Finding(
                    "ENV005", "error",
                    ("README.md" if doc_name == "README.md"
                     else f"docs/{doc_name}"), i,
                    f"documented gate {tok} is not declared in "
                    f"racon_tpu/utils/envspec.py")


# ============================================================ fault-site

def _iter_fault_sites(tree: ast.Module) -> \
        Iterator[Tuple[int, str, bool]]:
    """(lineno, site-pattern, is_prefix) for literals handed to
    maybe_fault/maybe_torn and retry ``call`` sites."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        fname = _func_name(node)
        hook = fname in ("maybe_fault", "maybe_torn")
        retry = fname in ("retry_call",) or (
            fname == "call" and isinstance(node.func, ast.Attribute)
            and _unparse(node.func.value).split(".")[-1] in
            ("retry", "_retry"))
        if not (hook or retry):
            continue
        arg = node.args[0]
        if isinstance(arg, ast.Constant) and \
                isinstance(arg.value, str):
            if retry and "/" not in arg.value:
                continue  # retry.call with a non-site label
            yield node.lineno, arg.value, False
        elif isinstance(arg, ast.JoinedStr):
            key = _str_key(arg) or ""
            prefix = key.split("*", 1)[0]
            yield node.lineno, prefix, True


def check_fault_site(ctx: Context) -> Iterator[Finding]:
    sites = set(ctx.fault_sites())
    prefixes = tuple(ctx.fault_prefixes())

    for path in ctx.scoped("racon_tpu/"):
        rel = ctx.rel(path)
        if rel == "racon_tpu/resilience/faults.py":
            continue
        tree = ctx.tree(path)
        if tree is None:
            continue
        for lineno, site, is_prefix in _iter_fault_sites(tree):
            if ctx.pragma(path, lineno, "fault-site-ok"):
                continue
            if is_prefix:
                ok = any(site.startswith(p) for p in prefixes)
            else:
                ok = site in sites or \
                    any(site.startswith(p) for p in prefixes)
            if not ok:
                yield Finding(
                    "FLT001", "error", rel, lineno,
                    f"fault site {site!r} is not declared in "
                    f"racon_tpu/resilience/faults.py SITES")

    if not ctx.full:
        return

    # FLT002: every declared site exercised by a test or smoke script.
    import os
    corpus = []
    for top in ("tests", "scripts"):
        base = os.path.join(ctx.root, top)
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__",)]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    try:
                        with open(os.path.join(dirpath, fn), "r",
                                  encoding="utf-8") as fh:
                            corpus.append(fh.read())
                    except OSError:
                        pass
    blob = "\n".join(corpus)
    faults_rel = "racon_tpu/resilience/faults.py"
    faults_src = ""
    for f in ctx.files:
        if ctx.rel(f) == faults_rel:
            faults_src = ctx.source(f)

    def site_line(site: str) -> int:
        for i, ln in enumerate(faults_src.splitlines(), 1):
            if f'"{site}"' in ln:
                return i
        return 1

    for site in sorted(set(ctx.fault_sites()) | set(prefixes)):
        if site not in blob:
            yield Finding(
                "FLT002", "error", faults_rel, site_line(site),
                f"declared fault site {site!r} is exercised by no "
                f"test or smoke script")


# ======================================================= metrics-contract

_KEY_RE = re.compile(r"^[a-z_][a-z0-9_*]*$")


def _iter_metric_keys(ctx: Context, path: str) -> \
        Iterator[Tuple[int, str]]:
    tree = ctx.tree(path)
    if tree is None:
        return
    in_metrics = ctx.rel(path) == "racon_tpu/obs/metrics.py"
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("inc", "set", "max") and node.args:
            for key in _str_keys(node.args[0]):
                if _KEY_RE.match(key):
                    yield node.lineno, key
        # The reg.apply(mutator) convention in obs/metrics.py: the
        # mutator's dict parameter is named ``v`` and its subscript
        # stores are recorded keys (docs/ANALYSIS.md).
        elif in_metrics and isinstance(node,
                                       (ast.Assign, ast.AugAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for tgt in targets:
                if isinstance(tgt, ast.Subscript) and \
                        isinstance(tgt.value, ast.Name) and \
                        tgt.value.id == "v":
                    for key in _str_keys(tgt.slice):
                        if _KEY_RE.match(key):
                            yield node.lineno, key


def _key_matches(key: str, pattern: str) -> bool:
    """``pipe_stage_*_items`` covers ``pipe_stage_encode_items`` and
    the statically-extracted ``pipe_stage_*_items`` itself (dynamic
    f-string segments become ``*`` on both sides; concretize the key's
    stars before matching)."""
    return fnmatch.fnmatchcase(key.replace("*", "x"), pattern)


def check_metrics_contract(ctx: Context) -> Iterator[Finding]:
    specs = ctx.metric_specs()
    patterns = [s[0] for s in specs]

    for path in ctx.scoped("racon_tpu/"):
        rel = ctx.rel(path)
        for lineno, key in _iter_metric_keys(ctx, path):
            if key.startswith("_"):
                continue  # internal, excluded from snapshots
            if ctx.pragma(path, lineno, "metric-ok"):
                continue
            if not any(_key_matches(key, p) for p in patterns):
                yield Finding(
                    "MET001", "error", rel, lineno,
                    f"metric key {key!r} matches no METRIC_SPECS row "
                    f"in racon_tpu/obs/metrics.py")

    if not ctx.full:
        return

    metrics_rel = "racon_tpu/obs/metrics.py"
    metrics_src = ""
    corpus_blob = []
    for f in ctx.scoped("racon_tpu/"):
        if ctx.rel(f) == metrics_rel:
            metrics_src = ctx.source(f)
        corpus_blob.append(ctx.source(f))
    blob = "\n".join(corpus_blob)
    obs_doc = ctx.doc_text("OBSERVABILITY.md")

    def spec_line(pattern: str) -> int:
        for i, ln in enumerate(metrics_src.splitlines(), 1):
            if f'("{pattern}"' in ln:
                return i
        return 1

    from racon_tpu.obs import metrics as metrics_mod
    for pattern, kind, doc_token in specs:
        token = pattern.split("*", 1)[0]
        # MET002: spec with no producer anywhere in racon_tpu/.
        if token and token not in blob:
            yield Finding(
                "MET002", "error", metrics_rel, spec_line(pattern),
                f"METRIC_SPECS row {pattern!r} has no producer in "
                f"racon_tpu/ (dead spec)")
        # MET003: spec with no docs row.
        if doc_token not in obs_doc:
            yield Finding(
                "MET003", "error", metrics_rel, spec_line(pattern),
                f"METRIC_SPECS row {pattern!r}: doc token "
                f"{doc_token!r} not found in docs/OBSERVABILITY.md")
        # MET004: declared merge kind must agree with merge_kind(),
        # i.e. with what fleet.aggregate will actually do.
        concrete = pattern.replace("*", "x")
        actual = metrics_mod.merge_kind(concrete)
        if actual != kind:
            yield Finding(
                "MET004", "error", metrics_rel, spec_line(pattern),
                f"METRIC_SPECS row {pattern!r} declares merge kind "
                f"{kind!r} but merge_kind({concrete!r}) = {actual!r}")


# =========================================================== span-schema

_TRACERY = re.compile(r"(^|\.)get_tracer\(\)$")


def _iter_span_emits(tree: ast.Module) -> \
        Iterator[Tuple[int, str, Optional[set]]]:
    """(lineno, kind, kwarg-names or None-when-splatted) for every
    Tracer .span/.point/.emit call. Receiver heuristic: a bare
    ``tracer``/``tr`` name or a ``get_tracer()`` call — io indexes and
    other .span APIs don't match."""
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not isinstance(f, ast.Attribute) or \
                f.attr not in ("span", "point", "emit"):
            continue
        recv = _unparse(f.value)
        if not (recv in ("tracer", "tr") or _TRACERY.search(recv)):
            continue
        if not node.args:
            continue
        kind = _str_key(node.args[0])
        if kind is None or "*" in kind:
            continue
        if any(kw.arg is None for kw in node.keywords):
            kwargs: Optional[set] = None       # **splat: not static
        else:
            kwargs = {kw.arg for kw in node.keywords}
        yield node.lineno, kind, kwargs


def check_span_schema(ctx: Context) -> Iterator[Finding]:
    required = ctx.span_required()
    free = set(ctx.span_attr_free())
    legal = set(required) | free
    emitted: Dict[str, str] = {}

    for path in ctx.scoped("racon_tpu/", "bench.py"):
        rel = ctx.rel(path)
        tree = ctx.tree(path)
        if tree is None:
            continue
        for lineno, kind, kwargs in _iter_span_emits(tree):
            emitted.setdefault(kind, f"{rel}:{lineno}")
            if ctx.pragma(path, lineno, "span-ok"):
                continue
            if kind not in legal:
                yield Finding(
                    "SPAN001", "error", rel, lineno,
                    f"span kind {kind!r} is not in "
                    f"scripts/obs_report.py KIND_REQUIRED_ATTRS or "
                    f"ATTR_FREE_KINDS")
                continue
            need = required.get(kind, ())
            if need and kwargs is not None:
                missing = [a for a in need if a not in kwargs]
                if missing:
                    yield Finding(
                        "SPAN002", "error", rel, lineno,
                        f"span kind {kind!r} emitted without required "
                        f"attrs {missing} (obs_report.py validator "
                        f"will reject the trace)")

    if not ctx.full:
        return

    # SPAN003: validator kinds nobody emits (dead schema).
    report_rel = "scripts/obs_report.py"
    for kind in sorted(legal):
        if kind not in emitted:
            yield Finding(
                "SPAN003", "error", report_rel, 1,
                f"span kind {kind!r} is validated in obs_report.py "
                f"but emitted nowhere")


# ========================================================== atomic-write

def check_atomic_write(ctx: Context) -> Iterator[Finding]:
    for path in ctx.scoped("racon_tpu/cache/", "racon_tpu/distributed/",
                           "racon_tpu/gateway/",
                           "racon_tpu/resilience/", "racon_tpu/obs/"):
        rel = ctx.rel(path)
        if rel == "racon_tpu/utils/atomicio.py":
            continue
        tree = ctx.tree(path)
        if tree is None:
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and
                    isinstance(node.func, ast.Name) and
                    node.func.id == "open"):
                continue
            mode = None
            if len(node.args) >= 2:
                mode = _str_key(node.args[1])
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = _str_key(kw.value)
            if mode is None or not any(c in mode for c in "wx"):
                continue
            if ctx.pragma(path, node.lineno, "atomic-ok"):
                continue
            yield Finding(
                "ATM001", "error", rel, node.lineno,
                f"bare open(..., {mode!r}) under a durable-output "
                f"tree: use racon_tpu.utils.atomicio "
                f"(atomic_write_bytes / atomic_writer / "
                f"publish_exclusive)")


# ======================================================== lock-discipline

_GUARD_RE = re.compile(
    r"self\.(\w+)\b[^#]*#\s*guarded-by:\s*(\w+)")
_MUTATORS = ("append", "add", "extend", "insert", "remove", "pop",
             "popitem", "clear", "update", "setdefault", "discard")


class _LockWalk(ast.NodeVisitor):
    def __init__(self, guarded: Dict[str, str]):
        self.guarded = guarded
        self.held: List[str] = []
        self.hits: List[Tuple[int, str, str]] = []

    def visit_With(self, node: ast.With):
        names = []
        for item in node.items:
            src = _unparse(item.context_expr)
            for attr, lock in self.guarded.items():
                if src in (f"self.{lock}", f"self.{lock}:"):
                    names.append(lock)
        self.held.extend(names)
        self.generic_visit(node)
        for _ in names:
            self.held.pop()

    def _attr_of(self, node: ast.AST) -> Optional[str]:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and node.attr in self.guarded:
            return node.attr
        return None

    def _flag(self, lineno: int, attr: str):
        lock = self.guarded[attr]
        if lock not in self.held:
            self.hits.append((lineno, attr, lock))

    def visit_Assign(self, node: ast.Assign):
        for tgt in node.targets:
            a = self._attr_of(tgt)
            if a:
                self._flag(node.lineno, a)
            if isinstance(tgt, ast.Subscript):
                a = self._attr_of(tgt.value)
                if a:
                    self._flag(node.lineno, a)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign):
        a = self._attr_of(node.target)
        if a:
            self._flag(node.lineno, a)
        if isinstance(node.target, ast.Subscript):
            a = self._attr_of(node.target.value)
            if a:
                self._flag(node.lineno, a)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call):
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            a = self._attr_of(f.value)
            if a:
                self._flag(node.lineno, a)
        self.generic_visit(node)


def check_lock_discipline(ctx: Context) -> Iterator[Finding]:
    for path in ctx.scoped("racon_tpu/"):
        rel = ctx.rel(path)
        tree = ctx.tree(path)
        if tree is None:
            continue
        src_lines = ctx.lines(path)
        for cls in ast.walk(tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            end = getattr(cls, "end_lineno", None) or len(src_lines)
            guarded: Dict[str, str] = {}
            for ln in src_lines[cls.lineno - 1:end]:
                m = _GUARD_RE.search(ln)
                if m:
                    guarded[m.group(1)] = m.group(2)
            if not guarded:
                continue
            for fn in cls.body:
                if not isinstance(fn, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    continue
                if fn.name == "__init__":
                    continue  # construction precedes sharing
                if ctx.pragma(path, fn.lineno, "unlocked-ok"):
                    continue
                walker = _LockWalk(guarded)
                walker.visit(fn)
                for lineno, attr, lock in walker.hits:
                    if ctx.pragma(path, lineno, "unlocked-ok"):
                        continue
                    yield Finding(
                        "LCK001", "error", rel, lineno,
                        f"{cls.name}.{attr} is declared guarded-by "
                        f"{lock} but is mutated outside 'with "
                        f"self.{lock}'")


# =========================================================== choke-point

def check_choke_point(ctx: Context) -> Iterator[Finding]:
    for path in ctx.scoped("racon_tpu/"):
        rel = ctx.rel(path)
        tree = ctx.tree(path)
        if tree is None:
            continue
        # Function names handed to retry.call / watchdog guard in this
        # module: device_put inside those closures is envelope-covered.
        wrapped = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Call) and _func_name(node) in \
                    ("retry_call", "call", "guard"):
                for arg in node.args:
                    if isinstance(arg, ast.Name):
                        wrapped.add(arg.id)
        # Walk with the enclosing-function stack.
        stack: List[str] = []

        def walk(node):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    yield from walk(child)
                stack.pop()
                return
            if isinstance(node, ast.Call) and \
                    isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "device_put" and \
                    _unparse(node.func.value) == "jax":
                if not any(n in wrapped for n in stack) and \
                        not ctx.pragma(path, node.lineno,
                                       "unguarded-ok"):
                    yield Finding(
                        "CHK001", "error", rel, node.lineno,
                        "jax.device_put outside a resilience.retry"
                        ".call / watchdog-guarded closure: a wedged "
                        "transfer here hangs the worker with no "
                        "deadline")
            for child in ast.iter_child_nodes(node):
                yield from walk(child)

        yield from walk(tree)


# ============================================================= histogram

def _iter_record_hist(tree: ast.Module) -> Iterator[Tuple[int, str]]:
    """(lineno, family) for every ``record_hist(<literal>, ...)`` call
    whose family name is statically resolvable."""
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and \
                _func_name(node) == "record_hist" and node.args:
            name = _str_key(node.args[0])
            if name is not None and "*" not in name:
                yield node.lineno, name


def check_histogram(ctx: Context) -> Iterator[Finding]:
    buckets = ctx.hist_buckets()

    # HIS001 (per-file direction): every recorded family has declared
    # bucket bounds — record_hist raises at runtime otherwise, and the
    # linter catches the site before any test exercises it.
    for path in ctx.scoped("racon_tpu/", "scripts/", "bench.py"):
        rel = ctx.rel(path)
        tree = ctx.tree(path)
        if tree is None:
            continue
        for lineno, name in _iter_record_hist(tree):
            if ctx.pragma(path, lineno, "hist-ok"):
                continue
            if name not in buckets:
                yield Finding(
                    "HIS001", "error", rel, lineno,
                    f"record_hist family {name!r} has no bucket bounds "
                    f"declared in racon_tpu/obs/metrics.py "
                    f"HIST_BUCKETS")

    if not ctx.full:
        return

    metrics_rel = "racon_tpu/obs/metrics.py"
    metrics_src = ""
    export_src = ""
    corpus = []
    for f in ctx.scoped("racon_tpu/", "bench.py"):
        rel = ctx.rel(f)
        if rel == metrics_rel:
            metrics_src = ctx.source(f)
        elif rel == "racon_tpu/obs/export.py":
            export_src = ctx.source(f)
        corpus.append(ctx.source(f))
    blob = "\n".join(corpus)

    def bucket_line(name: str) -> int:
        for i, ln in enumerate(metrics_src.splitlines(), 1):
            if f'"{name}"' in ln:
                return i
        return 1

    # Registry directions: buckets <-> METRIC_SPECS 'hist' rows agree
    # both ways, and every declared family has a producer somewhere.
    hist_specs = {s[0] for s in ctx.metric_specs() if s[1] == "hist"}
    for name in sorted(buckets):
        if name not in hist_specs:
            yield Finding(
                "HIS001", "error", metrics_rel, bucket_line(name),
                f"HIST_BUCKETS family {name!r} has no METRIC_SPECS "
                f"row with merge kind 'hist' (fleet aggregation would "
                f"not fold its buckets)")
        if f'record_hist("{name}"' not in blob:
            yield Finding(
                "HIS001", "error", metrics_rel, bucket_line(name),
                f"HIST_BUCKETS family {name!r} is recorded nowhere "
                f"(no record_hist call in racon_tpu/ or bench.py)")
    for pattern in sorted(hist_specs):
        if pattern not in buckets:
            yield Finding(
                "HIS001", "error", metrics_rel, bucket_line(pattern),
                f"METRIC_SPECS row {pattern!r} declares merge kind "
                f"'hist' but HIST_BUCKETS has no bounds for it")
    if buckets and ('le="' not in export_src or
                    "_bucket" not in export_src):
        yield Finding(
            "HIS001", "error", "racon_tpu/obs/export.py", 1,
            "histogram families are declared but obs/export.py has no "
            "OpenMetrics histogram rendering (_bucket samples with le "
            "labels)")


# =========================================================== determinism

_WALLCLOCK = ("time.time", "time.time_ns", "datetime.now",
              "datetime.datetime.now", "datetime.utcnow",
              "datetime.datetime.utcnow")
_DET_FILES = ("racon_tpu/distributed/ledger.py",
              "racon_tpu/resilience/checkpoint.py")
_DET_FN = re.compile(r"fingerprint|nonce")
_BLESSED_FN = ("_now",)


def check_determinism(ctx: Context) -> Iterator[Finding]:
    for path in ctx.scoped("racon_tpu/"):
        rel = ctx.rel(path)
        whole_file = rel in _DET_FILES or not ctx.full
        tree = ctx.tree(path)
        if tree is None:
            continue
        stack: List[str] = []

        def in_scope() -> bool:
            if any(fn in _BLESSED_FN for fn in stack):
                return False
            if whole_file and stack:
                return True
            return any(_DET_FN.search(fn) for fn in stack)

        def offender(node: ast.Call) -> Optional[str]:
            src = _unparse(node.func)
            if src in _WALLCLOCK:
                return src
            head = src.split(".", 1)[0]
            if head in ("random", "uuid"):
                return src
            return None

        def walk(node):
            if isinstance(node, (ast.FunctionDef,
                                 ast.AsyncFunctionDef)):
                stack.append(node.name)
                for child in ast.iter_child_nodes(node):
                    yield from walk(child)
                stack.pop()
                return
            if isinstance(node, ast.Call) and in_scope():
                off = offender(node)
                if off and not ctx.pragma(path, node.lineno,
                                          "wallclock-ok"):
                    yield Finding(
                        "DET001", "error", rel, node.lineno,
                        f"{off} in a fingerprint/ledger/checkpoint "
                        f"path: identity and lease state must be "
                        f"deterministic (use the _now shim or "
                        f"os.urandom)")
            for child in ast.iter_child_nodes(node):
                yield from walk(child)

        yield from walk(tree)


# ================================================================ the set

ALL_RULES = (
    Rule("env-contract",
         ("ENV001", "ENV002", "ENV003", "ENV004", "ENV005"), "error",
         "every RACON_TPU_* read routes through utils/envspec.py and "
         "code, registry, and docs agree in both directions",
         check_env_contract),
    Rule("fault-site", ("FLT001", "FLT002"), "error",
         "fault-hook literals match faults.SITES and every declared "
         "site is exercised by a test or smoke script",
         check_fault_site),
    Rule("metrics-contract",
         ("MET001", "MET002", "MET003", "MET004"), "error",
         "recorded registry keys match METRIC_SPECS; specs have a "
         "producer, a docs row, and the correct fleet merge kind",
         check_metrics_contract),
    Rule("span-schema", ("SPAN001", "SPAN002", "SPAN003"), "error",
         "Tracer emissions and the obs_report.py validators agree on "
         "span kinds and required attrs in both directions",
         check_span_schema),
    Rule("atomic-write", ("ATM001",), "error",
         "no bare open(w) under ledger/checkpoint/obs trees outside "
         "utils/atomicio.py", check_atomic_write),
    Rule("lock-discipline", ("LCK001",), "error",
         "# guarded-by: attrs are only mutated under their lock",
         check_lock_discipline),
    Rule("choke-point", ("CHK001",), "error",
         "jax.device_put sites sit inside retry/watchdog-guarded "
         "closures", check_choke_point),
    Rule("determinism", ("DET001",), "error",
         "no wallclock/PRNG in fingerprint, ledger, or checkpoint "
         "paths outside the blessed shims", check_determinism),
    Rule("histogram", ("HIS001",), "error",
         "record_hist families, HIST_BUCKETS bounds, METRIC_SPECS "
         "'hist' rows, and the OpenMetrics exporter agree in every "
         "direction", check_histogram),
)
