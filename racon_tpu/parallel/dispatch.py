"""Multi-chip dispatch: the thread_pool replacement, TPU-style.

The reference's only parallelism is a shared-memory thread pool over
embarrassingly-parallel tasks (reference: src/polisher.cpp:143-155,
341-364, 457-469). The TPU equivalents here:

- **dp** (data parallel): alignment jobs (window, layer) are the batch
  dimension, sharded across chips with a ``jax.sharding.Mesh`` +
  ``NamedSharding``. Zero collectives — jobs are independent, exactly like
  the reference's per-window futures; XLA partitions the vmapped DP scan
  with no communication.
- **sp** (sequence parallel): for windows longer than one chip's liking,
  the NW target axis is sharded over chips. Each DP row step then needs a
  one-column halo from the left neighbour (``ppermute``) and a global
  max-prefix for the gap chain (``all_gather`` of block maxima) — the
  long-context decomposition over ICI.
- **hosts / DCN**: disjoint target chunks via racon_tpu.tools (rampler
  split), no communication, matching the reference wrapper's sequential
  chunking (scripts/racon_wrapper.py:125-135).
"""

from __future__ import annotations

import functools
import time
from typing import Optional, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from racon_tpu.obs.metrics import record_d2h, record_h2d
from racon_tpu.resilience.retry import call as retry_call
from racon_tpu.utils.jaxcompat import pvary, shard_map


def make_mesh(n_devices: Optional[int] = None,
              axes: Tuple[str, ...] = ("dp",),
              shape: Optional[Tuple[int, ...]] = None) -> Mesh:
    """Build a device mesh over the first n available devices.

    With one axis, all devices go to "dp". With two axes and no explicit
    shape, devices split evenly with "sp" getting the smaller factor.
    """
    devs = jax.devices()
    n = n_devices if n_devices is not None else len(devs)
    if n > len(devs):
        raise ValueError(
            f"[racon_tpu::parallel] error: {n} devices requested, "
            f"{len(devs)} available")
    devs = devs[:n]
    if shape is None:
        if len(axes) == 1:
            shape = (n,)
        elif len(axes) == 2:
            sp = 2 if n % 2 == 0 and n >= 2 else 1
            shape = (n // sp, sp)
        else:
            raise ValueError("unsupported axes")
    return Mesh(np.asarray(devs).reshape(shape), axes)


def pad_batch(n: int, mult: int) -> int:
    return ((n + mult - 1) // mult) * mult


def shard_align_inputs(mesh: Mesh, q: np.ndarray, t: np.ndarray,
                       lq: np.ndarray, lt: np.ndarray, axis: str = "dp"):
    """Pad the batch to the dp size and place inputs sharded over chips.

    Padded rows get length-1 dummies so traceback terminates instantly.
    """
    ndp = mesh.shape[axis]
    B = q.shape[0]
    Bp = pad_batch(B, ndp)
    if Bp != B:
        q = np.concatenate([q, np.zeros((Bp - B, q.shape[1]), q.dtype)])
        t = np.concatenate([t, np.zeros((Bp - B, t.shape[1]), t.dtype)])
        lq = np.concatenate([lq, np.ones(Bp - B, lq.dtype)])
        lt = np.concatenate([lt, np.ones(Bp - B, lt.dtype)])
    row = NamedSharding(mesh, P(axis, None))
    vec = NamedSharding(mesh, P(axis))

    def _put():
        t0 = time.perf_counter()
        out = (jax.device_put(jnp.asarray(q), row),
               jax.device_put(jnp.asarray(t), row),
               jax.device_put(jnp.asarray(lq), vec),
               jax.device_put(jnp.asarray(lt), vec), B)
        record_h2d(q.nbytes + t.nbytes + lq.nbytes + lt.nbytes,
                   time.perf_counter() - t0, name="h2d/align")
        return out

    from racon_tpu.ops.budget import transfer_deadline_s
    return retry_call(
        "h2d/align", _put,
        deadline_s=transfer_deadline_s(
            q.nbytes + t.nbytes + lq.nbytes + lt.nbytes, "h2d"))


def nw_align_batch_sharded(mesh: Mesh, q: np.ndarray, t: np.ndarray,
                           lq: np.ndarray, lt: np.ndarray, *, match: int,
                           mismatch: int, gap: int):
    """Data-parallel batched NW: jobs sharded over the mesh's dp axis.

    Returns host numpy (ops, n_ops) trimmed back to the true batch size.
    """
    from racon_tpu.ops.align import nw_align_batch
    qd, td, lqd, ltd, B = shard_align_inputs(mesh, q, t, lq, lt)
    with mesh:
        ops, n = nw_align_batch(qd, td, lqd, ltd, match=match,
                                mismatch=mismatch, gap=gap)

    def _pull():
        t0 = time.perf_counter()
        ops_h, n_h = np.asarray(ops), np.asarray(n)
        record_d2h(ops_h.nbytes + n_h.nbytes, time.perf_counter() - t0,
                   name="d2h/align")
        return ops_h, n_h

    from racon_tpu.ops.budget import transfer_deadline_s
    # jax arrays expose shape/dtype-derived nbytes without a sync.
    ops_h, n_h = retry_call(
        "d2h/align", _pull,
        deadline_s=transfer_deadline_s(ops.nbytes + n.nbytes, "d2h"))
    return ops_h[:B], n_h[:B]


def _sp_forward(sp, nsp, jglob, qv, tv, a, *, match, mismatch, gap,
                emit_dirs):
    """Shared sequence-parallel NW forward scan over query rows.

    One target shard's view: local cummax + a cross-chip prefix of block
    maxima close the global gap chain, a one-column ppermute halo feeds
    the next row's diagonal, and rows freeze past the true query length
    so the final carry holds row lq. With ``emit_dirs`` the scan also
    yields per-row direction labels (DIAG > UP > LEFT, the rule every
    other kernel uses); _sp_scores_jit and _sp_align_jit both ride this
    single implementation so scores and tracebacks cannot desynchronize.

    Returns (final_row, dirs-or-None).
    """
    from racon_tpu.ops.cigar import DIAG, UP, LEFT

    row0 = jglob * gap
    halo0 = (sp * jglob.shape[0]) * gap   # H[0, first_j - 1]

    def step(carry, inp):
        prev, halo = carry
        i, qi = inp
        sub = jnp.where(tv == qi, match, mismatch).astype(jnp.int32)
        prev_shift = jnp.concatenate([halo[None], prev[:-1]])
        diag = prev_shift + sub
        up = prev + gap
        tmp = jnp.maximum(diag, up)
        # Global gap-chain closure: local cummax + cross-chip prefix of
        # block maxima + the j=0 boundary (i*gap).
        f = tmp - jglob * gap
        lmax = jax.lax.cummax(f)
        blockmax = jax.lax.all_gather(lmax[-1], "sp")
        idx = jnp.arange(nsp)
        before = jnp.where(idx < sp, blockmax,
                           jnp.iinfo(jnp.int32).min // 2)
        prefix = jnp.maximum(jnp.max(before), i * gap)
        h = jnp.maximum(lmax, prefix) + jglob * gap
        d = (jnp.where(h == diag, DIAG,
                       jnp.where(h == up, UP, LEFT)).astype(jnp.uint8)
             if emit_dirs else None)
        # Row frozen past the true query length so the final carry
        # holds row lq.
        h = jnp.where(i <= a, h, prev)
        # Halo for the next row: my last column -> right neighbour.
        nh = jax.lax.ppermute(
            h[-1], "sp", [(k, k + 1) for k in range(nsp - 1)])
        nh = jnp.where(sp == 0, i * gap, nh)
        nh = jnp.where(i <= a, nh, halo)
        return (h, nh), d

    ii = jnp.arange(1, qv.shape[0] + 1, dtype=jnp.int32)
    # The scan body outputs are dp-varying (they read qv/tv), so the
    # initial carry must carry the same varying-axes type.
    carry0 = (pvary(row0, ("dp",)),
              pvary(jnp.int32(halo0), ("dp",)))
    (final, _), dirs = jax.lax.scan(step, carry0,
                                    (ii, qv.astype(jnp.int32)))
    return final, dirs


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "mesh"))
def _sp_scores_jit(q, t, lq, lt, *, match, mismatch, gap, mesh):
    nsp = mesh.shape["sp"]
    Lt = t.shape[1]
    assert Lt % nsp == 0

    def block(qb, tb, lqb, ltb):
        # qb [b, Lq] replicated over sp; tb [b, Lt/nsp] — my target shard.
        sp = jax.lax.axis_index("sp")
        Ltl = tb.shape[1]
        jglob = sp * Ltl + jnp.arange(1, Ltl + 1, dtype=jnp.int32)

        def one(qv, tv, a, bcol):
            final, _ = _sp_forward(sp, nsp, jglob, qv, tv, a, match=match,
                                   mismatch=mismatch, gap=gap,
                                   emit_dirs=False)
            # Score H[lq, lt] lives on the chip owning global column lt.
            mine = jnp.sum(jnp.where(jglob == bcol, final, 0))
            return jax.lax.psum(mine, "sp")

        return jax.vmap(one)(qb, tb, lqb, ltb)

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P("dp", None), P("dp", "sp"), P("dp"), P("dp")),
        out_specs=P("dp"), check_vma=False)
    return fn(q, t, lq, lt)


def sp_nw_scores(mesh: Mesh, q: np.ndarray, t: np.ndarray, lq: np.ndarray,
                 lt: np.ndarray, *, match: int, mismatch: int, gap: int):
    """Sequence-parallel NW scores: target axis sharded over the "sp"
    mesh axis, batch over "dp". Semantically identical to
    racon_tpu.ops.align.nw_scores."""
    qd, td, lqd, ltd, B = shard_align_inputs(mesh, q, t, lq, lt)
    out = _sp_scores_jit(qd, td, lqd, ltd, match=match, mismatch=mismatch,
                         gap=gap, mesh=mesh)

    def _pull():
        t0 = time.perf_counter()
        out_h = np.asarray(out)
        record_d2h(out_h.nbytes, time.perf_counter() - t0, name="d2h/sp")
        return out_h

    from racon_tpu.ops.budget import transfer_deadline_s
    return retry_call(
        "d2h/sp", _pull,
        deadline_s=transfer_deadline_s(out.nbytes, "d2h"))[:B]


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "mesh"))
def _sp_align_jit(q, t, lq, lt, *, match, mismatch, gap, mesh):
    """Sequence-parallel NW *with traceback*: target axis sharded over
    "sp", batch over "dp".

    The forward pass is the sp scan of _sp_scores_jit, additionally
    emitting per-row direction labels into each shard's local dirs
    [Lq, Lt/nsp] (diag/up come from local state, LEFT covers the
    prefix-max gap chain regardless of which shard supplied it). The
    traceback is a *replicated* walk over all sp shards: every step the
    owning shard gathers its direction bit and one psum broadcasts it
    (tiny — one int per job per step over ICI), so the path crosses
    shard boundaries with no host round-trips and no dirs gather.
    """
    from racon_tpu.ops.align import PAD_OP
    from racon_tpu.ops.cigar import DIAG, UP, LEFT

    nsp = mesh.shape["sp"]
    Lq = q.shape[1]
    Lt = t.shape[1]
    assert Lt % nsp == 0
    steps = Lq + Lt

    def block(qb, tb, lqb, ltb):
        sp = jax.lax.axis_index("sp")
        Ltl = tb.shape[1]
        jglob = sp * Ltl + jnp.arange(1, Ltl + 1, dtype=jnp.int32)

        def one(qv, tv, a, bcol):
            _, dirs = _sp_forward(sp, nsp, jglob, qv, tv, a, match=match,
                                  mismatch=mismatch, gap=gap,
                                  emit_dirs=True)               # [Lq, Ltl]

            # Replicated cross-shard walk from (lq, lt) to (0, 0).
            d1 = dirs.reshape(-1)
            base = sp * Ltl

            def tstep(state, _):
                i, j = state
                done = (i == 0) & (j == 0)
                loc = j - 1 - base
                own = (i >= 1) & (j >= 1) & (loc >= 0) & (loc < Ltl)
                idx = jnp.clip((i - 1) * Ltl + loc, 0, Lq * Ltl - 1)
                dv = jnp.where(own, jnp.take(d1, idx).astype(jnp.int32), 0)
                dv = jax.lax.psum(dv, "sp")
                d = jnp.where(done, PAD_OP,
                              jnp.where(i == 0, LEFT,
                                        jnp.where(j == 0, UP,
                                                  dv))).astype(jnp.uint8)
                i = i - jnp.where((d == DIAG) | (d == UP), 1, 0)
                j = j - jnp.where((d == DIAG) | (d == LEFT), 1, 0)
                return (i, j), d

            (_, _), rev = jax.lax.scan(
                tstep, (a.astype(jnp.int32), bcol.astype(jnp.int32)),
                None, length=steps)
            return rev

        return jax.vmap(one)(qb, tb, lqb, ltb)

    fn = shard_map(
        block, mesh=mesh,
        in_specs=(P("dp", None), P("dp", "sp"), P("dp"), P("dp")),
        out_specs=P("dp", None), check_vma=False)
    rev = fn(q, t, lq, lt)
    n = jnp.sum(rev != PAD_OP, axis=1).astype(jnp.int32)
    return jnp.flip(rev, axis=1), n


def sp_nw_align(mesh: Mesh, q: np.ndarray, t: np.ndarray, lq: np.ndarray,
                lt: np.ndarray, *, match: int, mismatch: int, gap: int):
    """Sequence-parallel batched NW with full traceback.

    Contract matches racon_tpu.ops.align.nw_align_batch: returns host
    (ops uint8[B, Lq+Lt] right-aligned, n_ops int32[B]).

    When to use (the long-window routing bound): a single chip's device
    engine handles a window as long as its dirs tensor fits the int32
    flat-index budget — at the minimum 128-job chunk that is
    Lq*LA <= ~12.5e6, i.e. ~3.5 kb x 3.5 kb windows; the host path
    (adaptive-band native aligner, unbounded) covers anything beyond on
    one host. This sp path is the scale-out primitive past both: the
    target axis shards over "sp" chips so per-chip dirs memory drops to
    Lq*Lt/nsp, covering windows ~nsp x longer at the same budget. The
    per-step psum walk costs one tiny collective per op (~2 us on ICI;
    latency-bound, so reserve sp for windows that genuinely exceed a
    chip).
    """
    qd, td, lqd, ltd, B = shard_align_inputs(mesh, q, t, lq, lt)
    nsp = mesh.shape["sp"]
    Lt = t.shape[1]
    if Lt % nsp:
        pad = (nsp - Lt % nsp)
        td = jnp.concatenate(
            [td, jnp.zeros((td.shape[0], pad), td.dtype)], axis=1)
    ops, n = _sp_align_jit(qd, td, lqd, ltd, match=match,
                           mismatch=mismatch, gap=gap, mesh=mesh)
    W = ops.shape[1]

    def _pull():
        t0 = time.perf_counter()
        ops_h = np.asarray(ops)
        n_h = np.asarray(n)
        record_d2h(ops_h.nbytes + n_h.nbytes, time.perf_counter() - t0,
                   name="d2h/sp")
        return ops_h, n_h

    from racon_tpu.ops.budget import transfer_deadline_s
    ops_h, n_h = retry_call(
        "d2h/sp", _pull,
        deadline_s=transfer_deadline_s(ops.nbytes + n.nbytes, "d2h"))
    ops_h = ops_h[:B]
    n_h = n_h[:B]
    # Re-right-align to Lq+Lt width if target padding widened the walk.
    want = q.shape[1] + Lt
    if W != want:
        from racon_tpu.ops.align import PAD_OP
        out = np.full((B, want), PAD_OP, np.uint8)
        for b in range(B):
            out[b, want - n_h[b]:] = ops_h[b, W - n_h[b]:]
        ops_h = out
    return ops_h, n_h
