"""Streaming record emission for assembly-scale result sets.

A kC job emits tens of contigs; holding them as a list of Python bytes
objects (server/jobs.Job.chunks) is free. An ava job emits one record
PER READ — millions of small blobs whose object headers alone dwarf the
payload, pinned for the job's whole lifetime so ``/stream`` can replay
them. Two pieces fix that without changing any caller-visible byte:

- :class:`RecordSpool` — the Job result sink. Records accumulate
  in-memory until ``RACON_TPU_SERVE_SPOOL_MB`` worth of bytes, then
  the whole stream spills to one append-only scratch file
  (``result.spool`` in the job directory) and later records go
  straight to disk. ``read_all`` returns the identical concatenation
  either way, so ``/stream`` and the CAS never know which side of the
  threshold the job landed on.
- :func:`iter_fasta_records` — the streaming replacement for reading a
  merged ``out.fasta`` whole and splitting it in memory
  (``gateway/dispatch._split_fasta``): the fleet re-commit loop pulls
  one record at a time off the file, so a 10 GB merged output costs
  one record of memory, not two copies of the file.

The spool file is scratch, not durable state: it is rebuilt from the
job's checkpoint store on daemon restart (``jobs.rebuild_result``),
exactly like the in-memory chunk list it replaces.
"""

from __future__ import annotations

import os
import threading
from typing import Iterator, List, Optional

from racon_tpu.utils import envspec

ENV_SERVE_SPOOL = "RACON_TPU_SERVE_SPOOL_MB"
DEFAULT_SPOOL_MB = 8
SPOOL_FILE = "result.spool"


def spool_limit_bytes() -> int:
    """In-memory result bytes a job may hold before spilling. A
    non-positive or malformed value means "never spill" — the pre-spool
    behavior, and the right call for test rigs with no job directory."""
    raw = envspec.read(ENV_SERVE_SPOOL).strip()
    if not raw:
        return DEFAULT_SPOOL_MB << 20
    try:
        mb = int(raw)
    except ValueError:
        return 0
    return mb << 20 if mb > 0 else 0


class RecordSpool:
    """Bounded-memory, append-only byte stream with replay.

    Appends are cheap list appends until the in-memory total crosses
    the spill threshold; from then on every record goes straight to the
    scratch file. The stream is strictly append-ordered in both phases,
    so ``read_all`` is always the exact concatenation of every record
    ever appended — the invariant the daemon's ``/stream`` replay and
    the CAS key derivation both rest on. Thread-safe: the job runner
    appends while HTTP streamers read."""

    def __init__(self, directory: Optional[str] = None,
                 limit_bytes: Optional[int] = None):
        self._limit = spool_limit_bytes() if limit_bytes is None \
            else max(0, int(limit_bytes))
        self._path = os.path.join(directory, SPOOL_FILE) \
            if directory else None
        self._lock = threading.Lock()
        self._chunks: List[bytes] = []
        self._mem = 0
        self._total = 0
        self._file = None

    @property
    def spilled(self) -> bool:
        return self._file is not None

    @property
    def total_bytes(self) -> int:
        with self._lock:
            return self._total

    def append(self, blob: bytes) -> None:
        with self._lock:
            self._total += len(blob)
            if self._file is not None:
                self._file.write(blob)
                return
            self._chunks.append(blob)
            self._mem += len(blob)
            if (self._path is not None and self._limit > 0
                    and self._mem > self._limit):
                self._spill()

    def _spill(self) -> None:
        # Scratch, not durable state (no fsync, no atomic rename): a
        # crash loses nothing the checkpoint store can't rebuild.
        if os.path.exists(self._path):
            os.remove(self._path)
        fh = open(self._path, "ab")
        for chunk in self._chunks:
            fh.write(chunk)
        self._file = fh
        self._chunks = []
        self._mem = 0

    def read_all(self) -> bytes:
        """The full stream so far — identical bytes whether or not the
        spool has spilled."""
        with self._lock:
            if self._file is None:
                return b"".join(self._chunks)
            self._file.flush()
            with open(self._path, "rb") as fh:
                return fh.read()

    def reset(self) -> None:
        """Drop everything (restart rebuild repopulates from the
        checkpoint store)."""
        with self._lock:
            self._chunks = []
            self._mem = 0
            self._total = 0
            if self._file is not None:
                self._file.close()
                self._file = None
            if self._path is not None and os.path.exists(self._path):
                os.remove(self._path)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


def iter_fasta_records(path: str) -> Iterator[bytes]:
    """Stream per-record byte runs off a FASTA file, splitting at ``>``
    record starts — record-for-record identical to splitting the whole
    blob in memory for any ``\\n``-terminated FASTA (which the merge
    output is: it concatenates per-record emissions that each end in a
    newline). Holds one record at a time."""
    record: List[bytes] = []
    with open(path, "rb") as fh:
        for line in fh:
            if line.startswith(b">"):
                if record:
                    yield b"".join(record)
                record = [line]
            elif record:
                record.append(line)
        if record:
            yield b"".join(record)
