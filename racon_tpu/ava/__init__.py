"""Assembly-scale all-vs-all (ava) workload planning.

Racon's second mode (``-f``, fragment correction — the paper's kF
configuration) makes EVERY read a target: millions of short,
length-diverse targets per run instead of the kC regime's
tens-to-hundreds of large contigs. The rest of the system is shaped
for kC; this package holds the pieces that open the ava regime without
forking any execution path (docs/AVA.md):

- :mod:`racon_tpu.ava.partition` — length-weighted shard bounds over
  the ledger's published ``scan_sequence_index`` offsets, so 10M short
  reads shard by bytes of work, not by record count
  (``WorkLedger.open`` consults it whenever offsets are available);
- :mod:`racon_tpu.ava.planner` — greedy run-level shape buckets
  layered over the ops/budget.py tile tiers, publishing a compile
  count against ``RACON_TPU_AVA_COMPILE_BUDGET`` so read-length
  diversity can't explode compilation;
- :mod:`racon_tpu.ava.emit` — the streaming record spool the daemon's
  result path uses so millions of emitted records never materialize
  as millions of live Python objects;
- segment sizing for the v2 checkpoint manifest
  (resilience/checkpoint.py): :func:`seg_targets_for` below decides
  how many committed targets amortize into one run-length manifest
  record.

An ava job is still an ordinary :class:`~racon_tpu.server.engine`
JobSpec with ``fragment_correction=True`` — it rides the existing
submit → route → ledger path unchanged; only the planning decisions
above switch with the workload shape.
"""

from __future__ import annotations

from racon_tpu.utils import envspec

#: Targets per v2 manifest segment when the env leaves it to us: large
#: enough that a 10M-target run writes ~40k manifest records instead
#: of 10M, small enough that a crash recomputes at most one segment.
DEFAULT_SEG_TARGETS = 256

ENV_AVA_SEG = "RACON_TPU_AVA_SEG"


def seg_targets_for(fragment_correction: bool) -> int:
    """Checkpoint-manifest segment size for a run: ``0`` keeps the v1
    one-record-per-target manifest. Unset defaults to segmented for
    ava runs (every read is a target — per-target manifest records are
    exactly what cannot survive that scale) and v1 for kC polishing;
    an explicit ``RACON_TPU_AVA_SEG`` value wins in either mode."""
    raw = envspec.read(ENV_AVA_SEG).strip()
    if raw:
        try:
            return max(0, int(raw))
        except ValueError:
            return 0
    return DEFAULT_SEG_TARGETS if fragment_correction else 0


from racon_tpu.ava.emit import RecordSpool, iter_fasta_records  # noqa: E402
from racon_tpu.ava.partition import (weighted_bounds,  # noqa: E402
                                     weights_from_offsets)
from racon_tpu.ava.planner import BucketPlan, plan_buckets  # noqa: E402

__all__ = [
    "DEFAULT_SEG_TARGETS", "ENV_AVA_SEG", "seg_targets_for",
    "RecordSpool", "iter_fasta_records",
    "weighted_bounds", "weights_from_offsets",
    "BucketPlan", "plan_buckets",
]
