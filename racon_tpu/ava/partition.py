"""Length-weighted shard partitioning for all-vs-all target sets.

``distributed/ledger._partition`` balances shard bounds by target
COUNT — the right call for kC polishing, where contigs are few and
comparably sized. In the ava regime (``-f``) the targets are reads:
millions of them, with length distributions that routinely span two
orders of magnitude, so count-balanced shards can differ 10x in actual
work. The ledger already publishes per-target byte offsets
(``scan_sequence_index``) in ``meta.json``; this module turns those
offsets into per-target byte weights and cuts contiguous shard bounds
at equal-weight points instead of equal-count points.

The contract (docs/AVA.md "Weighted partition"):

- bounds are still contiguous and ascending over ``[0, n_targets]`` —
  every invariant downstream of ``_partition`` (manifest-as-prefix
  resume, split carving, the merge's tiling check) holds unchanged;
- every shard owns at least one target (``n_shards`` is pre-clamped to
  ``n_targets`` by the caller, as for the count partition);
- the weight of target ``i`` is the byte distance to the next record's
  offset; the final record, whose extent the offset list cannot see,
  weighs the mean record size. Weights are derived only from the
  PUBLISHED offsets, so any worker recomputing bounds from meta.json
  gets the same answer — no new shared state;
- merged output is unaffected: bounds change which worker polishes a
  target, never the target order the merge emits.

``RACON_TPU_AVA_WEIGHTED=0`` falls back to the count partition (the
A/B lever scripts/ava_scale_smoke.py uses to show the skew).
"""

from __future__ import annotations

from bisect import bisect_left
from typing import List, Optional, Sequence

from racon_tpu.utils import envspec

ENV_AVA_WEIGHTED = "RACON_TPU_AVA_WEIGHTED"


def weighted_enabled() -> bool:
    return envspec.read(ENV_AVA_WEIGHTED).strip().lower() not in (
        "0", "false", "no", "off")


def weights_from_offsets(offsets: Sequence[int]) -> List[int]:
    """Per-target byte weights from record start offsets. Each target
    weighs the gap to its successor's offset (header + data + quality
    bytes — exactly the I/O and, for length-proportional consensus
    work, the compute it represents); the last target weighs the mean
    gap, the best estimate the offset list alone supports. Every
    weight is at least 1 so empty-looking records still count."""
    n = len(offsets)
    if n == 0:
        return []
    if n == 1:
        return [1]
    weights = [max(1, int(offsets[i + 1]) - int(offsets[i]))
               for i in range(n - 1)]
    weights.append(max(1, round(sum(weights) / len(weights))))
    return weights


def weighted_partition(n_targets: int, n_shards: int,
                       weights: Sequence[int]) -> List[int]:
    """Contiguous bounds cutting ``weights`` into ``n_shards`` runs of
    near-equal total weight: shard ``k`` owns ``[bounds[k],
    bounds[k+1])``. Cut ``k`` lands where the weight prefix first
    reaches ``k/n_shards`` of the total, then is clamped so every
    shard (including all that follow) keeps at least one target —
    the non-empty-shard invariant the count partition guarantees."""
    if len(weights) != n_targets:
        raise ValueError(
            f"[racon_tpu::ava] weighted_partition got {len(weights)} "
            f"weights for {n_targets} targets")
    prefix = [0]
    for w in weights:
        prefix.append(prefix[-1] + max(1, int(w)))
    total = prefix[-1]
    bounds = [0]
    for k in range(1, n_shards):
        ideal = total * k / n_shards
        cut = bisect_left(prefix, ideal)
        # Keep >=1 target in this shard and >=1 in each remaining one.
        cut = max(cut, bounds[-1] + 1)
        cut = min(cut, n_targets - (n_shards - k))
        bounds.append(cut)
    bounds.append(n_targets)
    return bounds


def weighted_bounds(n_targets: int, n_shards: int,
                    offsets: Sequence[int]) -> Optional[List[int]]:
    """The bounds ``WorkLedger.open`` publishes when per-target offsets
    are in hand: the length-weighted partition, or ``None`` to keep
    the count partition (gate off, offset list inconsistent with the
    target count, or a single shard where balance is moot)."""
    if n_shards <= 1 or len(offsets) != n_targets:
        return None
    if not weighted_enabled():
        return None
    return weighted_partition(n_targets, n_shards,
                              weights_from_offsets(offsets))
