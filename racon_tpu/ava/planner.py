"""Greedy run-level shape buckets for read-length diversity.

The shape-bucket scheduler (racon_tpu/sched/) already coalesces window
shapes inside one polisher; what it cannot control is how many DISTINCT
overlap-alignment geometries an ava run presents to the device in the
first place — one per distinct padded read length, and an assembly-scale
read set has millions of distinct lengths. Every distinct geometry is a
compile (PROFILE.md: 44.5 s cold), so unplanned ava input is a compile
storm.

The planner quantizes lengths to a bucket quantum
(``ops/budget.ava_bucket_quantum``, tied to the consensus window
length), sweeps the targets IN INPUT ORDER coalescing consecutive
same-bucket reads into runs (reads arrive roughly length-sorted from
many assemblers, so run-level greediness preserves that locality for
the ledger's contiguous shards), and layers the result over the PR 6
tile tiers: each bucket's compile key is its padded length plus the
tier geometry ``ops/budget.tile_plan`` would pick for a same-length
overlap. If the distinct buckets exceed the compile budget
(``RACON_TPU_AVA_COMPILE_BUDGET``), the quantum doubles and the sweep
repeats — coarser buckets mean more padding, never more compiles, so
the loop always terminates with ``n_buckets <= budget``.

The plan is published (``ava_*`` gauges, docs/OBSERVABILITY.md) by the
distributed worker at ledger-join time, costing one pass over the
already-published offset deltas — no extra file I/O.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional, Sequence, Tuple

from racon_tpu.ops import budget as _budget


class BucketPlan(NamedTuple):
    """One planned run: ``buckets`` maps padded-length capacity to read
    count (ascending by capacity); ``n_runs`` counts the input-order
    runs the greedy sweep coalesced (locality measure: n_runs close to
    n_buckets means the input was already length-sorted);
    ``compile_keys`` are the distinct (tier W, tier T, capacity)
    geometry classes — the compile count the budget bounds;
    ``pad_frac`` is the padding overhead the quantization costs."""
    n_targets: int
    quantum: int
    buckets: Tuple[Tuple[int, int], ...]
    n_runs: int
    compile_keys: Tuple[Tuple[int, int, int], ...]
    pad_frac: float
    budget: int

    @property
    def n_buckets(self) -> int:
        return len(self.buckets)


def _tier_key(cap: int) -> Tuple[int, int]:
    """The tile-tier geometry a same-length overlap of ``cap`` bases
    lands on — (W, T) of the admitting tier, or (0, 0) for the
    untiled/native class. Equal-length pairs always clear the band-
    clearance test, so this is a pure function of the capacity."""
    plan = _budget.tile_plan(cap, cap)
    if plan is None:
        return (0, 0)
    return (plan.W, plan.T)


def plan_buckets(lengths: Sequence[int], *, window_length: int = 500,
                 budget: Optional[int] = None) -> BucketPlan:
    """Plan shape buckets for ``lengths`` (per-target sizes, input
    order). Guarantees ``n_buckets <= budget`` by doubling the quantum;
    raises on an empty target set (the ledger refuses those runs before
    planning ever happens)."""
    if not lengths:
        raise ValueError(
            "[racon_tpu::ava] plan_buckets needs at least one target")
    if budget is None:
        budget = _budget.ava_compile_budget()
    budget = max(1, int(budget))
    quantum = _budget.ava_bucket_quantum(window_length)
    total_len = sum(max(1, int(ln)) for ln in lengths)
    while True:
        counts = {}
        n_runs = 0
        prev_cap = None
        padded_total = 0
        for ln in lengths:
            ln = max(1, int(ln))
            cap = -(-ln // quantum) * quantum
            padded_total += cap
            counts[cap] = counts.get(cap, 0) + 1
            if cap != prev_cap:
                n_runs += 1
                prev_cap = cap
        if len(counts) <= budget:
            break
        quantum *= 2
    buckets = tuple(sorted(counts.items()))
    keys = tuple(sorted({_tier_key(cap) + (cap,) for cap, _ in buckets}))
    pad_frac = round(1.0 - total_len / padded_total, 4) \
        if padded_total else 0.0
    return BucketPlan(n_targets=len(lengths), quantum=quantum,
                      buckets=buckets, n_runs=n_runs,
                      compile_keys=keys, pad_frac=pad_frac,
                      budget=budget)


def lengths_from_offsets(offsets: Sequence[int]) -> List[int]:
    """Per-target byte sizes from the ledger's published record
    offsets — the planner's input when no parse has happened yet. Byte
    extents overstate base counts by the header/quality overhead, but
    bucketing is scale-free so the bucket structure is the same."""
    from racon_tpu.ava.partition import weights_from_offsets
    return weights_from_offsets(offsets)
