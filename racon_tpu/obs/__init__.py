"""Unified observability: structured run tracing + metrics registry.

- :mod:`racon_tpu.obs.trace` — nested spans (run → phase → chunk →
  round → dispatch → transfer) emitted as JSONL when
  ``RACON_TPU_TRACE=<path>`` (or ``--trace``) is set; a no-op null
  tracer otherwise.
- :mod:`racon_tpu.obs.metrics` — process-wide counter registry: the
  single source for the polisher's stderr scheduler summary,
  ``SchedTelemetry.as_extras()``, and bench.py's JSON extras, plus
  h2d/d2h transfer accounting (bytes, seconds, effective bandwidth)
  and dispatch / compile-cache counters.

Schema and env vars are documented in docs/OBSERVABILITY.md;
``scripts/obs_report.py`` renders a trace into a per-stage breakdown.
"""

from racon_tpu.obs.trace import Tracer, NullTracer, get_tracer, configure
from racon_tpu.obs.metrics import (MetricsRegistry, registry, reset,
                                   record_h2d, record_d2h,
                                   transfer_extras, publish_sched,
                                   sched_extras, sched_summary_line)

__all__ = [
    "Tracer", "NullTracer", "get_tracer", "configure",
    "MetricsRegistry", "registry", "reset",
    "record_h2d", "record_d2h", "transfer_extras",
    "publish_sched", "sched_extras", "sched_summary_line",
]
