"""Unified observability: tracing, metrics, fleet aggregation, export.

- :mod:`racon_tpu.obs.trace` — nested spans (run → phase → chunk →
  round → dispatch → transfer) emitted as JSONL when
  ``RACON_TPU_TRACE=<path>`` (or ``--trace``) is set; a no-op null
  tracer otherwise. Spans carry process-wide context attrs
  (``worker_id``/``shard``/``run_fp``) via ``set_context``.
- :mod:`racon_tpu.obs.metrics` — process-wide counter registry: the
  single source for the polisher's stderr scheduler summary,
  ``SchedTelemetry.as_extras()``, and bench.py's JSON extras, plus
  h2d/d2h transfer accounting (bytes, seconds, effective bandwidth)
  and dispatch / compile-cache counters. Every key has an explicit
  fleet merge kind (``merge_kind``: sum/max/last).
- :mod:`racon_tpu.obs.fleet` — the multi-process plane: per-worker
  metric shards (``obs/worker_<id>.metrics.jsonl``, atomically
  published, SIGTERM-flushed) and :func:`~racon_tpu.obs.fleet.aggregate`
  merging them with the ledger's ``events.jsonl`` into one fleet model.
- :mod:`racon_tpu.obs.export` — OpenMetrics/Prometheus text renderer
  for registries and fleet models, plus the ``RACON_TPU_METRICS_PORT``
  pull endpoint.

Schema and env vars are documented in docs/OBSERVABILITY.md;
``scripts/obs_report.py`` renders a trace into a per-stage breakdown
and ``scripts/obs_export.py`` emits OpenMetrics.
"""

from racon_tpu.obs.trace import Tracer, NullTracer, get_tracer, configure
from racon_tpu.obs.metrics import (MetricsRegistry, registry, reset,
                                   record_h2d, record_d2h,
                                   transfer_extras, publish_sched,
                                   sched_extras, sched_summary_line,
                                   merge_kind)

__all__ = [
    "Tracer", "NullTracer", "get_tracer", "configure",
    "MetricsRegistry", "registry", "reset",
    "record_h2d", "record_d2h", "transfer_extras",
    "publish_sched", "sched_extras", "sched_summary_line",
    "merge_kind",
]
