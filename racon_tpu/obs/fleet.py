"""Fleet observability plane: worker metric shards + aggregation.

PR 7 made execution multi-process (``--ledger-dir`` fleets of
preemptible workers), which left each worker's metrics registry and
trace to die with its process — steal/eviction behavior was only
reconstructable by hand from ``events.jsonl``. This module is the
missing read side:

- :class:`WorkerMetricsWriter` — every worker (and the serial CLI when
  ``RACON_TPU_OBS_DIR`` is set) periodically snapshots its registry to
  ``obs/worker_<id>.metrics.jsonl``, an append-ordered history of
  snapshots rewritten atomically per flush (tmp + fsync + rename, the
  atomicio discipline), so readers never see a torn shard no matter
  when the worker dies. SIGTERM routes through the CLI's teardown into
  :func:`flush_final`, so an *evicted* worker leaves a final snapshot;
  a hard ``kill`` leaves the last periodic one. The ``obs/snapshot``
  fault site drills the one hazard atomic publication removes: a
  ``torn`` rule makes the flush write a truncated file *directly* to
  the final path and hard-exit, and the aggregator must still recover
  every complete record before the tear (load_jsonl_prefix).

- :func:`aggregate` — merges all worker shards plus the ledger's
  ``events.jsonl`` into one fleet model: per-worker last snapshot,
  windows/s and phase seconds; fleet-wide counters folded with the
  explicit per-metric merge kind (obs/metrics.py::merge_kind — sum for
  counters, max for peaks, last for gauges); a per-shard lease timeline
  (claim/renew/steal/complete, renew runs compressed). Shards written
  by different run fingerprints refuse to merge (:class:`FleetObsError`)
  — same discipline as the ledger itself.

The model feeds scripts/obs_report.py (``fleet:`` section),
obs/export.py (OpenMetrics render + pull endpoint), and
scripts/dp_scaling_bench.py. Layout and merge semantics are documented
in docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import json
import os
from racon_tpu.utils import envspec
import threading
import time
from typing import Dict, List, Optional

from racon_tpu.obs.metrics import MetricsRegistry, merge_values
from racon_tpu.obs.metrics import registry as _default_registry
from racon_tpu.resilience.faults import hard_exit, maybe_torn
from racon_tpu.utils.atomicio import (atomic_write_bytes, fsync_dir,
                                      load_jsonl_prefix)

SNAPSHOT_SCHEMA = 1
OBS_SUBDIR = "obs"
SHARD_SUFFIX = ".metrics.jsonl"
#: The autoscaler's per-tick heartbeat (distributed/autoscaler.py),
#: written atomically next to the worker metric shards.
SUPERVISOR_NAME = "autoscaler.json"

#: Serial CLI opt-in: point at a directory to get the same metric shard
#: a fleet worker writes (the aggregator treats a one-shard directory
#: as a one-worker fleet).
ENV_OBS_DIR = "RACON_TPU_OBS_DIR"
#: Seconds between periodic flushes (default 5). ``0`` flushes on every
#: :func:`maybe_flush` call — smokes and tests use it to make snapshot
#: cadence deterministic.
ENV_FLUSH_S = "RACON_TPU_OBS_FLUSH_S"
DEFAULT_FLUSH_S = 5.0
#: Straggler threshold: a worker whose windows/s sits below this
#: fraction of the fleet median (computed over workers that polished
#: at all) gets ``straggler: true`` in the aggregate model. Merge-only
#: workers (rate 0) are never flagged — they did no window work to be
#: slow at.
ENV_STRAGGLER_FRAC = "RACON_TPU_STRAGGLER_FRAC"
DEFAULT_STRAGGLER_FRAC = 0.5


def straggler_frac() -> float:
    env = envspec.read(ENV_STRAGGLER_FRAC).strip()
    if not env:
        return DEFAULT_STRAGGLER_FRAC
    try:
        v = float(env)
    except ValueError:
        raise FleetObsError(
            f"[racon_tpu::fleet] invalid {ENV_STRAGGLER_FRAC}="
            f"{env!r} (expected a fraction in (0, 1])")
    if not 0.0 < v <= 1.0:
        raise FleetObsError(
            f"[racon_tpu::fleet] invalid {ENV_STRAGGLER_FRAC}={v} "
            "(expected a fraction in (0, 1])")
    return v


class FleetObsError(ValueError):
    """Unusable fleet observability state: no worker shards where some
    were promised, or shards stamped by different run fingerprints
    (merging metrics across runs would silently fabricate a fleet that
    never existed)."""


def _slug(worker_id: str) -> str:
    """Filesystem-safe shard name component for a worker id."""
    out = "".join(ch if ch.isalnum() or ch in "._-" else "_"
                  for ch in str(worker_id))
    return out[:80] or "worker"


def shard_path(directory: str, worker_id: str) -> str:
    return os.path.join(directory, f"worker_{_slug(worker_id)}"
                        f"{SHARD_SUFFIX}")


def flush_interval() -> float:
    env = envspec.read(ENV_FLUSH_S)
    if env:
        try:
            return max(0.0, float(env))
        except ValueError:
            pass
    return DEFAULT_FLUSH_S


class WorkerMetricsWriter:
    """Periodic, atomically-published registry snapshots for one worker.

    The shard file is a JSONL *history*: one record per flush, ``seq``
    strictly increasing, each record carrying the full registry
    snapshot at that moment plus identity (``worker_id``/``run_fp``)
    and wall clock. Each flush rewrites the whole file through
    atomic_write_bytes, so the published file is always a complete
    history — the aggregator just takes the last record. History size
    is bounded: snapshots are tiny (flat dicts) and flush cadence is
    seconds, so even hour-long runs stay in the kilobytes.
    """

    def __init__(self, directory: str, worker_id: str, run_fp: str,
                 reg: Optional[MetricsRegistry] = None,
                 interval_s: Optional[float] = None):
        os.makedirs(directory, exist_ok=True)
        fsync_dir(os.path.dirname(os.path.abspath(directory)))
        self.directory = directory
        self.worker_id = str(worker_id)
        self.run_fp = str(run_fp)
        self.path = shard_path(directory, worker_id)
        self.interval_s = (flush_interval() if interval_s is None
                           else max(0.0, float(interval_s)))
        self._reg = reg if reg is not None else _default_registry()
        self._lock = threading.Lock()
        self._records: List[Dict] = []
        self._t0 = time.perf_counter()
        self._last_flush = -1.0
        self._final = False

    def maybe_flush(self) -> bool:
        """Flush if the interval elapsed (always, at interval 0).
        Cheap enough for per-contig call sites; returns True when a
        snapshot was published."""
        now = time.perf_counter()
        if self._last_flush >= 0.0 and \
                now - self._last_flush < self.interval_s:
            return False
        self.flush()
        return True

    def flush(self, final: bool = False) -> None:
        """Snapshot the registry and atomically republish the shard.

        ``final`` marks the run-exit snapshot (normal exit or SIGTERM
        teardown); after it the writer goes inert so late teardown
        paths can call it unconditionally.
        """
        with self._lock:
            if self._final:
                return
            self._final = bool(final)
            rec = {
                "schema": SNAPSHOT_SCHEMA,
                "seq": len(self._records),
                "worker_id": self.worker_id,
                "run_fp": self.run_fp,
                "unix_time": round(time.time(), 3),
                "wall_s": round(time.perf_counter() - self._t0, 3),
                "final": bool(final),
                "metrics": self._reg.snapshot(),
            }
            self._records.append(rec)
            data = b"".join(
                json.dumps(r, sort_keys=True,
                           separators=(",", ":")).encode() + b"\n"
                for r in self._records)
            if maybe_torn("obs/snapshot"):
                # The drill: tear THIS write. Bypass the atomic publish
                # (tmp+rename can't tear — that's the point of it) and
                # leave a truncated shard at the final path, durable,
                # then die without cleanup. The aggregator must recover
                # every record before the tear.
                torn = data[:max(1, len(data) - 17)]
                with open(self.path, "wb") as fh:  # lint: atomic-ok (torn-write drill)
                    fh.write(torn)
                    fh.flush()
                    os.fsync(fh.fileno())
                hard_exit(137)
            atomic_write_bytes(self.path, data)
            self._last_flush = time.perf_counter()


# One writer per process, installed by the CLI/worker at join time so
# library code (and teardown paths) can flush without plumbing.
_WRITER: Optional[WorkerMetricsWriter] = None


def install_writer(directory: str, worker_id: str, run_fp: str,
                   reg: Optional[MetricsRegistry] = None,
                   interval_s: Optional[float] = None
                   ) -> WorkerMetricsWriter:
    """Install (and immediately flush) the process metrics writer.
    The eager first flush publishes the shard at join time, so a
    worker evicted before its first contig still appears in the fleet
    model."""
    global _WRITER
    _WRITER = WorkerMetricsWriter(directory, worker_id, run_fp,
                                  reg=reg, interval_s=interval_s)
    _WRITER.flush()
    return _WRITER


def get_writer() -> Optional[WorkerMetricsWriter]:
    return _WRITER


def maybe_flush() -> None:
    """Periodic-flush hook for hot paths; no-op without a writer."""
    if _WRITER is not None:
        _WRITER.maybe_flush()


def flush_final(reason: str = "teardown") -> None:
    """Final-snapshot hook for exit paths (normal return, SIGTERM
    teardown, watchdog self-eviction, unhandled-exception unwinds).
    Idempotent; no-op without a writer. Also the flight-recorder
    chokepoint: every abnormal teardown already routes through here,
    so the ring (obs/flightrec.py) dumps beside the metric shards."""
    if _WRITER is not None:
        _WRITER.flush(final=True)
        from racon_tpu.obs import flightrec
        flightrec.dump(_WRITER.directory, reason=reason)


# ----------------------------------------------------------- aggregation

def obs_dir_for(root: str) -> str:
    """The worker-shard directory for ``root``: its ``obs/`` subdir
    when present (a ledger dir), else ``root`` itself (a bare
    RACON_TPU_OBS_DIR)."""
    sub = os.path.join(root, OBS_SUBDIR)
    return sub if os.path.isdir(sub) else root


def load_worker_shards(obs_dir: str) -> List[Dict]:
    """Read every ``worker_*.metrics.jsonl`` shard under ``obs_dir``,
    torn-tolerantly: a truncated tail (the obs/snapshot drill, or a
    mid-write power cut on a non-atomic filesystem) drops only the
    torn record. Returns ``[{path, records, clean}, ...]`` sorted by
    filename; shards with no recoverable record are skipped."""
    shards = []
    try:
        names = sorted(os.listdir(obs_dir))
    except OSError:
        return shards
    for name in names:
        if not (name.startswith("worker_") and
                name.endswith(SHARD_SUFFIX)):
            continue
        path = os.path.join(obs_dir, name)
        records, clean = load_jsonl_prefix(path)
        records = [r for r in records
                   if r.get("schema") == SNAPSHOT_SCHEMA and
                   isinstance(r.get("metrics"), dict) and
                   "worker_id" in r and "run_fp" in r]
        if records:
            shards.append({"path": path, "records": records,
                           "clean": clean})
    return shards


def _compress_timeline(events: List[Dict]) -> Dict[str, List[Dict]]:
    """Group ledger events by shard name into per-shard timelines,
    collapsing each consecutive run of renews by one worker into a
    single ``{"ev": "renew", "n": count, ...}`` entry — a shard
    polishing hundreds of contigs renews per contig, and the timeline
    is for humans."""
    timeline: Dict[str, List[Dict]] = {}
    for rec in events:
        name = rec.get("name")
        ev = rec.get("ev")
        if not isinstance(name, str) or ev not in ("claim", "renew",
                                                   "steal", "complete",
                                                   "release", "split"):
            continue
        lane = timeline.setdefault(name, [])
        if ev == "renew" and lane and lane[-1]["ev"] == "renew" and \
                lane[-1].get("worker") == rec.get("worker"):
            lane[-1]["n"] += 1
            lane[-1]["t_last"] = rec.get("t")
            continue
        entry = {"ev": ev, "worker": rec.get("worker"),
                 "t": rec.get("t")}
        if ev == "renew":
            entry["n"] = 1
            entry["t_last"] = rec.get("t")
        if ev == "steal":
            entry["victim"] = rec.get("victim")
            entry["expired_for_s"] = rec.get("expired_for_s")
        if ev == "split":
            entry["child"] = rec.get("child")
        if "epoch" in rec:
            entry["epoch"] = rec.get("epoch")
        lane.append(entry)
    return timeline


def load_supervisor(root: str) -> Optional[Dict]:
    """The autoscaler's heartbeat (``obs/autoscaler.json``, written
    atomically once per control tick), or None when no supervisor ever
    attached to this ledger. Unreadable/torn heartbeats read as absent
    — the /healthz staleness check only fires on a heartbeat that
    parsed."""
    path = os.path.join(obs_dir_for(root), SUPERVISOR_NAME)
    try:
        with open(path, "rb") as fh:
            rec = json.loads(fh.read())
    except (OSError, ValueError):
        return None
    return rec if isinstance(rec, dict) else None


def aggregate(root: str) -> Dict:
    """Merge every worker metric shard under ``root`` (plus the
    ledger's ``events.jsonl`` when present) into one fleet model::

        {"run_fp": ..., "n_workers": N,
         "workers": {wid: {"seq", "wall_s", "final", "clean",
                           "unix_time", "windows_per_sec",
                           "phase_seconds": {...}, "metrics": {...}}},
         "fleet":   {key: merged value},     # merge_kind() semantics
         "timeline": {shard: [lease events]},
         "steals": total, "stragglers": [worker ids]}

    Each worker record also carries ``straggler`` (windows/s below
    ``RACON_TPU_STRAGGLER_FRAC`` of the fleet median — only computed
    when >= 2 workers polished windows; merge-only workers are never
    flagged).

    Raises :class:`FleetObsError` when no shard is readable or when
    shards carry different run fingerprints.
    """
    obs_dir = obs_dir_for(root)
    shards = load_worker_shards(obs_dir)
    if not shards:
        raise FleetObsError(
            f"[racon_tpu::fleet] no worker metric shards under "
            f"{obs_dir!r} — was the fleet run with fleet obs enabled "
            "(ledger workers write them automatically; serial runs "
            f"need {ENV_OBS_DIR})?")
    fps = sorted({sh["records"][-1]["run_fp"] for sh in shards})
    if len(fps) > 1:
        raise FleetObsError(
            f"[racon_tpu::fleet] refusing to merge shards from "
            f"different runs: {obs_dir!r} holds run_fp "
            f"{', '.join(fp[:12] for fp in fps)} — stale shards from "
            "a previous run share this directory; clear it or point "
            "at a fresh one")
    workers: Dict[str, Dict] = {}
    for sh in shards:
        last = sh["records"][-1]
        wid = str(last["worker_id"])
        metrics = last["metrics"]
        wall = float(last.get("wall_s", 0.0))
        windows = metrics.get("poa_windows_total", 0)
        phase = {k[len("phase_seconds_"):]: v
                 for k, v in metrics.items()
                 if k.startswith("phase_seconds_") and
                 k != "phase_seconds_total"}
        workers[wid] = {
            "seq": last.get("seq"),
            "wall_s": wall,
            "final": bool(last.get("final")),
            "clean": bool(sh["clean"]),
            "unix_time": last.get("unix_time"),
            "windows_per_sec": (round(windows / wall, 3)
                                if wall > 0 and windows else 0.0),
            "phase_seconds": phase,
            "metrics": metrics,
        }
    # Straggler flags: a fleet-slow-worker median comparison needs at
    # least two workers that actually polished windows.
    rates = sorted(w["windows_per_sec"] for w in workers.values()
                   if w["windows_per_sec"] > 0)
    stragglers: List[str] = []
    if len(rates) >= 2:
        mid = len(rates) // 2
        median = rates[mid] if len(rates) % 2 else \
            (rates[mid - 1] + rates[mid]) / 2.0
        cutoff = straggler_frac() * median
        for wid in sorted(workers):
            w = workers[wid]
            w["straggler"] = bool(0 < w["windows_per_sec"] < cutoff)
            if w["straggler"]:
                stragglers.append(wid)
    else:
        for w in workers.values():
            w["straggler"] = False
    keys = sorted({k for w in workers.values() for k in w["metrics"]})
    order = sorted(workers)
    fleet = {}
    for key in keys:
        merged = merge_values(
            key, [workers[w]["metrics"].get(key) for w in order])
        if merged is not None:
            fleet[key] = merged
    events_path = os.path.join(root, "events.jsonl")
    events: List[Dict] = []
    if os.path.exists(events_path):
        events, _ = load_jsonl_prefix(events_path)
    timeline = _compress_timeline(events)
    steals = sum(1 for rec in events if rec.get("ev") == "steal")
    splits = sum(1 for rec in events if rec.get("ev") == "split")
    spawns = sum(1 for rec in events if rec.get("ev") == "spawn")
    retires = sum(1 for rec in events if rec.get("ev") == "retire")
    # Split lineage: child shard name -> parent shard name, so readers
    # (obs_report --fleet) can render each lane's full ancestry chain.
    lineage = {rec["child"]: rec["name"] for rec in events
               if rec.get("ev") == "split" and
               isinstance(rec.get("child"), str) and
               isinstance(rec.get("name"), str)}
    # The supervisor heartbeat contributes the autoscaler's decision
    # counters and target gauge to the fleet fold — it has no metric
    # shard of its own (it polishes nothing), so its metrics ride the
    # heartbeat instead.
    supervisor = load_supervisor(root)
    if supervisor is not None:
        for key, val in sorted(
                (supervisor.get("metrics") or {}).items()):
            if isinstance(val, (int, float)) and \
                    not isinstance(val, bool):
                fleet[key] = val
    return {
        "run_fp": fps[0],
        "n_workers": len(workers),
        "workers": workers,
        "fleet": fleet,
        "timeline": timeline,
        "lineage": lineage,
        "steals": steals,
        "splits": splits,
        "spawns": spawns,
        "retires": retires,
        "supervisor": supervisor,
        "stragglers": stragglers,
    }


# ----------------------------------------------------- per-job timelines


def _span_matches_trace(span: Dict, trace_id: str) -> bool:
    """Batch spans carry comma-joined trace ids (one cross-request
    dispatch serves several jobs); a span belongs to the job when the
    id appears in the list."""
    tid = span.get("trace_id")
    if not isinstance(tid, str):
        return False
    return trace_id in tid.split(",")


def assemble_job_timeline(root: str, trace_id: str) -> Dict:
    """Stitch one causal per-job timeline out of every span file under
    ``root`` (its ``obs/`` subdir for a ledger dir): each process —
    daemon, ledger workers, autoscaler spawns — writes its own
    ``RACON_TPU_TRACE`` JSONL, and every span carrying the job's
    ``trace_id`` (adopted via the ``RACON_TPU_TRACE_CTX`` handoff) is
    placed on a common wall clock using its trace's ``begin`` header.
    ``.part`` sidecars count too: a hard-killed worker never promoted
    its trace, and its spans are exactly the interesting ones.

    Nested ``obs/`` dirs under ``root`` are scanned too: a gateway
    state dir holds the daemon's trace in ``<root>/obs`` and each
    fleet run's worker traces in ``<root>/fleet/<fp>/ledger/obs``
    (docs/GATEWAY.md), and one trace_id spans all of them — nested
    sources are keyed by their root-relative path.

    Returns ``{"trace_id", "n_processes", "n_spans", "sources": {file:
    span count}, "spans": [...]}`` with spans sorted by absolute start
    time (each span gains ``t_abs`` and ``src``). Refuses loudly
    (:class:`FleetObsError`) when no span carries the id, or when the
    matched spans straddle different ``run_fp`` stamps — merging two
    runs' spans would fabricate a timeline that never happened."""
    obs_dir = obs_dir_for(root)
    dirs = [obs_dir]
    for dirpath, _dirnames, _files in os.walk(root):
        if os.path.basename(dirpath) == OBS_SUBDIR and \
                os.path.abspath(dirpath) != os.path.abspath(obs_dir):
            dirs.append(dirpath)
    spans: List[Dict] = []
    sources: Dict[str, int] = {}
    fps = set()
    for d in dirs:
        try:
            names = sorted(os.listdir(d))
        except OSError:
            names = []
        for name in names:
            if name.endswith(SHARD_SUFFIX) or not (
                    name.endswith(".jsonl") or
                    name.endswith(".jsonl.part")):
                continue
            path = os.path.join(d, name)
            src = name if d == obs_dir else \
                os.path.relpath(path, root)
            records, _ = load_jsonl_prefix(path)
            if not records or records[0].get("ev") != "begin":
                continue
            begin = float(records[0].get("unix_time", 0.0))
            n = 0
            for rec in records[1:]:
                if rec.get("ev") != "span" or \
                        not _span_matches_trace(rec, trace_id):
                    continue
                span = dict(rec)
                span["t_abs"] = round(
                    begin + float(rec.get("t0", 0.0)), 6)
                span["src"] = src
                spans.append(span)
                n += 1
                fp = rec.get("run_fp")
                if isinstance(fp, str):
                    fps.add(fp)
            if n:
                sources[src] = n
    if not spans:
        raise FleetObsError(
            f"[racon_tpu::fleet] no span under {obs_dir!r} carries "
            f"trace_id {trace_id!r} — was the job run with tracing on "
            f"and the trace context handed to every process?")
    if len(fps) > 1:
        raise FleetObsError(
            f"[racon_tpu::fleet] refusing to assemble a timeline from "
            f"mixed runs: trace_id {trace_id!r} matched spans stamped "
            f"run_fp {', '.join(sorted(fp[:12] for fp in fps))} — "
            "stale traces from a previous run share this directory")
    spans.sort(key=lambda s: (s["t_abs"], s["src"], s.get("id", 0)))
    return {
        "trace_id": trace_id,
        "n_processes": len(sources),
        "n_spans": len(spans),
        "sources": sources,
        "spans": spans,
    }
