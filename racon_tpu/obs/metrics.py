"""Process-wide metrics registry — the single reporting source.

Three consumers used to format/serialize their own counters and could
drift: the polisher's stderr scheduler summary (utils/logger.py), the
scheduler's ``SchedTelemetry.as_extras()``, and bench.py's JSON extras.
They now all read one registry: :func:`publish_sched` writes the
canonical ``sched_*`` keys, :func:`sched_summary_line` formats the
stderr line from them, and :func:`transfer_extras` derives the
h2d/d2h byte / second / effective-bandwidth numbers recorded at the
transfer choke points (parallel/dispatch.py, ops/device_poa.py,
sched/scheduler.py).

Counter conventions (all keys appear in bench extras, metric_version 3;
docs/OBSERVABILITY.md documents the full set):

- ``h2d_bytes`` / ``h2d_s`` / ``h2d_transfers`` — bytes shipped to the
  device, wall seconds of the ``device_put`` calls, call count.
  device_put is asynchronous, so ``h2d_s`` measures the synchronous
  (serialization + enqueue) portion — a lower bound on true transfer
  time; through this environment's tunnel the call blocks on the wire
  and the derived ``h2d_mb_per_s`` is the effective tunnel bandwidth.
- ``d2h_bytes`` / ``d2h_s`` / ``d2h_transfers`` — device pulls
  (``np.asarray`` on device values). A pull blocks until any residual
  compute drains, so ``d2h_s`` is "time blocked pulling results" (the
  number PROFILE.md decomposed by hand) and ``d2h_mb_per_s`` is a
  lower bound on link bandwidth.
- ``sched_flag_pulls`` / ``sched_flag_pull_s`` — the scheduler's
  per-round convergence-flag pulls. These sync on compute, so their
  time is accounted separately and never enters the bandwidth estimate.
- ``device_dispatches`` — jitted chunk/round executions launched.
- ``jax_cache_entries_start`` / ``jax_cache_entries_added`` — persistent
  compile-cache population at enable time and entries added since
  (= compiles this process paid; 0 on a fully warm cache), from
  utils/jaxcache.py.

No device syncs anywhere: every value rides on host data the pipeline
already had in hand.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from racon_tpu.obs import flightrec as _flightrec
from racon_tpu.obs import trace as _trace


class MetricsRegistry:
    """Flat name -> value store: numeric counters plus JSON-ready
    structured values (lists/dicts). Keys starting with ``_`` are
    internal and excluded from snapshots. Mutations of the process
    registry additionally land in the flight-recorder ring
    (obs/flightrec.py) so a crash dump shows the final metric deltas;
    scratch registries stay out of the ring."""

    def __init__(self):
        self._lock = threading.Lock()
        self._v: Dict[str, object] = {}   # guarded-by: _lock

    def _flight(self, key: str, value) -> None:
        if self is _REGISTRY:
            _flightrec.note_metric(key, value)

    def inc(self, key: str, value: float = 1) -> None:
        with self._lock:
            self._v[key] = self._v.get(key, 0) + value
        self._flight(key, value)

    def set(self, key: str, value: object) -> None:
        with self._lock:
            self._v[key] = value
        self._flight(key, value)

    def max(self, key: str, value: float) -> None:
        """Keep the running maximum (gauge peaks, e.g. queue depth)."""
        with self._lock:
            cur = self._v.get(key)
            if cur is None or value > cur:
                self._v[key] = value
        self._flight(key, value)

    def apply(self, fn) -> None:
        """Run ``fn(values_dict)`` under the registry lock — the single
        mutation point for multi-key read-modify-write updates. A
        recorder that composes ``get``/``inc``/``set`` instead takes
        and releases the lock per call, and two pipeline/streaming
        threads interleaving between those calls drop updates or
        publish a ratio computed from mismatched numerator/denominator
        reads."""
        with self._lock:
            fn(self._v)

    def get(self, key: str, default: object = 0) -> object:
        with self._lock:
            return self._v.get(key, default)

    def reset(self) -> None:
        with self._lock:
            self._v.clear()

    def snapshot(self) -> Dict[str, object]:
        with self._lock:
            return {k: v for k, v in self._v.items()
                    if not k.startswith("_")}


_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    return _REGISTRY


def reset() -> None:
    _REGISTRY.reset()


# ------------------------------------------------------------ histograms

#: Fixed-bucket latency histograms: family name -> ascending log-spaced
#: upper bucket bounds (seconds, ``le`` semantics; one implicit +Inf
#: overflow bucket rides at the end). The set of families IS the
#: histogram registry: merge_kind() answers ``hist`` for exactly these
#: keys, METRIC_SPECS carries one MERGE_HIST row per family, the
#: OpenMetrics exporter renders each as a ``_bucket``/``_sum``/
#: ``_count`` family, and the HIS001 lint rule keeps all of that
#: consistent with the record_hist() call sites.
HIST_BUCKETS = {
    "dispatch_round_s": (0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                         0.5, 1.0, 2.5, 5.0, 10.0),
    "h2d_transfer_s": (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                       0.1, 0.25, 0.5, 1.0, 2.5),
    "serve_job_latency_s": (0.05, 0.1, 0.25, 0.5, 1.0, 2.5,
                            5.0, 10.0, 25.0, 60.0, 120.0),
    "serve_queue_wait_s": (0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                           1.0, 2.5, 5.0, 10.0, 30.0),
    "walk_hidden_s": (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
                      0.1, 0.25, 0.5, 1.0, 2.5),
}


def record_hist(name: str, value: float,
                reg: Optional[MetricsRegistry] = None) -> None:
    """Record one observation into the fixed-bucket histogram ``name``
    (a :data:`HIST_BUCKETS` family). The registry value is a dict
    ``{"buckets": [c0, ..., cN, overflow], "sum": s, "count": n}``
    with non-cumulative per-bucket counts — per-bucket SUM is the fleet
    merge, and the exporter derives the cumulative ``le`` series."""
    reg = reg if reg is not None else _REGISTRY
    bounds = HIST_BUCKETS[name]
    value = float(value)

    def _mutate(v):
        h = v.get(name)
        if h is None:
            h = v[name] = {"buckets": [0] * (len(bounds) + 1),
                           "sum": 0.0, "count": 0}
        idx = len(bounds)
        for i, bound in enumerate(bounds):
            if value <= bound:
                idx = i
                break
        h["buckets"][idx] += 1
        h["sum"] = round(h["sum"] + value, 6)
        h["count"] += 1

    reg.apply(_mutate)
    if reg is _REGISTRY:
        _flightrec.note_metric(name, round(value, 6))


def hist_quantile(hist: Dict, q: float, bounds) -> float:
    """The q-quantile (0..1) estimated from a histogram dict by linear
    interpolation inside the landing bucket; the overflow bucket clamps
    to the last finite bound. 0.0 on an empty histogram."""
    count = int(hist.get("count", 0))
    if count <= 0:
        return 0.0
    target = q * count
    seen = 0
    lo = 0.0
    for i, c in enumerate(hist["buckets"]):
        hi = float(bounds[i]) if i < len(bounds) else float(bounds[-1])
        if c and seen + c >= target:
            frac = (target - seen) / c
            return round(lo + (hi - lo) * min(max(frac, 0.0), 1.0), 6)
        seen += c
        lo = hi
    return round(float(bounds[-1]), 6)


def hist_percentiles(name: str,
                     reg: Optional[MetricsRegistry] = None
                     ) -> Dict[str, float]:
    """``{name_p50, name_p95, name_p99}`` from the recorded buckets;
    empty when the family has no observations."""
    reg = reg if reg is not None else _REGISTRY
    h = reg.get(name, None)
    if not isinstance(h, dict) or not h.get("count"):
        return {}
    bounds = HIST_BUCKETS[name]
    return {f"{name}_p{p}": hist_quantile(h, p / 100.0, bounds)
            for p in (50, 95, 99)}


# ------------------------------------------------------------- transfers

def record_h2d(nbytes: int, seconds: float,
               reg: Optional[MetricsRegistry] = None,
               name: str = "h2d") -> None:
    """Account one host-to-device transfer (and trace it when tracing
    is on)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("h2d_bytes", int(nbytes))
    reg.inc("h2d_s", float(seconds))
    reg.inc("h2d_transfers")
    record_hist("h2d_transfer_s", float(seconds), reg)
    _trace.get_tracer().point("transfer", name, dur_s=float(seconds),
                              bytes=int(nbytes), dir="h2d")


def record_d2h(nbytes: int, seconds: float,
               reg: Optional[MetricsRegistry] = None,
               name: str = "d2h") -> None:
    """Account one device-to-host pull whose value was already computed
    (so ``seconds`` measures transfer, not compute wait)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("d2h_bytes", int(nbytes))
    reg.inc("d2h_s", float(seconds))
    reg.inc("d2h_transfers")
    _trace.get_tracer().point("transfer", name, dur_s=float(seconds),
                              bytes=int(nbytes), dir="d2h")


def record_flag_pull(nbytes: int, seconds: float,
                     reg: Optional[MetricsRegistry] = None) -> None:
    """The scheduler's per-round flag pull: a sync point, so its time
    includes compute wait and stays out of the bandwidth estimate."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("sched_flag_pulls")
    reg.inc("sched_flag_pull_s", float(seconds))


def transfer_extras(reg: Optional[MetricsRegistry] = None
                    ) -> Dict[str, object]:
    """Derived transfer numbers for bench extras / reports."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for d in ("h2d", "d2h"):
        b = int(reg.get(f"{d}_bytes", 0))
        s = float(reg.get(f"{d}_s", 0.0))
        n = int(reg.get(f"{d}_transfers", 0))
        if not n:
            continue
        out[f"{d}_bytes"] = b
        out[f"{d}_s"] = round(s, 4)
        out[f"{d}_transfers"] = n
        if s > 0:
            out[f"{d}_mb_per_s"] = round(b / s / 1e6, 3)
    n = int(reg.get("sched_flag_pulls", 0))
    if n:
        out["sched_flag_pulls"] = n
        out["sched_flag_pull_s"] = round(
            float(reg.get("sched_flag_pull_s", 0.0)), 4)
    n = int(reg.get("device_dispatches", 0))
    if n:
        out["device_dispatches"] = n
    return out


# ----------------------------------------------------------- resilience

def _site_key(site: str) -> str:
    """Metric-key slug for a call-site name ("h2d/chunk" -> "h2d_chunk")."""
    return site.replace("/", "_").replace(".", "_")


def record_retry(site: str, attempt: int, delay_s: float, error: str,
                 injected: bool,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account one retried attempt at a resilience-wrapped call site
    (racon_tpu/resilience/retry.py) and trace it as a ``retry`` span."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("res_retry_total")
    reg.inc(f"res_retry_site_{_site_key(site)}")
    reg.inc("res_retry_backoff_s", float(delay_s))
    _trace.get_tracer().point("retry", site, attempt=int(attempt),
                              error=error, injected=int(bool(injected)))


def record_retry_exhausted(site: str, attempts: int,
                           reg: Optional[MetricsRegistry] = None) -> None:
    """A retry loop gave up; the caller degrades or aborts."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("res_retry_exhausted")
    _trace.get_tracer().point("retry", f"{site}/exhausted",
                              attempt=int(attempts), error="exhausted",
                              injected=0)


def record_fault(site: str, index: int, action: str,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account one injected fault (racon_tpu/resilience/faults.py)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("res_fault_injected_total")
    reg.inc(f"res_fault_site_{_site_key(site)}")
    _trace.get_tracer().point("fault", site, index=int(index),
                              action=action)


def record_watchdog_breach(site: str, deadline_s: float, waited_s: float,
                           terminal: bool = False,
                           reg: Optional[MetricsRegistry] = None) -> None:
    """Account one fail-slow deadline breach
    (racon_tpu/resilience/watchdog.py) and trace it as a ``watchdog``
    span; terminal breaches (the self-eviction trigger) additionally
    bump ``res_watchdog_terminal_total``."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("res_watchdog_breach_total")
    reg.inc(f"res_watchdog_site_{_site_key(site)}")
    if terminal:
        reg.inc("res_watchdog_terminal_total")
    _flightrec.note_breach(site, deadline_s, waited_s, terminal)
    _trace.get_tracer().point("watchdog", site, dur_s=float(waited_s),
                              deadline_s=float(deadline_s),
                              waited_s=round(float(waited_s), 6),
                              terminal=int(bool(terminal)))


def record_stall(window_s: float, n_stages: int,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account one pipeline stall-detector firing (no stage progressed
    for a full window) and trace it as a ``stall`` span."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("pipe_stall_events")
    _trace.get_tracer().point("stall", "pipeline",
                              window_s=float(window_s),
                              stages=int(n_stages))


def record_degraded(n_windows: int,
                    reg: Optional[MetricsRegistry] = None) -> None:
    """A chunk exhausted its retries and its windows were re-polished
    on the host-fallback consensus path."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("res_degraded_chunks")
    reg.inc("res_degraded_windows", int(n_windows))


def record_ckpt(event: str, tid: int, nbytes: int,
                reg: Optional[MetricsRegistry] = None) -> None:
    """Account one checkpoint event: ``commit`` (contig durably
    retired), ``skip`` (resume re-emitted a committed contig), or
    ``resume`` (store opened with N committed contigs in ``tid``)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc(f"res_ckpt_{event}s" if event != "resume" else
            "res_ckpt_resumes")
    if event == "commit":
        reg.inc("res_ckpt_bytes", int(nbytes))
    _trace.get_tracer().point("checkpoint", event, tid=int(tid),
                              bytes=int(nbytes))


def resilience_extras(reg: Optional[MetricsRegistry] = None
                      ) -> Dict[str, object]:
    """The registry's res_* keys as a JSON-ready dict (bench extras /
    obs_report "Resilience" section). Empty when nothing resilience-
    related happened, so quiet runs stay quiet."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("res_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ----------------------------------------------------- distributed work

def record_dist(event: str, shard, worker, value: float = 1,
                reg: Optional[MetricsRegistry] = None, **attrs) -> None:
    """Account one distributed-ledger event (racon_tpu/distributed/):
    ``claims`` / ``shards_stolen`` / ``leases_expired`` /
    ``lease_renewals`` / ``leases_lost`` / ``contigs_polished`` /
    ``contigs_repolished`` / ``contigs_resumed`` /
    ``shards_completed`` / ``steal_latency_s`` / ``recovery_wall_s`` /
    ``merges`` — each lands as the counter
    ``dist_<event>`` (incremented by ``value``) plus a ``dist`` trace
    span carrying the shard id and worker identity. ``shard`` is -1 for
    run-level events (merge)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc(f"dist_{event}", value)
    _trace.get_tracer().point("dist", event, shard=int(shard),
                              worker=str(worker), **attrs)


def set_dist(key: str, value: object,
             reg: Optional[MetricsRegistry] = None) -> None:
    """Set a distributed gauge (``dist_workers``, ``dist_shards``,
    ``dist_n_targets`` — fleet shape, not counters)."""
    reg = reg if reg is not None else _REGISTRY
    reg.set(f"dist_{key}", value)


def dist_extras(reg: Optional[MetricsRegistry] = None
                ) -> Dict[str, object]:
    """The registry's dist_* keys as a JSON-ready dict (bench extras
    metric_version 8 / obs_report "Distributed" section). Empty when no
    ledger ran, so single-process runs stay quiet."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("dist_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ------------------------------------------- overlap-alignment counters

def record_ovl(device_jobs: int, native_jobs: int, tiles: int,
               reg: Optional[MetricsRegistry] = None) -> None:
    """Account one device_breaking_points batch (ops/ovl_align.py):
    ``device_jobs`` overlaps whose breaking points the device produced
    (untiled + tiled, minus uncertified), ``native_jobs`` overlaps
    routed to the native aligner (over budget OR uncertified), and
    ``tiles`` query-axis tiles executed by the tiled ultralong path.
    ``ovl_device_fraction`` is the running device share — the headline
    number for ROADMAP item 3 (it was pinned ~0 for ultralong inputs
    before the tiled path existed)."""
    reg = reg if reg is not None else _REGISTRY

    def _mutate(v):
        # One lock for the whole read-modify-write: the device fraction
        # must be derived from the same totals its increments produced,
        # and ovl batches land concurrently from pipeline stage threads.
        v["ovl_device_jobs"] = v.get("ovl_device_jobs", 0) + int(device_jobs)
        v["ovl_native_jobs"] = v.get("ovl_native_jobs", 0) + int(native_jobs)
        v["ovl_tiles_exec"] = v.get("ovl_tiles_exec", 0) + int(tiles)
        total = v["ovl_device_jobs"] + v["ovl_native_jobs"]
        if total > 0:
            v["ovl_device_fraction"] = round(
                v["ovl_device_jobs"] / total, 4)

    reg.apply(_mutate)


def record_align_phase(seconds: float,
                       reg: Optional[MetricsRegistry] = None) -> None:
    """Wall seconds of one polisher align phase (device dispatch +
    native fallback + breaking-point walk; models/polisher.py phase 5).
    Accumulates across contigs so bench extras see the whole run."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("align_phase_seconds", float(seconds))


def ovl_extras(reg: Optional[MetricsRegistry] = None
               ) -> Dict[str, object]:
    """The registry's ovl_* keys plus align_phase_seconds as a
    JSON-ready dict (bench extras metric_version 7 / obs_report).
    Empty when no overlap alignment ran."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("ovl_") or k == "align_phase_seconds":
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# --------------------------------------------------- wide-band redo

def record_redo(device_windows: int, host_windows: int,
                reg: Optional[MetricsRegistry] = None) -> None:
    """Account one wide-band redo pass (ops/redo.py): ``device_windows``
    flagged windows the on-device second pass resolved,
    ``host_windows`` windows still unresolved after it (saturation
    class, or certificate failure at the widened band) that fall back
    to the host consensus. Zero host windows at bench geometry is the
    acceptance criterion the redo smoke pins."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("redo_passes")
    reg.inc("redo_device_windows", int(device_windows))
    reg.inc("redo_host_windows", int(host_windows))


def redo_extras(reg: Optional[MetricsRegistry] = None
                ) -> Dict[str, object]:
    """The registry's redo_* keys plus the ``walk_chain_len`` gauge as a
    JSON-ready dict (bench extras metric_version 9 / obs_report "Redo"
    section). ``walk_chain_len`` reports even when no redo fired — it is
    the traceback critical-path gauge, set at every chunk dispatch."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("redo_") or k == "walk_chain_len":
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ------------------------------------------------------- ingest plane

def record_ingest_inflate(mode: str, bytes_in: int, bytes_out: int,
                          seconds: float, blocks: int,
                          reg: Optional[MetricsRegistry] = None) -> None:
    """Account one gzip source's inflate totals (io/inflate.py, called
    once when the source finishes): the inflate plan (``bgzf`` /
    ``members`` / ``stream``), compressed bytes consumed, decompressed
    bytes produced, summed worker-pool inflate seconds (may exceed wall
    on the parallel paths — that is the point), and blocks/members
    inflated. Emits one ``ingest`` trace span per source."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("ingest_bytes_in", int(bytes_in))
    reg.inc("ingest_bytes_out", int(bytes_out))
    reg.inc("ingest_inflate_s", float(seconds))
    reg.inc("ingest_blocks", int(blocks))
    _trace.get_tracer().point("ingest", f"inflate/{mode}",
                              dur_s=float(seconds), mode=mode,
                              bytes=int(bytes_out), blocks=int(blocks))


def record_ingest_parse(mode: str, seconds: float, records: int,
                        raw_bytes: int,
                        reg: Optional[MetricsRegistry] = None) -> None:
    """Account one file's parse totals: the reader plan (``indexed`` /
    ``serial`` / ``prefetch``), seconds spent turning bytes into
    records (on the prefetch thread when overlapped, inline otherwise),
    records produced, and raw (decompressed) bytes consumed. Emits one
    ``ingest`` trace span per file."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("ingest_parse_s", float(seconds))
    reg.inc("ingest_records", int(records))
    reg.inc("ingest_raw_bytes", int(raw_bytes))
    _trace.get_tracer().point("ingest", f"parse/{mode}",
                              dur_s=float(seconds), mode=mode,
                              bytes=int(raw_bytes), records=int(records))


def record_ingest_wait(seconds: float,
                       reg: Optional[MetricsRegistry] = None) -> None:
    """Account consumer time blocked on ingest — the ONLY ingest term
    on the run's critical path when prefetch overlaps. Serial (non-
    prefetch) ingest books its whole parse wall here."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("ingest_wait_s", float(seconds))


def set_ingest_fraction(wall_s: float,
                        reg: Optional[MetricsRegistry] = None) -> None:
    """Derive and set the ``ingest_fraction_of_wall`` gauge = critical-
    path ingest wait / total run wall (cli.py, end of run). A fraction
    near 0 with nonzero ingest_parse_s means the overlap worked."""
    reg = reg if reg is not None else _REGISTRY
    if wall_s > 0:
        wait = float(reg.get("ingest_wait_s", 0.0))
        reg.set("ingest_fraction_of_wall", round(wait / wall_s, 4))


def ingest_extras(reg: Optional[MetricsRegistry] = None
                  ) -> Dict[str, object]:
    """The registry's ingest_* keys as a JSON-ready dict (bench extras
    metric_version 11 / obs_report "ingest:" section), plus derived
    ``ingest_mb_per_sec`` (decompressed MB over inflate+parse seconds).
    Empty when no ingest accounting ran."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("ingest_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    if not out:
        return out
    raw = float(reg.get("ingest_raw_bytes", 0.0)) or float(
        reg.get("ingest_bytes_out", 0.0))
    busy = float(reg.get("ingest_inflate_s", 0.0)) + float(
        reg.get("ingest_parse_s", 0.0))
    if raw > 0 and busy > 0:
        out["ingest_mb_per_sec"] = round(raw / busy / 1e6, 2)
    out["ingest_seconds"] = round(busy, 4)
    return out


# ------------------------------------------------------ pipeline gauges

def record_stage(name: str, busy_s: float, stall_in_s: float,
                 stall_out_s: float, items: int,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account one pipeline stage's lifetime totals (called when the
    stage thread exits; racon_tpu/pipeline/stages.py). ``busy`` is time
    in the stage's work function, ``stall`` time blocked on its input
    (starved) or output (choked) queue — together they say which stage
    bounds the pipeline. ``pipe_stage_compute_busy_s`` doubles as the
    device-busy term of the overlap-efficiency ratio."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc(f"pipe_stage_{name}_busy_s", float(busy_s))
    reg.inc(f"pipe_stage_{name}_stall_in_s", float(stall_in_s))
    reg.inc(f"pipe_stage_{name}_stall_out_s", float(stall_out_s))
    reg.inc(f"pipe_stage_{name}_items", int(items))


def record_queue(name: str, peak: int, put_wait_s: float,
                 get_wait_s: float,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account one bounded queue's gauges (peak depth is a max across
    pipeline runs, blocked times accumulate)."""
    reg = reg if reg is not None else _REGISTRY
    reg.max(f"pipe_queue_{name}_peak", int(peak))
    reg.inc(f"pipe_queue_{name}_put_wait_s", float(put_wait_s))
    reg.inc(f"pipe_queue_{name}_get_wait_s", float(get_wait_s))


def record_pipeline_wall(seconds: float,
                         reg: Optional[MetricsRegistry] = None) -> None:
    """Account one stream_consensus invocation's wall time — the
    denominator of overlap efficiency."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("pipe_runs")
    reg.inc("pipe_wall_s", float(seconds))


def pipeline_extras(reg: Optional[MetricsRegistry] = None
                    ) -> Dict[str, object]:
    """The registry's pipe_* keys as a JSON-ready dict (bench extras /
    obs_report "Pipeline" section), plus the derived overlap efficiency
    = device-busy (compute stage) / pipeline wall. Empty when no
    pipeline ran."""
    reg = reg if reg is not None else _REGISTRY
    if not int(reg.get("pipe_runs", 0)):
        return {}
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("pipe_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    wall = float(reg.get("pipe_wall_s", 0.0))
    busy = float(reg.get("pipe_stage_compute_busy_s", 0.0))
    if wall > 0:
        out["pipe_overlap_efficiency"] = round(busy / wall, 4)
    return out


# -------------------------------------------- decoupled-walk telemetry


def record_walk(walk_s: float, overlap_s: float, dispatches: int,
                fused_chunks: int, queue_peak: int, enabled: bool,
                reg: Optional[MetricsRegistry] = None) -> None:
    """Account one stream_consensus invocation's decoupled-walk
    telemetry (pipeline/streaming.py walk stage):

    - ``walk_s``       seconds spent inside walk dispatches (the walk
      stage's synchronized dispatch+collect window);
    - ``overlap_s``    the portion of that during which at least one
      OTHER chunk's forward dispatch was in flight — the latency the
      decoupling actually hid;
    - ``dispatches``   decoupled walk dispatches issued;
    - ``fused_chunks`` chunks that took the fused fallback;
    - ``queue_peak``   peak depth of the in-flight walk-input queue;
    - ``enabled``      whether the decoupled path was active at all.

    The derived ``walk_hidden_fraction`` = overlap / walk seconds is
    the bench/ablation headline (ISSUE 14 acceptance gate)."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("walk_async_enabled", int(bool(enabled)))
    reg.inc("walk_seconds", float(walk_s))
    reg.inc("walk_overlap_s", float(overlap_s))
    reg.inc("walk_dispatches", int(dispatches))
    reg.inc("walk_fused_chunks", int(fused_chunks))
    reg.max("walk_queue_peak", int(queue_peak))
    if dispatches:
        record_hist("walk_hidden_s", float(overlap_s), reg)
    total = float(reg.get("walk_seconds", 0.0))
    if total > 0:
        reg.set("walk_hidden_fraction",
                round(float(reg.get("walk_overlap_s", 0.0)) / total, 4))


def walk_extras(reg: Optional[MetricsRegistry] = None
                ) -> Dict[str, object]:
    """The registry's walk_* keys as a JSON-ready dict (bench extras /
    ablation report). Empty when no streaming run recorded walk
    telemetry (record_walk never ran)."""
    reg = reg if reg is not None else _REGISTRY
    if reg.get("walk_async_enabled", None) is None:
        return {}
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("walk_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ---------------------------------------------------------- serve plane

def record_serve_job(event: str, job: str, tenant: str,
                     trace_id: str = "-", parent_id: int = 0,
                     reg: Optional[MetricsRegistry] = None) -> int:
    """Account one daemon job-lifecycle event (racon_tpu/server/):
    ``submitted`` / ``completed`` / ``failed`` / ``cancelled`` /
    ``resumed`` — each lands as the counter ``serve_jobs_<event>``
    plus a ``serve`` trace span carrying the job id, tenant, and the
    job's trace context (``"-"``/0 when the caller has none, e.g. the
    bench driving the batcher directly). Returns the span id — the
    ``submitted`` span is the root the daemon mints the job's
    :class:`~racon_tpu.obs.trace.TraceContext` from."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc(f"serve_jobs_{event}")
    return _trace.get_tracer().point("serve", event, job=str(job),
                                     tenant=str(tenant),
                                     trace_id=str(trace_id),
                                     parent_id=int(parent_id))


def record_serve_batch(n_windows: int, capacity: int, jobs, tenants,
                       wait_s: float, round_s: float = 0.0,
                       trace_ids=(), parent_ids=(),
                       reg: Optional[MetricsRegistry] = None) -> None:
    """Account one cross-request batch dispatch
    (racon_tpu/server/batch.py): windows carried, the jobs/tenants that
    contributed, the summed staging wait its items paid, the dispatch
    round's wall (``dispatch_round_s`` histogram), and the trace
    contexts riding the batched items (comma-joined into the span's
    ``trace_id`` so a mixed batch appears in every contributing job's
    timeline). The derived ``serve_batch_occupancy`` gauge — mean
    windows per dispatch over the bucket capacity — is the headline:
    strictly higher under concurrent jobs than one-at-a-time is the
    server smoke's acceptance gate. Stamps ``serve_rate_wall_s`` so
    readers can tell a live gauge from one re-served forever after the
    final dispatch."""
    reg = reg if reg is not None else _REGISTRY
    cap = max(int(capacity), 1)

    def _mutate(v):
        # One lock for the whole read-modify-write: the occupancy ratio
        # must be derived from the same totals its increments produced.
        v["serve_batches"] = v.get("serve_batches", 0) + 1
        v["serve_batch_windows"] = \
            v.get("serve_batch_windows", 0) + int(n_windows)
        v["serve_tenant_wait_s"] = \
            v.get("serve_tenant_wait_s", 0.0) + float(wait_s)
        v["serve_batch_occupancy"] = round(
            v["serve_batch_windows"] / (v["serve_batches"] * cap), 4)
        v["serve_rate_wall_s"] = round(time.time(), 3)

    reg.apply(_mutate)
    if round_s > 0:
        record_hist("dispatch_round_s", float(round_s), reg)
    tid = ",".join(sorted({str(t) for t in trace_ids if t})) or "-"
    pid = int(next(iter(parent_ids), 0))
    _trace.get_tracer().point("serve", "batch",
                              job=",".join(str(j) for j in jobs),
                              tenant=",".join(str(t) for t in tenants),
                              windows=int(n_windows), capacity=cap,
                              wait_s=round(float(wait_s), 6),
                              trace_id=tid, parent_id=pid)


def set_serve_active(n: int,
                     reg: Optional[MetricsRegistry] = None) -> None:
    """Set the daemon's in-flight job gauge (submitted or running,
    not yet terminal)."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("serve_active_jobs", int(n))


def set_serve_rate(jobs_per_min: float,
                   reg: Optional[MetricsRegistry] = None) -> None:
    """Set the daemon's completion-rate gauge (completed jobs over
    daemon uptime minutes; recomputed at each completion) plus the
    ``serve_rate_wall_s`` freshness stamp: MERGE_LAST gauges re-serve
    their final value forever, so obs_report flags them stale once the
    stamp trails the trace end by more than 5x the flush cadence."""
    reg = reg if reg is not None else _REGISTRY

    def _mutate(v):
        v["serve_jobs_per_min"] = round(float(jobs_per_min), 4)
        v["serve_rate_wall_s"] = round(time.time(), 3)

    reg.apply(_mutate)


def serve_extras(reg: Optional[MetricsRegistry] = None
                 ) -> Dict[str, object]:
    """The registry's serve_* keys as a JSON-ready dict (bench extras
    metric_version 13 / obs_report "server:" section). Empty when no
    daemon/batcher ran, so CLI runs stay quiet."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("serve_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ------------------------------------------------------------ gate plane

#: Gateway lifecycle events -> the counter each lands in. The mapping
#: IS the gate counter registry: record_gate refuses unknown events,
#: so a typo'd call site fails loudly instead of minting an
#: undeclared key.
_GATE_EVENT_KEYS = {
    "route_fleet": "gate_routed_fleet",
    "route_local": "gate_routed_local",
    "adopt": "gate_adoptions",
    "fleet_run": "gate_fleet_runs",
}


def record_gate(event: str, job: str, tenant: str,
                trace_id: str = "-", parent_id: int = 0,
                reg: Optional[MetricsRegistry] = None,
                wall_s: Optional[float] = None, **attrs) -> int:
    """Account one gateway event (racon_tpu/gateway/, docs/GATEWAY.md):
    ``route_fleet`` / ``route_local`` — the dispatch decision for an
    accepted job; ``adopt`` — a standby gateway fenced a dead primary
    and took over its journal; ``fleet_run`` — one fleet execution
    finished streaming back (its ``wall_s`` accumulates into
    ``gate_fleet_wall_s``). Each event is a counter bump plus a
    ``gate`` trace span carrying the job's trace context, so the
    per-job timeline shows the routing decision between the daemon's
    ``serve`` spans and the fleet's worker spans. Returns the span
    id."""
    reg = reg if reg is not None else _REGISTRY
    try:
        key = _GATE_EVENT_KEYS[event]
    except KeyError:
        raise ValueError(f"[racon_tpu::metrics] unknown gate event "
                         f"{event!r}") from None
    reg.inc(key)
    if wall_s is not None:
        reg.inc("gate_fleet_wall_s", float(wall_s))
        attrs["wall_s"] = round(float(wall_s), 6)
    return _trace.get_tracer().point("gate", event, job=str(job),
                                     tenant=str(tenant),
                                     trace_id=str(trace_id),
                                     parent_id=int(parent_id), **attrs)


def set_gate_fleet_target(n: int,
                          reg: Optional[MetricsRegistry] = None) -> None:
    """Set the gateway's fleet sizing gauge — the worker target the
    service policy (gateway/policy.py) chose on its latest supervisor
    tick."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("gate_fleet_target", int(n))


def set_gate_rate(jobs_per_min: float,
                  compile_skip_s: Optional[float] = None,
                  reg: Optional[MetricsRegistry] = None) -> None:
    """Set the gateway throughput gauges (bench metric_version 16):
    fleet-path jobs/min, and — when measured — the wall seconds a
    freshly spawned worker skipped by hitting the shared jaxcache warm
    pool instead of compiling cold."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("gate_fleet_jobs_per_min", round(float(jobs_per_min), 4))
    if compile_skip_s is not None:
        reg.set("gate_compile_skip_s", round(float(compile_skip_s), 4))


def gate_extras(reg: Optional[MetricsRegistry] = None
                ) -> Dict[str, object]:
    """The registry's gate_* keys as a JSON-ready dict (bench extras
    metric_version 16 / obs_report "gateway:" section). Empty when no
    gateway ran, so plain daemon and CLI runs stay quiet."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("gate_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ------------------------------------------------------------ ava plane


def record_ava_plan(plan,
                    reg: Optional[MetricsRegistry] = None) -> None:
    """Publish the ava shape-bucket plan (racon_tpu/ava/planner.py,
    docs/AVA.md): target count, bucket count vs the compile budget,
    the quantum the budget loop settled on, and the padding overhead
    it cost. All gauges — every worker computes the identical plan
    from the published offsets, so the fleet merge takes the last."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("ava_targets", int(plan.n_targets))
    reg.set("ava_buckets", int(plan.n_buckets))
    reg.set("ava_quantum", int(plan.quantum))
    reg.set("ava_compile_budget", int(plan.budget))
    reg.set("ava_pad_frac", round(float(plan.pad_frac), 4))


def set_ava_bench(reads_per_sec: float, peak_rss_mb: float,
                  manifest_bytes_per_target: float,
                  reg: Optional[MetricsRegistry] = None) -> None:
    """Set the ava bench gauges (bench metric_version 17): corrected
    reads per wall second, the run's peak resident set, and manifest
    bytes per committed target — the v2 segmented manifest's
    amortization, which v1's one-record-per-target format holds at
    ~100 regardless of scale."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("ava_reads_per_sec", round(float(reads_per_sec), 4))
    reg.set("ava_peak_rss_mb", round(float(peak_rss_mb), 4))
    reg.set("ava_manifest_bytes_per_target",
            round(float(manifest_bytes_per_target), 4))


def ava_extras(reg: Optional[MetricsRegistry] = None
               ) -> Dict[str, object]:
    """The registry's ava_* keys as a JSON-ready dict (bench extras
    metric_version 17 / obs_report "ava:" section). Empty when no ava
    planning ran, so kC runs stay quiet."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("ava_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# --------------------------------------------------- result cache plane


def record_cache(tier: str, outcome: str, n: int = 1, nbytes: int = 0,
                 reg: Optional[MetricsRegistry] = None) -> None:
    """Account result-cache events (racon_tpu/cache/, docs/CACHE.md).
    ``tier`` is ``job`` (the on-disk CAS) or ``window`` (the
    in-batcher consensus memo); ``outcome`` is ``hit`` / ``miss`` /
    ``store`` / ``evict`` / ``verify_fail``. ``n`` batches per-window
    probes into one call so a 256-window chunk is one registry pass
    and one trace point, not 256; ``nbytes`` (stores) feeds
    ``cache_bytes``. The derived ``cache_hit_ratio`` gauge is
    recomputed inside the same registry pass so it can never drift
    from the totals it summarizes."""
    reg = reg if reg is not None else _REGISTRY

    def _mutate(v):
        if outcome == "hit":
            v["cache_hits_total"] = \
                v.get("cache_hits_total", 0) + int(n)
        elif outcome == "miss":
            v["cache_misses_total"] = \
                v.get("cache_misses_total", 0) + int(n)
        elif outcome == "store":
            v["cache_stores_total"] = \
                v.get("cache_stores_total", 0) + int(n)
        elif outcome == "evict":
            v["cache_evictions_total"] = \
                v.get("cache_evictions_total", 0) + int(n)
        elif outcome == "verify_fail":
            v["cache_verify_fail_total"] = \
                v.get("cache_verify_fail_total", 0) + int(n)
        else:
            raise ValueError(f"[racon_tpu::metrics] unknown cache "
                             f"outcome {outcome!r}")
        if nbytes:
            v["cache_bytes"] = v.get("cache_bytes", 0) + int(nbytes)
        seen = v.get("cache_hits_total", 0) + \
            v.get("cache_misses_total", 0)
        if seen:
            v["cache_hit_ratio"] = round(
                v.get("cache_hits_total", 0) / seen, 4)

    reg.apply(_mutate)
    _trace.get_tracer().point("cache", outcome, tier=str(tier),
                              outcome=str(outcome), n=int(n),
                              bytes=int(nbytes))


def result_cache_extras(reg: Optional[MetricsRegistry] = None
                        ) -> Dict[str, object]:
    """The registry's cache_* keys as a JSON-ready dict (bench extras
    metric_version 14 / obs_report "cache:" section); named to stay
    clear of utils/jaxcache.cache_extras, the compile-cache gauges.
    Empty when nothing probed the result cache."""
    reg = reg if reg is not None else _REGISTRY
    out: Dict[str, object] = {}
    for k, v in sorted(reg.snapshot().items()):
        if k.startswith("cache_"):
            out[k] = round(v, 4) if isinstance(v, float) else v
    return out


# ------------------------------------------------------- sched telemetry

#: Canonical sched_* registry keys (docs/SCHEDULER.md documents each).
SCHED_KEYS = ("sched_rounds", "sched_windows", "sched_chunks",
              "sched_rounds_hist", "sched_survivor_frac",
              "sched_rounds_saved_frac", "sched_repack_overhead_s",
              "sched_dispatches_saved")


def publish_sched(telem, reg: Optional[MetricsRegistry] = None) -> None:
    """Write a SchedTelemetry's counters into the registry under the
    canonical ``sched_*`` keys — the one place their shape is defined."""
    reg = reg if reg is not None else _REGISTRY
    reg.set("sched_rounds", telem.rounds)
    reg.set("sched_windows", telem.windows)
    reg.set("sched_chunks", telem.chunks)
    reg.set("sched_rounds_hist",
            {str(k): v for k, v in sorted(telem.hist.items())})
    reg.set("sched_survivor_frac",
            [round(f, 4) for f in telem.survivor_frac()])
    reg.set("sched_rounds_saved_frac", round(telem.rounds_saved_frac(), 4))
    reg.set("sched_repack_overhead_s", round(telem.repack_s, 4))
    reg.set("sched_dispatches_saved", telem.dispatches_saved)


def sched_extras(reg: Optional[MetricsRegistry] = None
                 ) -> Dict[str, object]:
    """The registry's sched_* keys as a JSON-ready dict (bench extras)."""
    reg = reg if reg is not None else _REGISTRY
    return {k: reg.get(k) for k in SCHED_KEYS}


# ------------------------------------------------- fleet merge semantics

#: Merge kinds for cross-worker aggregation (racon_tpu/obs/fleet.py).
#: Every registry key has exactly one kind, decided by
#: :func:`merge_kind`, so the fleet aggregator never guesses:
#:
#: - ``sum``  — monotone counters (bytes, events, seconds of work);
#:   the fleet value is the sum over workers.
#: - ``max``  — peak gauges (queue depths); fleet value is the max.
#: - ``last`` — point-in-time gauges and per-run snapshots (fleet
#:   shape, cache population, derived ratios, structured sched
#:   telemetry); summing them across workers would be meaningless, so
#:   the most recent worker snapshot wins.
#: - ``hist`` — fixed-bucket histograms (:data:`HIST_BUCKETS`); the
#:   fleet value is the per-bucket sum (plus summed sum/count), which
#:   is exact: bucket bounds are declared per family, so every worker
#:   bins identically.
MERGE_SUM = "sum"
MERGE_MAX = "max"
MERGE_LAST = "last"
MERGE_HIST = "hist"

#: Exact keys whose fleet merge is ``last`` (point-in-time gauges).
#: ``sched_flag_pulls``/``sched_flag_pull_s`` are NOT here — despite
#: the prefix they are inc'd counters, so they sum.
_MERGE_LAST_KEYS = frozenset({
    "dist_workers", "dist_shards", "dist_n_targets",
    "ovl_device_fraction", "walk_chain_len",
    "pipe_overlap_efficiency",
    "jax_cache_enabled", "jax_cache_entries_start",
    "jax_cache_entries_added",
    "sched_rounds", "sched_windows", "sched_chunks",
    "sched_rounds_hist", "sched_survivor_frac",
    "sched_rounds_saved_frac", "sched_repack_overhead_s",
    "sched_dispatches_saved",
    # The autoscaler's target-size gauge (distributed/autoscaler.py):
    # folded into the fleet model from the supervisor heartbeat, never
    # summed across workers.
    "fleet_target_workers",
    # Ingest plane gauges (io/ingest.py): per-run derived ratio and the
    # gate state — the ingest_* byte/second/record counters sum.
    "ingest_fraction_of_wall", "ingest_enabled",
    # Decoupled-walk gauges (record_walk above): gate state and the
    # derived hidden fraction — the walk_* second/dispatch counters sum
    # and walk_queue_peak maxes via its suffix.
    "walk_async_enabled", "walk_hidden_fraction",
    # Daemon gauges (racon_tpu/server/): in-flight jobs, mean batch
    # occupancy, completion rate — the serve_* event/window counters
    # sum and serve_queue_depth_peak maxes via its suffix.
    "serve_active_jobs", "serve_batch_occupancy", "serve_jobs_per_min",
    # Freshness stamp for the two gauges above (set_serve_rate /
    # record_serve_batch): the latest wall clock wins.
    "serve_rate_wall_s",
    # Result-cache derived gauge (record_cache above): the hit ratio
    # re-derives from the totals on every event, so the most recent
    # snapshot wins — the cache_* hit/miss/store/evict counters sum.
    "cache_hit_ratio",
    # Gateway gauges (racon_tpu/gateway/): the policy's latest fleet
    # sizing decision and the bench throughput/compile-skip readings —
    # the gate_* routed/adoption/run counters sum.
    "gate_fleet_target", "gate_fleet_jobs_per_min",
    "gate_compile_skip_s",
    # Ava plane gauges (record_ava_plan / set_ava_bench above): the
    # bucket plan is identical on every worker and the bench readings
    # are per-run measurements, so the latest snapshot wins —
    # ava_peak_rss_mb is listed despite its name because it lacks the
    # ``_peak`` suffix the max rule keys on.
    "ava_targets", "ava_buckets", "ava_quantum", "ava_compile_budget",
    "ava_pad_frac", "ava_reads_per_sec", "ava_peak_rss_mb",
    "ava_manifest_bytes_per_target",
})


def merge_kind(key: str) -> str:
    """The fleet merge kind for a registry key (docs/OBSERVABILITY.md
    documents the table). Unknown keys default to ``sum`` — new
    counters aggregate correctly without registration; a new gauge must
    be added to ``_MERGE_LAST_KEYS`` (or end in ``_peak``) or the fleet
    number is wrong, which tests/test_fleet_obs.py pins for the known
    key set."""
    if key in HIST_BUCKETS:
        return MERGE_HIST
    if key in _MERGE_LAST_KEYS:
        return MERGE_LAST
    if key.endswith("_peak"):
        return MERGE_MAX
    return MERGE_SUM


#: Declared metric contract — one row per registry key family, the
#: ground truth the metrics-contract lint rule (racon_tpu/analysis,
#: MET001–MET004) checks against the recorded keys, merge_kind(), and
#: the docs/OBSERVABILITY.md producer table. Each row is
#: ``(pattern, merge kind, doc token)``: ``*`` in a pattern matches one
#: runtime-named segment (site slug, stage name, phase slug); the doc
#: token must appear verbatim in docs/OBSERVABILITY.md. Exact keys
#: sort before the wildcard that would shadow them. ``_``-prefixed
#: keys are internal (excluded from snapshots) and carry no row.
METRIC_SPECS = (
    ("adaptive_early_exits", MERGE_SUM, "adaptive_early_exits"),
    ("adaptive_rounds_executed", MERGE_SUM, "adaptive_rounds_executed"),
    ("adaptive_rounds_scheduled", MERGE_SUM, "adaptive_rounds_scheduled"),
    ("align_phase_seconds", MERGE_SUM, "align_phase_seconds"),
    ("ava_buckets", MERGE_LAST, "ava_buckets"),
    ("ava_compile_budget", MERGE_LAST, "ava_compile_budget"),
    ("ava_manifest_bytes_per_target", MERGE_LAST,
     "ava_manifest_bytes_per_target"),
    ("ava_pad_frac", MERGE_LAST, "ava_pad_frac"),
    ("ava_peak_rss_mb", MERGE_LAST, "ava_peak_rss_mb"),
    ("ava_quantum", MERGE_LAST, "ava_quantum"),
    ("ava_reads_per_sec", MERGE_LAST, "ava_reads_per_sec"),
    ("ava_targets", MERGE_LAST, "ava_targets"),
    ("cache_bytes", MERGE_SUM, "cache_bytes"),
    ("cache_evictions_total", MERGE_SUM, "cache_evictions_total"),
    ("cache_hit_ratio", MERGE_LAST, "cache_hit_ratio"),
    ("cache_hits_total", MERGE_SUM, "cache_hits_total"),
    ("cache_misses_total", MERGE_SUM, "cache_misses_total"),
    ("cache_stores_total", MERGE_SUM, "cache_stores_total"),
    ("cache_verify_fail_total", MERGE_SUM, "cache_verify_fail_total"),
    ("d2h_bytes", MERGE_SUM, "d2h_bytes"),
    ("d2h_s", MERGE_SUM, "d2h_s"),
    ("d2h_transfers", MERGE_SUM, "d2h_transfers"),
    ("device_dispatches", MERGE_SUM, "device_dispatches"),
    ("dist_n_targets", MERGE_LAST, "dist_n_targets"),
    ("dist_shards", MERGE_LAST, "dist_shards"),
    ("dist_workers", MERGE_LAST, "dist_workers"),
    ("dist_*", MERGE_SUM, "dist_claims"),
    ("dispatch_round_s", MERGE_HIST, "dispatch_round_s"),
    ("fleet_target_workers", MERGE_LAST, "fleet_target_workers"),
    ("flight_dump_write_s", MERGE_SUM, "flight_dump_write_s"),
    ("flight_dumps_total", MERGE_SUM, "flight_dumps_total"),
    ("gate_compile_skip_s", MERGE_LAST, "gate_compile_skip_s"),
    ("gate_fleet_jobs_per_min", MERGE_LAST, "gate_fleet_jobs_per_min"),
    ("gate_fleet_target", MERGE_LAST, "gate_fleet_target"),
    ("gate_*", MERGE_SUM, "gate_routed_fleet"),
    ("h2d_bytes", MERGE_SUM, "h2d_bytes"),
    ("h2d_s", MERGE_SUM, "h2d_s"),
    ("h2d_transfer_s", MERGE_HIST, "h2d_transfer_s"),
    ("h2d_transfers", MERGE_SUM, "h2d_transfers"),
    ("ingest_blocks", MERGE_SUM, "ingest_blocks"),
    ("ingest_bytes_in", MERGE_SUM, "ingest_bytes_in"),
    ("ingest_bytes_out", MERGE_SUM, "ingest_bytes_out"),
    ("ingest_enabled", MERGE_LAST, "ingest_enabled"),
    ("ingest_fraction_of_wall", MERGE_LAST, "ingest_fraction_of_wall"),
    ("ingest_inflate_s", MERGE_SUM, "ingest_inflate_s"),
    ("ingest_parse_s", MERGE_SUM, "ingest_parse_s"),
    ("ingest_raw_bytes", MERGE_SUM, "ingest_raw_bytes"),
    ("ingest_records", MERGE_SUM, "ingest_records"),
    ("ingest_wait_s", MERGE_SUM, "ingest_wait_s"),
    ("jax_cache_enabled", MERGE_LAST, "jax_cache_enabled"),
    ("jax_cache_entries_added", MERGE_LAST, "jax_cache_entries_added"),
    ("jax_cache_entries_start", MERGE_LAST, "jax_cache_entries_start"),
    ("ovl_device_fraction", MERGE_LAST, "ovl_device_fraction"),
    ("ovl_device_jobs", MERGE_SUM, "ovl_device_jobs"),
    ("ovl_native_jobs", MERGE_SUM, "ovl_native_jobs"),
    ("ovl_tiles_exec", MERGE_SUM, "ovl_tiles_exec"),
    ("phase_seconds_*", MERGE_SUM, "phase_seconds_"),
    ("pipe_overlap_efficiency", MERGE_LAST, "pipe_overlap_efficiency"),
    ("pipe_queue_*_get_wait_s", MERGE_SUM, "pipe_queue_"),
    ("pipe_queue_*_peak", MERGE_MAX, "pipe_queue_"),
    ("pipe_queue_*_put_wait_s", MERGE_SUM, "pipe_queue_"),
    ("pipe_runs", MERGE_SUM, "pipe_runs"),
    ("pipe_stage_*_busy_s", MERGE_SUM, "pipe_stage_"),
    ("pipe_stage_*_items", MERGE_SUM, "pipe_stage_"),
    ("pipe_stage_*_stall_in_s", MERGE_SUM, "pipe_stage_"),
    ("pipe_stage_*_stall_out_s", MERGE_SUM, "pipe_stage_"),
    ("pipe_stall_events", MERGE_SUM, "pipe_stall_events"),
    ("pipe_wall_s", MERGE_SUM, "pipe_wall_s"),
    ("poa_windows_total", MERGE_SUM, "poa_windows_total"),
    ("redo_device_windows", MERGE_SUM, "redo_device_windows"),
    ("redo_host_windows", MERGE_SUM, "redo_host_windows"),
    ("redo_passes", MERGE_SUM, "redo_passes"),
    ("res_ckpt_*", MERGE_SUM, "res_ckpt_commits"),
    ("res_degraded_chunks", MERGE_SUM, "res_degraded_chunks"),
    ("res_degraded_windows", MERGE_SUM, "res_degraded_windows"),
    ("res_fault_injected_total", MERGE_SUM, "res_fault_injected_total"),
    ("res_fault_site_*", MERGE_SUM, "res_fault_site_"),
    ("res_retry_backoff_s", MERGE_SUM, "res_retry_backoff_s"),
    ("res_retry_exhausted", MERGE_SUM, "res_retry_exhausted"),
    ("res_retry_site_*", MERGE_SUM, "res_retry_site_"),
    ("res_retry_total", MERGE_SUM, "res_retry_total"),
    ("res_watchdog_breach_total", MERGE_SUM, "res_watchdog_breach_total"),
    ("res_watchdog_site_*", MERGE_SUM, "res_watchdog_site_"),
    ("res_watchdog_terminal_total", MERGE_SUM,
     "res_watchdog_terminal_total"),
    ("sched_dispatches_saved", MERGE_LAST, "sched_"),
    ("sched_flag_pull_s", MERGE_SUM, "sched_flag_pull_s"),
    ("sched_flag_pulls", MERGE_SUM, "sched_flag_pulls"),
    ("sched_repack_overhead_s", MERGE_LAST, "sched_"),
    ("sched_rounds", MERGE_LAST, "sched_"),
    ("sched_rounds_hist", MERGE_LAST, "sched_"),
    ("sched_rounds_saved_frac", MERGE_LAST, "sched_"),
    ("sched_survivor_frac", MERGE_LAST, "sched_"),
    ("sched_chunks", MERGE_LAST, "sched_"),
    ("sched_windows", MERGE_LAST, "sched_"),
    ("serve_active_jobs", MERGE_LAST, "serve_active_jobs"),
    ("serve_batch_occupancy", MERGE_LAST, "serve_batch_occupancy"),
    ("serve_batch_windows", MERGE_SUM, "serve_batch_windows"),
    ("serve_batches", MERGE_SUM, "serve_batches"),
    ("serve_job_latency_s", MERGE_HIST, "serve_job_latency_s"),
    ("serve_jobs_per_min", MERGE_LAST, "serve_jobs_per_min"),
    ("serve_jobs_*", MERGE_SUM, "serve_jobs_"),
    ("serve_queue_depth_peak", MERGE_MAX, "serve_queue_depth_peak"),
    ("serve_queue_wait_s", MERGE_HIST, "serve_queue_wait_s"),
    ("serve_rate_wall_s", MERGE_LAST, "serve_rate_wall_s"),
    ("serve_tenant_wait_s", MERGE_SUM, "serve_tenant_wait_s"),
    ("walk_async_enabled", MERGE_LAST, "walk_async_enabled"),
    ("walk_chain_len", MERGE_LAST, "walk_chain_len"),
    ("walk_dispatches", MERGE_SUM, "walk_dispatches"),
    ("walk_fused_chunks", MERGE_SUM, "walk_fused_chunks"),
    ("walk_hidden_fraction", MERGE_LAST, "walk_hidden_fraction"),
    ("walk_hidden_s", MERGE_HIST, "walk_hidden_s"),
    ("walk_overlap_s", MERGE_SUM, "walk_overlap_s"),
    ("walk_queue_peak", MERGE_MAX, "walk_queue_peak"),
    ("walk_seconds", MERGE_SUM, "walk_seconds"),
)


def merge_values(key: str, values) -> object:
    """Fold per-worker values for ``key`` by its merge kind. Non-numeric
    values (sched hist dicts, fraction lists) always take the last —
    there is no meaningful sum/max for them — except histogram dicts,
    which fold per-bucket."""
    vals = [v for v in values if v is not None]
    if not vals:
        return None
    kind = merge_kind(key)
    if kind == MERGE_HIST:
        n = len(HIST_BUCKETS[key]) + 1
        out = {"buckets": [0] * n, "sum": 0.0, "count": 0}
        for v in vals:
            if not isinstance(v, dict):
                continue
            for i, c in enumerate(v.get("buckets", ())[:n]):
                out["buckets"][i] += int(c)
            out["sum"] = round(out["sum"] + float(v.get("sum", 0.0)), 6)
            out["count"] += int(v.get("count", 0))
        return out
    numeric = all(isinstance(v, (int, float)) and
                  not isinstance(v, bool) for v in vals)
    if not numeric or kind == MERGE_LAST:
        return vals[-1]
    if kind == MERGE_MAX:
        return max(vals)
    total = sum(vals)
    return round(total, 6) if isinstance(total, float) else total


# -------------------------------------------------- phases and windows

def _phase_slug(msg: str) -> str:
    """Registry-key slug for a logger phase message:
    ``"[racon_tpu::Polisher::initialize] loaded sequences"`` ->
    ``"initialize_loaded_sequences"``."""
    msg = msg.strip()
    if msg.startswith("[") and "]" in msg:
        head, _, rest = msg.partition("]")
        msg = head[1:].rsplit("::", 1)[-1] + " " + rest
    out = []
    for ch in msg.lower():
        out.append(ch if ch.isalnum() else "_")
    slug = "_".join(filter(None, "".join(out).split("_")))
    return slug[:64] or "unnamed"


def record_phase_seconds(msg: str, seconds: float,
                         reg: Optional[MetricsRegistry] = None) -> None:
    """Account one completed logger phase (utils/logger.py) as
    ``phase_seconds_<slug>`` plus the ``phase_seconds_total`` roll-up —
    the per-worker phase decomposition the fleet aggregator and the
    OpenMetrics exporter publish (the trace-span equivalent only exists
    when tracing is on)."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc(f"phase_seconds_{_phase_slug(msg)}", float(seconds))
    reg.inc("phase_seconds_total", float(seconds))


def record_windows(n: int,
                   reg: Optional[MetricsRegistry] = None) -> None:
    """Account ``n`` polished windows (ops/poa.py consensus_windows).
    ``poa_windows_total`` is cumulative across chunks, contigs, and
    shards — unlike ``sched_windows`` (a per-run snapshot overwritten
    by each polisher instance), so per-worker windows/s in the fleet
    report divides this by the snapshot's wall clock."""
    reg = reg if reg is not None else _REGISTRY
    reg.inc("poa_windows_total", int(n))


def sched_summary_line(reg: Optional[MetricsRegistry] = None) -> str:
    """The polisher's one-line stderr scheduler summary, formatted from
    the registry (format kept stable across the registry refactor)."""
    reg = reg if reg is not None else _REGISTRY
    hist = reg.get("sched_rounds_hist", {}) or {}
    hist_s = " ".join(f"r{k}:{v}" for k, v in
                      sorted(hist.items(), key=lambda kv: int(kv[0])))
    saved = float(reg.get("sched_rounds_saved_frac", 0.0))
    repack = float(reg.get("sched_repack_overhead_s", 0.0))
    return (f"windows={reg.get('sched_windows', 0)} "
            f"chunks={reg.get('sched_chunks', 0)} "
            f"frozen[{hist_s}] "
            f"rounds_saved={saved:.0%} "
            f"repack={repack:.3f}s")
