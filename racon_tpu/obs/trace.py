"""Structured run tracer: nested spans as JSONL.

Disabled by default (the module-level :data:`NULL` tracer is a no-op on
every call — no file handle, no clock reads beyond the caller's own).
Enable by pointing ``RACON_TPU_TRACE`` at a file path, or pass
``--trace <path>`` to the CLI (which calls :func:`configure`).

Trace format (one JSON object per line):

- ``{"ev": "begin", "schema": 1, "unix_time": ...}`` — first line.
- ``{"ev": "span", "id": N, "parent": M|null, "kind": ..., "name": ...,
  "t0": seconds-since-begin, "dur_s": ..., ...attrs}`` — one line per
  *closed* span; children therefore appear before their parent. ``kind``
  is one of run/phase/chunk/round/dispatch/transfer (plus free-form
  kinds from future callers); numeric attrs (bytes, lanes, rounds, ...)
  ride at the top level of the object.
- ``{"ev": "metrics", ...}`` — a metrics-registry snapshot, written by
  :meth:`Tracer.finish` (the CLI and bench call it on exit).

``RACON_TPU_TRACE_XPROF=1`` additionally wraps every span in a
``jax.profiler.TraceAnnotation`` so spans land in XLA device profiles;
it is off by default because it imports jax at first span.

Spans nest per thread (a thread-local stack supplies ``parent``); file
writes are serialized by a lock. Close-time emission keeps the hot path
to two ``time.perf_counter()`` calls and one dict build per span.
"""

from __future__ import annotations

import json
import os
from racon_tpu.utils import envspec
import threading
import time
from typing import Optional

SCHEMA_VERSION = 1

ENV_TRACE = "RACON_TPU_TRACE"
ENV_XPROF = "RACON_TPU_TRACE_XPROF"
ENV_TRACE_CTX = "RACON_TPU_TRACE_CTX"

# How many hex chars of the JobSpec fingerprint become the trace id.
TRACE_ID_LEN = 16


class TraceContext:
    """Cross-process trace correlation: ``trace_id`` names the job (a
    prefix of the JobSpec fingerprint, so every process that polishes
    the same job derives the same id) and ``parent_id`` is the span id,
    in the minting process, that causally precedes the handoff. The
    encoded form (``"<trace_id>:<parent_id>"``) rides the
    ``RACON_TPU_TRACE_CTX`` environment variable and the ledger's
    ``meta.json``; :func:`parse_trace_ctx` treats anything malformed as
    absent, so a garbled handoff degrades to a fresh root trace instead
    of crashing the worker."""

    __slots__ = ("trace_id", "parent_id")

    def __init__(self, trace_id: str, parent_id: int):
        self.trace_id = trace_id
        self.parent_id = parent_id

    def encode(self) -> str:
        return f"{self.trace_id}:{self.parent_id}"

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"TraceContext({self.trace_id!r}, {self.parent_id})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, TraceContext)
                and other.trace_id == self.trace_id
                and other.parent_id == self.parent_id)


def mint_trace_context(fingerprint: str, parent_id: int = 0) -> TraceContext:
    """Derive a job's trace context from its run fingerprint and the
    span that minted it (the daemon's ``serve submitted`` point)."""
    return TraceContext(str(fingerprint)[:TRACE_ID_LEN], int(parent_id))


def parse_trace_ctx(text) -> Optional[TraceContext]:
    """Decode ``"<trace_id>:<parent_id>"``; None on anything malformed
    (empty, missing separator, non-integer parent, blank id)."""
    if not text or not isinstance(text, str):
        return None
    head, sep, tail = text.strip().partition(":")
    if not sep or not head:
        return None
    try:
        parent = int(tail)
    except ValueError:
        return None
    return TraceContext(head, parent)


def env_trace_ctx() -> str:
    """The raw (already-validated) encoded context from the
    environment, or "" — the ledger stores this string verbatim in
    meta.json so late-joining workers can adopt it."""
    ctx = parse_trace_ctx(envspec.read(ENV_TRACE_CTX))
    return ctx.encode() if ctx is not None else ""


def adopt_trace_context(encoded=None, tracer=None) -> Optional[TraceContext]:
    """Adopt a handed-off trace context into the process tracer's
    span context. ``encoded=None`` reads ``RACON_TPU_TRACE_CTX``.
    Malformed or absent input is NOT an error: the process keeps a
    fresh root trace (returns None, sets nothing). Never raises."""
    if encoded is None:
        try:
            encoded = envspec.read(ENV_TRACE_CTX)
        except Exception:
            return None
    ctx = parse_trace_ctx(encoded)
    if ctx is None:
        return None
    tr = tracer if tracer is not None else get_tracer()
    tr.set_context(trace_id=ctx.trace_id, parent_id=ctx.parent_id)
    return ctx


class _NullSpan:
    """Shared no-op span: context manager with inert add/end."""
    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def add(self, **attrs) -> "_NullSpan":
        return self

    def end(self) -> None:
        pass


_NULL_SPAN = _NullSpan()


class NullTracer:
    """Disabled tracer: every operation is a no-op."""

    enabled = False

    def span(self, kind: str, name: str, **attrs):
        return _NULL_SPAN

    def emit(self, kind: str, name: str, t0_perf: float, dur_s: float,
             **attrs) -> int:
        return 0

    def point(self, kind: str, name: str, dur_s: float = 0.0,
              **attrs) -> int:
        return 0

    def set_context(self, **attrs) -> None:
        pass

    def snapshot_stack(self) -> list:
        return []

    def install_stack(self, stack: list) -> None:
        pass

    def finish(self, metrics: Optional[dict] = None) -> None:
        pass


NULL = NullTracer()


class _Span:
    __slots__ = ("tracer", "id", "parent", "kind", "name", "attrs",
                 "t0", "_xprof", "_done")

    def __init__(self, tracer: "Tracer", kind: str, name: str, attrs: dict):
        self.tracer = tracer
        self.kind = kind
        self.name = name
        self.attrs = attrs
        self._xprof = None
        self._done = False
        self.id, self.parent = tracer._push(self)
        self.t0 = time.perf_counter()
        if tracer._xprof:
            try:
                import jax
                self._xprof = jax.profiler.TraceAnnotation(
                    f"{kind}:{name}")
                self._xprof.__enter__()
            except Exception:
                self._xprof = None

    def add(self, **attrs) -> "_Span":
        """Attach counters to the span (merged into its JSONL record)."""
        self.attrs.update(attrs)
        return self

    def end(self) -> None:
        if self._done:
            return
        self._done = True
        dur = time.perf_counter() - self.t0
        if self._xprof is not None:
            try:
                self._xprof.__exit__(None, None, None)
            except Exception:
                pass
        self.tracer._pop(self, dur)

    def __enter__(self) -> "_Span":
        return self

    def __exit__(self, *exc) -> bool:
        self.end()
        return False


class Tracer:
    """JSONL span writer (see module docstring for the format)."""

    enabled = True

    def __init__(self, path: str):
        self.path = path
        self._t0 = time.perf_counter()
        self._lock = threading.Lock()
        self._local = threading.local()
        # Ids start at 1: a TraceContext's parent_id of 0 means "no
        # parent span" (fresh root), so no real span may claim it.
        self._next_id = 1                 # guarded-by: _lock
        # Process-wide span attributes (worker_id/shard/run_fp) merged
        # into every span record; explicit span attrs win on key clash.
        self._context: dict = {}          # guarded-by: _lock
        self._xprof = envspec.read(ENV_XPROF) not in ("", "0",
                                                            "false")
        # Spans stream to a ``.part`` sidecar; finish() promotes it to
        # ``path`` atomically, so readers of ``path`` never observe a
        # half-written trace (a killed run leaves only the sidecar).
        self._part = path + ".part"
        self._fh = open(self._part, "w",  # lint: atomic-ok (streamed sidecar; finish() promotes via atomic_finalize)
                        encoding="utf-8")
        self._write({"ev": "begin", "schema": SCHEMA_VERSION,
                     "unix_time": time.time()})

    # ------------------------------------------------------------- internals

    def _stack(self):
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def _write(self, obj: dict) -> None:
        line = json.dumps(obj, separators=(",", ":"))
        if obj.get("ev") == "span":
            from racon_tpu.obs import flightrec
            flightrec.note_span(obj)
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line + "\n")
            self._fh.flush()

    def _push(self, span: _Span):
        st = self._stack()
        parent = st[-1].id if st else None
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        st.append(span)
        return sid, parent

    def _pop(self, span: _Span, dur: float) -> None:
        st = self._stack()
        # Tolerate out-of-order ends (manual .end() mixed with with-blocks):
        # remove the span wherever it sits.
        if span in st:
            st.remove(span)
        self._write({"ev": "span", "id": span.id, "parent": span.parent,
                     "kind": span.kind, "name": span.name,
                     "t0": round(span.t0 - self._t0, 6),
                     "dur_s": round(dur, 6),
                     **self._context, **span.attrs})

    # ------------------------------------------------------------ public API

    def span(self, kind: str, name: str, **attrs) -> _Span:
        """Open a nested span; close with ``with`` or ``.end()``."""
        return _Span(self, kind, name, attrs)

    def emit(self, kind: str, name: str, t0_perf: float, dur_s: float,
             **attrs) -> int:
        """Record a span that already ran, from its own perf_counter
        start (utils/logger.py phases use this: the logger only learns
        the phase name when the phase ends). Returns the span id so
        callers can mint a :class:`TraceContext` parented on it."""
        st = self._stack()
        parent = st[-1].id if st else None
        with self._lock:
            sid = self._next_id
            self._next_id += 1
        self._write({"ev": "span", "id": sid, "parent": parent,
                     "kind": kind, "name": name,
                     "t0": round(max(t0_perf - self._t0, 0.0), 6),
                     "dur_s": round(max(dur_s, 0.0), 6),
                     **self._context, **attrs})
        return sid

    def point(self, kind: str, name: str, dur_s: float = 0.0,
              **attrs) -> int:
        """Record an instantaneous-ish event (e.g. one transfer) ending
        now, with ``dur_s`` of lead time. Returns the span id."""
        return self.emit(kind, name, time.perf_counter() - dur_s, dur_s,
                         **attrs)

    def set_context(self, **attrs) -> None:
        """Merge process-wide attributes (``worker_id``/``shard``/
        ``run_fp``) into every subsequent span record. ``None`` values
        drop the key — workers call ``set_context(shard=None)`` when a
        lease is released. Explicit per-span attrs shadow the context
        on clashes, so recorders keep full control of their own keys."""
        with self._lock:
            for k, v in attrs.items():
                if v is None:
                    self._context.pop(k, None)
                else:
                    self._context[k] = v

    def snapshot_stack(self) -> list:
        """A COPY of the calling thread's open-span stack, for handing
        to a helper thread (watchdog guard workers) so spans it emits
        keep their parents."""
        return list(self._stack())

    def install_stack(self, stack: list) -> None:
        """Adopt ``stack`` (from :meth:`snapshot_stack`) as THIS
        thread's span stack. The list is copied, so a thread abandoned
        mid-job can never corrupt the donor's stack; spans opened and
        closed on this thread pop themselves as usual, and spans from
        the donor stack are parent references only — this thread must
        not close them."""
        self._local.stack = list(stack)

    def finish(self, metrics: Optional[dict] = None) -> None:
        """Write a final metrics snapshot, then atomically promote the
        ``.part`` sidecar to the configured path."""
        if metrics:
            self._write({"ev": "metrics", **metrics})
        with self._lock:
            if self._fh is None:
                return
            self._fh.close()
            self._fh = None
        from racon_tpu.utils.atomicio import atomic_finalize
        atomic_finalize(self._part, self.path)


_tracer: Optional[object] = None


def configure(path: Optional[str] = None):
    """Install the process tracer. ``path=None`` reads RACON_TPU_TRACE;
    empty/unset keeps tracing disabled. Idempotent for the same path;
    a new path replaces (and closes) the previous tracer."""
    global _tracer
    path = path or envspec.read(ENV_TRACE)
    if not path:
        if _tracer is None:
            _tracer = NULL
        return _tracer
    if isinstance(_tracer, Tracer):
        if _tracer.path == path:
            return _tracer
        _tracer.finish()
    _tracer = Tracer(path)
    return _tracer


def get_tracer():
    """The process tracer; configures from the environment on first use
    so library runs honor RACON_TPU_TRACE without CLI involvement."""
    if _tracer is None:
        return configure()
    return _tracer
