"""OpenMetrics/Prometheus text exporter for the metrics plane.

Renders either a single-process registry snapshot or a fleet model
(obs/fleet.py::aggregate) as OpenMetrics text — the format Prometheus
scrapes and ``promtool`` parses:

- every metric is ``racon_tpu_<key>`` (keys sanitized to the metric
  charset), preceded by stable ``# HELP`` / ``# TYPE`` lines;
- merge kind decides the type: ``sum`` keys are counters (sample name
  gets the mandatory ``_total`` suffix), ``max``/``last`` keys are
  gauges;
- fleet renders additionally emit per-worker series
  (``racon_tpu_worker_*{worker="..."}``) and per-shard steal counts;
- output is **byte-stable**: keys sorted, numbers formatted through one
  deterministic path, no timestamps — two renders of the same model are
  identical, which tests and the smoke gate on;
- the text ends with the ``# EOF`` terminator OpenMetrics requires.

Non-numeric registry values (the sched histogram dict, fraction lists)
have no OpenMetrics representation and are skipped — they stay
available through bench extras and the fleet JSON model.

Entry points: :func:`render_registry`, :func:`render_fleet`,
:func:`validate_openmetrics` (the smoke/test gate), and
:func:`serve_metrics` — a stdlib ThreadingHTTPServer pull endpoint
the CLI starts when ``RACON_TPU_METRICS_PORT`` is set.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Tuple

from racon_tpu.obs.metrics import (HIST_BUCKETS, MERGE_HIST, MERGE_SUM,
                                   merge_kind)

PREFIX = "racon_tpu_"
CONTENT_TYPE = ("application/openmetrics-text; version=1.0.0; "
                "charset=utf-8")
ENV_METRICS_PORT = "RACON_TPU_METRICS_PORT"


def _sanitize(key: str) -> str:
    """Map a registry key into the OpenMetrics name charset
    ``[a-zA-Z0-9_]`` (leading digits get an underscore)."""
    out = "".join(ch if ch.isalnum() or ch == "_" else "_"
                  for ch in key)
    if out and out[0].isdigit():
        out = "_" + out
    return out or "unnamed"


def _fmt(value) -> str:
    """One deterministic number path — byte-stability depends on it."""
    if isinstance(value, bool):
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    f = float(value)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _escape_label(value: str) -> str:
    return (str(value).replace("\\", "\\\\").replace("\n", "\\n")
            .replace('"', '\\"'))


def _labels(pairs: List[Tuple[str, str]]) -> str:
    if not pairs:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"' for k, v in pairs)
    return "{" + body + "}"


def _numeric(value) -> bool:
    # bool is an int subclass; _fmt renders it 1/0.
    return isinstance(value, (int, float))


class _Family:
    """One metric family: TYPE/HELP header + sorted samples."""

    __slots__ = ("name", "mtype", "help", "samples")

    def __init__(self, name: str, mtype: str, help_text: str):
        self.name = name
        self.mtype = mtype
        self.help = help_text
        self.samples: List[Tuple[str, str]] = []

    def add(self, labels: List[Tuple[str, str]], value) -> None:
        suffix = "_total" if self.mtype == "counter" else ""
        self.samples.append(
            (f"{self.name}{suffix}{_labels(labels)}", _fmt(value)))

    def add_hist(self, labels: List[Tuple[str, str]], hist: Dict,
                 bounds) -> None:
        """One histogram series: cumulative ``_bucket`` samples in
        declared ``le`` order (ending at ``+Inf``), then ``_sum`` and
        ``_count``. Appended in order — render() keeps histogram
        samples unsorted because ``le`` values sort numerically, not
        lexically."""
        buckets = list(hist.get("buckets", ()))
        buckets += [0] * (len(bounds) + 1 - len(buckets))
        cum = 0
        for i, bound in enumerate(bounds):
            cum += int(buckets[i])
            self.samples.append((
                f"{self.name}_bucket"
                f"{_labels(labels + [('le', _fmt(float(bound)))])}",
                _fmt(cum)))
        cum += int(buckets[len(bounds)])
        self.samples.append((
            f"{self.name}_bucket{_labels(labels + [('le', '+Inf')])}",
            _fmt(cum)))
        self.samples.append((f"{self.name}_sum{_labels(labels)}",
                             _fmt(float(hist.get('sum', 0.0)))))
        self.samples.append((f"{self.name}_count{_labels(labels)}",
                             _fmt(int(hist.get('count', 0)))))

    def render(self, out: List[str]) -> None:
        out.append(f"# HELP {self.name} {self.help}")
        out.append(f"# TYPE {self.name} {self.mtype}")
        samples = self.samples if self.mtype == "histogram" \
            else sorted(self.samples)
        for sample, value in samples:
            out.append(f"{sample} {value}")


def _family_for_key(key: str) -> _Family:
    kind = merge_kind(key)
    name = PREFIX + _sanitize(key)
    if kind == MERGE_SUM and name.endswith("_total"):
        # The sample suffix is appended by _Family.add; a key that
        # already says _total (poa_windows_total) must not double it.
        name = name[:-len("_total")]
    if kind == MERGE_HIST:
        mtype = "histogram"
    else:
        mtype = "counter" if kind == MERGE_SUM else "gauge"
    return _Family(name, mtype,
                   f"racon_tpu metric {key} (merge={kind})")


def _render(families: List[_Family]) -> str:
    families = sorted(families, key=lambda f: f.name)
    out: List[str] = []
    for fam in families:
        fam.render(out)
    out.append("# EOF")
    return "\n".join(out) + "\n"


def render_registry(snapshot: Dict,
                    labels: Optional[List[Tuple[str, str]]] = None
                    ) -> str:
    """Render one registry snapshot (MetricsRegistry.snapshot()) as
    OpenMetrics text. ``labels`` are attached to every sample (the pull
    endpoint tags ``worker``)."""
    labels = labels or []
    fams: Dict[str, _Family] = {}
    for key in sorted(snapshot):
        value = snapshot[key]
        is_hist = key in HIST_BUCKETS and isinstance(value, dict)
        if not is_hist and not _numeric(value):
            continue
        fam = _family_for_key(key)
        if fam.name in fams:
            fam = fams[fam.name]
        else:
            fams[fam.name] = fam
        if is_hist:
            fam.add_hist(labels, value, HIST_BUCKETS[key])
        else:
            fam.add(labels, value)
    return _render(list(fams.values()))


def render_fleet(model: Dict) -> str:
    """Render a fleet model (obs/fleet.py::aggregate) as OpenMetrics:
    fleet-wide merged metrics unlabeled, per-worker rate/wall/final
    series labeled ``worker``, per-shard steal counts labeled
    ``shard``."""
    fams: Dict[str, _Family] = {}

    def fam(key_or_fam) -> _Family:
        f = key_or_fam if isinstance(key_or_fam, _Family) \
            else _family_for_key(key_or_fam)
        return fams.setdefault(f.name, f)

    for key in sorted(model.get("fleet", {})):
        value = model["fleet"][key]
        if key in HIST_BUCKETS and isinstance(value, dict):
            fam(key).add_hist([], value, HIST_BUCKETS[key])
        elif _numeric(value):
            fam(key).add([], value)

    n = _Family(PREFIX + "fleet_workers", "gauge",
                "racon_tpu fleet: worker shard count")
    fam(n).add([], model.get("n_workers", 0))
    s = _Family(PREFIX + "fleet_steals", "counter",
                "racon_tpu fleet: lease steals in events.jsonl")
    fam(s).add([], model.get("steals", 0))
    sp = _Family(PREFIX + "fleet_splits", "counter",
                 "racon_tpu fleet: dynamic shard splits in "
                 "events.jsonl")
    fam(sp).add([], model.get("splits", 0))

    per_worker = (
        ("windows_per_sec", "gauge",
         "racon_tpu worker: polished windows per wall second"),
        ("wall_s", "gauge", "racon_tpu worker: wall seconds at last "
                            "snapshot"),
        ("final", "gauge", "racon_tpu worker: 1 when the last snapshot "
                           "was a final (exit/SIGTERM) flush"),
    )
    for field, mtype, help_text in per_worker:
        f = fam(_Family(PREFIX + "worker_" + field, mtype, help_text))
        for wid in sorted(model.get("workers", {})):
            f.add([("worker", wid)],
                  model["workers"][wid].get(field, 0))

    timeline = model.get("timeline", {})
    if timeline:
        f = fam(_Family(PREFIX + "shard_steals", "counter",
                        "racon_tpu fleet: steals per ledger shard"))
        for name in sorted(timeline):
            f.add([("shard", name)],
                  sum(1 for e in timeline[name] if e["ev"] == "steal"))
    return _render(list(fams.values()))


# ---------------------------------------------------------- fleet health

#: A supervisor heartbeat older than this many of its own declared
#: intervals reads as a dead autoscaler (503 on /healthz).
SUPERVISOR_STALE_FACTOR = 5.0


def fleet_health(ledger_dir: str, base: Optional[Callable] = None,
                 stale_factor: float = SUPERVISOR_STALE_FACTOR) -> Dict:
    """The ``/healthz`` fleet view served when ``--ledger-dir`` is set:
    the process-local watchdog snapshot (``base``, typically
    watchdog.health_snapshot) extended with a ``"fleet"`` section —
    worker counts (live/evicted/retired/done from the supervisor
    heartbeat when one exists, else derived from metric-shard final
    flags), open shard count, and the autoscaler's last-decision age.

    Status degrades to ``"supervisor-dead"`` (→ 503, the probes'
    eviction signal) when a heartbeat EXISTS but is older than
    ``stale_factor`` × its own declared interval. A fleet that never
    ran a supervisor is not penalized for its absence.
    """
    import time as _time

    from racon_tpu.obs import fleet as _fleet

    snap: Dict = dict(base()) if base is not None else {"status": "ok"}
    view: Dict = {}
    live = exited = 0
    for sh in _fleet.load_worker_shards(_fleet.obs_dir_for(ledger_dir)):
        if sh["records"][-1].get("final"):
            exited += 1
        else:
            live += 1
    view["workers_live"] = live
    view["workers_exited"] = exited
    try:
        from racon_tpu.distributed.ledger import LedgerError, WorkLedger
        try:
            led = WorkLedger.attach(ledger_dir)
            view["open_shards"] = len(led.pending_shards())
            view["merge_done"] = led.merge_done()
        except LedgerError:
            view["open_shards"] = None  # meta not yet published
    except Exception:  # pragma: no cover — probe must never raise
        view["open_shards"] = None
    hb = _fleet.load_supervisor(ledger_dir)
    if hb is not None:
        age = max(0.0, _time.time() - float(hb.get("unix_time", 0.0)))
        interval = max(0.1, float(hb.get("interval_s", 1.0)))
        view["autoscaler"] = {
            "age_s": round(age, 3),
            "interval_s": interval,
            "target_workers": hb.get("target_workers"),
            "live_workers": hb.get("live_workers"),
            "done": bool(hb.get("done")),
        }
        for key in ("workers_live", "workers_evicted",
                    "workers_retired", "workers_done"):
            if key in hb:
                view[key] = hb[key]
        if age > stale_factor * interval and not hb.get("done") and \
                snap.get("status") == "ok":
            # The fleet may still finish on its own (workers hold the
            # ledger, not the supervisor), but nobody is replacing
            # evictions anymore — surface it as a liveness failure.
            snap["status"] = "supervisor-dead"
    snap["fleet"] = view
    return snap


# ------------------------------------------------------------ validation

def validate_openmetrics(text: str) -> List[str]:
    """Structural OpenMetrics check (the smoke/test gate — promtool is
    not in the image). Verifies: single trailing ``# EOF``; every
    sample parses as ``name[{labels}] value`` with a finite number;
    every sample's family has TYPE and HELP lines *before* it; counter
    samples end in ``_total``; histogram samples end in ``_bucket`` /
    ``_sum`` / ``_count`` and buckets carry an ``le`` label; families
    are not interleaved. Returns a list of problems (empty = valid)."""
    errors: List[str] = []
    lines = text.split("\n")
    if not text.endswith("\n"):
        errors.append("missing trailing newline")
    body = [ln for ln in lines if ln != ""]
    if not body or body[-1] != "# EOF":
        errors.append("missing '# EOF' terminator")
    if text.count("# EOF") != 1:
        errors.append("multiple '# EOF' terminators")
    types: Dict[str, str] = {}
    helps: Dict[str, str] = {}
    seen_families: List[str] = []
    for i, ln in enumerate(body):
        if ln == "# EOF":
            if i != len(body) - 1:
                errors.append("content after '# EOF'")
            break
        if ln.startswith("# TYPE ") or ln.startswith("# HELP "):
            parts = ln.split(" ", 3)
            if len(parts) < 4:
                errors.append(f"malformed meta line: {ln!r}")
                continue
            _, kw, fname, rest = parts
            table = types if kw == "TYPE" else helps
            if fname in table:
                errors.append(f"duplicate # {kw} for {fname}")
            table[fname] = rest
            if kw == "TYPE":
                if rest not in ("counter", "gauge", "histogram",
                                "summary", "info", "unknown"):
                    errors.append(f"bad type {rest!r} for {fname}")
                if seen_families and seen_families[-1] != fname:
                    seen_families.append(fname)
                elif not seen_families:
                    seen_families.append(fname)
            continue
        if ln.startswith("#"):
            errors.append(f"unexpected comment line: {ln!r}")
            continue
        # Sample: name[{labels}] value
        head, _, value = ln.rpartition(" ")
        if not head:
            errors.append(f"malformed sample line: {ln!r}")
            continue
        name = head.split("{", 1)[0]
        if "{" in head and not head.endswith("}"):
            errors.append(f"malformed labels in: {ln!r}")
        fam = name
        if fam not in types:
            # Family resolution: counters sample as <fam>_total,
            # histograms as <fam>_bucket/_sum/_count.
            for suf in ("_total", "_bucket", "_sum", "_count"):
                if name.endswith(suf) and name[:-len(suf)] in types:
                    fam = name[:-len(suf)]
                    break
        if fam not in types:
            errors.append(f"sample {name!r} has no # TYPE line")
            continue
        if fam not in helps:
            errors.append(f"sample {name!r} has no # HELP line")
        if types[fam] == "counter" and not name.endswith("_total"):
            errors.append(
                f"counter sample {name!r} lacks '_total' suffix")
        if types[fam] == "histogram":
            suffix = name[len(fam):]
            if suffix not in ("_bucket", "_sum", "_count"):
                errors.append(f"histogram sample {name!r} lacks "
                              f"'_bucket'/'_sum'/'_count' suffix")
            if suffix == "_bucket" and 'le="' not in head:
                errors.append(f"histogram bucket {name!r} lacks an "
                              f"'le' label")
        try:
            float(value)
        except ValueError:
            errors.append(f"non-numeric value {value!r} in: {ln!r}")
        if seen_families and seen_families[-1] != fam and \
                fam in seen_families:
            errors.append(f"family {fam!r} is interleaved")
    return errors


# ---------------------------------------------------------- pull endpoint

def serve_metrics(port: int, render: Callable[[], str],
                  host: str = "127.0.0.1", health=None):
    """Start a daemon-thread OpenMetrics pull endpoint on ``host:port``
    serving ``render()`` at every path. Returns the server (its
    ``.server_address`` carries the bound port — pass ``port=0`` for an
    ephemeral one). Stdlib-only by design; errors in ``render`` become
    a 500 so a scrape failure never kills the polisher.

    ``health``: optional zero-arg callable returning a JSON-able dict
    with a ``"status"`` key (watchdog.health_snapshot); when given,
    ``GET /healthz`` serves it as JSON — 200 while status is ``"ok"``,
    503 otherwise, so stock HTTP liveness probes can evict a wedged
    worker without parsing metrics."""
    import json
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 (stdlib naming)
            if self.path.rstrip("/") == "/healthz" and \
                    health is not None:
                try:
                    snap = health()
                    body = (json.dumps(snap, sort_keys=True) +
                            "\n").encode()
                    code = 200 if snap.get("status") == "ok" else 503
                except Exception as exc:  # probe must not crash the run
                    body = f'{{"status": "error: {exc}"}}\n'.encode()
                    code = 500
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)
                return
            try:
                body = render().encode()
                code = 200
            except Exception as exc:  # scrape must not crash the run
                body = f"render error: {exc}\n".encode()
                code = 500
            self.send_response(code)
            self.send_header("Content-Type", CONTENT_TYPE)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *args):  # silence per-request stderr
            pass

    server = ThreadingHTTPServer((host, int(port)), Handler)
    thread = threading.Thread(target=server.serve_forever,
                              name="racon-tpu-metrics", daemon=True)
    thread.start()
    return server
