"""Per-process crash flight recorder: a bounded in-memory ring of the
last N telemetry events — span records (fed by obs/trace.py), metric
mutations (fed by obs/metrics.py), and watchdog breaches — dumped to
``<obs-dir>/flight_<pid>.json`` when the process tears down abnormally
(WatchdogTerminal, PipelineStalled self-eviction, SIGTERM drain, or an
unhandled exception reaching the CLI/daemon teardown paths, all of
which already run :func:`racon_tpu.obs.fleet.flush_final`).

The dump is JSON Lines despite the ``.json`` suffix — one header line,
one line per ring event, one final metrics-registry snapshot line — so
a dump torn mid-write (power loss, SIGKILL racing the flush) still
loads as a valid prefix via
:func:`racon_tpu.utils.atomicio.load_jsonl_prefix`. The ``obs/flight``
fault site injects exactly that tear in tests and the resilience
drills.

The ring is always armed (capacity ``RACON_TPU_FLIGHT_EVENTS``,
default 256; 0 disables) because the events it needs most are the ones
nobody planned to capture; appends are O(1) deque pushes under a
dedicated lock, and nothing is written to disk until :func:`dump`.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from racon_tpu.utils import envspec
from racon_tpu.utils.atomicio import atomic_write_bytes, load_jsonl_prefix

SCHEMA_VERSION = 1

ENV_FLIGHT_EVENTS = "RACON_TPU_FLIGHT_EVENTS"
DEFAULT_EVENTS = 256

#: Dump filename prefix; one dump per pid so fleet workers never race.
FILE_PREFIX = "flight_"


class FlightRecorder:
    """Bounded event ring. ``capacity == 0`` records nothing (the
    disabled recorder still answers every call, so feed points need no
    gating)."""

    def __init__(self, capacity: int = DEFAULT_EVENTS):
        self.capacity = max(int(capacity), 0)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.capacity or 1)

    def note(self, rec: Dict) -> None:
        if not self.capacity:
            return
        with self._lock:
            self._ring.append(rec)

    def events(self) -> List[Dict]:
        with self._lock:
            return list(self._ring) if self.capacity else []


_RECORDER: Optional[FlightRecorder] = None
_LOCK = threading.Lock()


def recorder() -> FlightRecorder:
    """The process flight recorder; sized from the environment on first
    use."""
    global _RECORDER
    if _RECORDER is None:
        with _LOCK:
            if _RECORDER is None:
                raw = envspec.read(ENV_FLIGHT_EVENTS)
                try:
                    cap = int(raw) if raw else DEFAULT_EVENTS
                except ValueError:
                    cap = DEFAULT_EVENTS
                _RECORDER = FlightRecorder(cap)
    return _RECORDER


def reset() -> None:
    """Drop the process recorder (tests re-arm with a fresh ring)."""
    global _RECORDER
    with _LOCK:
        _RECORDER = None


# ---------------------------------------------------------- feed points

def note_span(rec: Dict) -> None:
    """Called by obs/trace.py for every span record written."""
    recorder().note(rec)


def note_metric(key: str, value) -> None:
    """Called by obs/metrics.py for global-registry mutations."""
    r = recorder()
    if not r.capacity:
        return
    r.note({"ev": "metric", "k": key, "v": value,
            "wall": round(time.time(), 3)})


def note_breach(site: str, deadline_s: float, waited_s: float,
                terminal: bool) -> None:
    """Called by obs/metrics.record_watchdog_breach — breaches land in
    the ring even when tracing is off."""
    recorder().note({"ev": "breach", "site": site,
                     "deadline_s": round(float(deadline_s), 6),
                     "waited_s": round(float(waited_s), 6),
                     "terminal": int(bool(terminal)),
                     "wall": round(time.time(), 3)})


# ----------------------------------------------------------- dump/load

def flight_path(directory: str, pid: Optional[int] = None) -> str:
    return os.path.join(directory,
                        f"{FILE_PREFIX}{pid or os.getpid()}.json")


def list_flights(directory: str) -> List[str]:
    """Every flight dump under ``directory``, sorted by name."""
    try:
        names = os.listdir(directory)
    except OSError:
        return []
    return sorted(os.path.join(directory, n) for n in names
                  if n.startswith(FILE_PREFIX) and n.endswith(".json"))


def dump(directory: Optional[str] = None, reason: str = "teardown") -> str:
    """Write the ring to ``<directory>/flight_<pid>.json`` atomically;
    returns the path ("" when no directory is resolvable — flight
    recording is strictly best-effort and never takes down a teardown
    path). ``directory=None`` falls back to ``RACON_TPU_OBS_DIR``."""
    if directory is None:
        directory = envspec.read("RACON_TPU_OBS_DIR")
    if not directory:
        return ""
    # Imported here, not at module top: metrics feeds this module, and
    # faults -> metrics would otherwise close an import cycle.
    from racon_tpu.obs import metrics as _metrics
    from racon_tpu.resilience import faults as _faults

    t0 = time.perf_counter()
    events = recorder().events()
    header = {"ev": "flight", "schema": SCHEMA_VERSION,
              "pid": os.getpid(), "reason": str(reason),
              "unix_time": round(time.time(), 3),
              "events": len(events)}
    lines = [json.dumps(header, separators=(",", ":"))]
    lines.extend(json.dumps(e, separators=(",", ":")) for e in events)
    lines.append(json.dumps(
        {"ev": "metrics", **_metrics.registry().snapshot()},
        separators=(",", ":"), default=str))
    data = ("\n".join(lines) + "\n").encode("utf-8")
    path = flight_path(directory)
    try:
        os.makedirs(directory, exist_ok=True)
        if _faults.maybe_torn("obs/flight"):
            torn = data[: max(1, len(data) - 17)]
            with open(path, "wb") as fh:  # lint: atomic-ok (torn-write drill)
                fh.write(torn)
                fh.flush()
                os.fsync(fh.fileno())
            _faults.hard_exit(137)
        atomic_write_bytes(path, data)
    except OSError:
        return ""
    dt = time.perf_counter() - t0
    _metrics.registry().inc("flight_dump_write_s", round(dt, 6))
    _metrics.registry().inc("flight_dumps_total")
    return path


def load_flight(path: str) -> Dict:
    """Parse a dump (torn-tolerant): the longest clean JSONL prefix,
    split into header / ring events / trailing metrics snapshot.
    Raises ValueError when even the header line is unusable."""
    records, clean = load_jsonl_prefix(path)
    if not records or records[0].get("ev") != "flight" or \
            records[0].get("schema") != SCHEMA_VERSION:
        raise ValueError(f"[racon_tpu::flightrec] not a flight dump: "
                         f"{path}")
    header = records[0]
    metrics = None
    body = records[1:]
    if body and body[-1].get("ev") == "metrics":
        metrics = body.pop()
    return {"header": header, "events": body, "metrics": metrics,
            "clean": clean}
