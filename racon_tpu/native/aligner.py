"""ctypes bindings for the native banded-NW aligner (edlib replacement).

The reference calls ``edlibAlign`` once per overlap under a thread pool
(reference: src/polisher.cpp:351-364, src/overlap.cpp:198-213). Here the
native aligner exposes a *batched* entry point over flat buffers so the
Python side makes one FFI call per batch, and the same op encoding as the
JAX device kernel (racon_tpu/ops/align.py) so either backend can serve any
alignment job.
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from racon_tpu.native.build import shared_library_path
from racon_tpu.ops.cigar import ops_to_cigar
from racon_tpu.ops.encode import encode_bases

_lib = None


def _load():
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(shared_library_path())
        lib.racon_nw_align.restype = ctypes.c_int32
        lib.racon_nw_align.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int32),
        ]
        lib.racon_nw_align_batch.restype = ctypes.c_int32
        lib.racon_nw_align_batch.argtypes = [
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.c_int32, ctypes.c_int32, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_int32, ctypes.c_int32,
            ctypes.POINTER(ctypes.c_uint8), ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
        ]
        _lib = lib
    return _lib


def _u8ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


class NativeAligner:
    """Host-side global aligner with adaptive banding.

    match/mismatch/gap default to edit-distance-equivalent scoring
    (maximizing m=0, x=-1, g=-1 yields a minimum-edit-distance alignment),
    which is what edlib computes for the reference's breaking-point
    alignments (src/overlap.cpp:198-200).
    """

    def __init__(self, match: int = 0, mismatch: int = -1, gap: int = -1,
                 band: int = 0, threads: int = 1):
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.band = band
        # Batch records fan out over OS threads (reference -t semantics,
        # src/polisher.cpp:341-364); 1 = serial, <=0 = all hardware cores.
        self.threads = threads
        _load()

    def align(self, q: bytes, t: bytes) -> np.ndarray:
        """Align raw sequence bytes; returns ops uint8[n] (0=M,1=I,2=D)."""
        qa = np.ascontiguousarray(encode_bases(q))
        ta = np.ascontiguousarray(encode_bases(t))
        return self.align_codes(qa, ta)

    def align_codes(self, qa: np.ndarray, ta: np.ndarray) -> np.ndarray:
        lib = _load()
        out = np.empty(len(qa) + len(ta), dtype=np.uint8)
        score = ctypes.c_int32(0)
        n = lib.racon_nw_align(
            _u8ptr(qa), len(qa), _u8ptr(ta), len(ta),
            self.match, self.mismatch, self.gap, self.band,
            _u8ptr(out), ctypes.byref(score))
        if n < 0:
            raise RuntimeError(
                "[racon_tpu::native] error: alignment failed "
                f"(lq={len(qa)}, lt={len(ta)})")
        return out[:n]

    def align_batch(self, pairs: Sequence[Tuple[np.ndarray, np.ndarray]]
                    ) -> List[np.ndarray]:
        """One FFI call for a whole batch of (q_codes, t_codes) pairs."""
        lib = _load()
        n = len(pairs)
        if n == 0:
            return []
        q_len = np.array([len(p[0]) for p in pairs], dtype=np.int32)
        t_len = np.array([len(p[1]) for p in pairs], dtype=np.int32)
        q_off = np.concatenate([[0], np.cumsum(q_len[:-1], dtype=np.int64)])
        t_off = np.concatenate([[0], np.cumsum(t_len[:-1], dtype=np.int64)])
        q_flat = np.concatenate([np.asarray(p[0], dtype=np.uint8)
                                 for p in pairs]) if q_len.sum() else \
            np.empty(0, np.uint8)
        t_flat = np.concatenate([np.asarray(p[1], dtype=np.uint8)
                                 for p in pairs]) if t_len.sum() else \
            np.empty(0, np.uint8)
        cap = (q_len + t_len).astype(np.int64)
        ops_off = np.concatenate([[0], np.cumsum(cap[:-1])])
        ops_out = np.empty(int(cap.sum()), dtype=np.uint8)
        ops_len = np.empty(n, dtype=np.int32)
        rc = lib.racon_nw_align_batch(
            _u8ptr(q_flat), q_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            q_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            _u8ptr(t_flat), t_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            t_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
            n, self.match, self.mismatch, self.gap, self.band, self.threads,
            _u8ptr(ops_out), ops_off.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            ops_len.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)))
        if rc != 0:
            raise RuntimeError(
                f"[racon_tpu::native] error: batch alignment failed at "
                f"record {rc - 1}")
        return [ops_out[ops_off[i]:ops_off[i] + ops_len[i]].copy()
                for i in range(n)]

    def cigar(self, q: bytes, t: bytes) -> bytes:
        """CIGAR bytes for Overlap.find_breaking_points's aligner hook."""
        return ops_to_cigar(self.align(q, t))
