// Banded Needleman-Wunsch global aligner with traceback.
//
// Native (host) replacement for the reference's edlib dependency: racon
// calls edlibAlign(..., EDLIB_MODE_NW, EDLIB_TASK_PATH) once per PAF/MHAP
// overlap to recover a CIGAR (reference: src/overlap.cpp:198-213). Overlap
// spans reach tens of kilobases, so the full O(Lq*Lt) matrix is avoided
// with a diagonal band that doubles until the optimal path stays strictly
// inside it (the same adaptive-band idea edlib uses); a band covering the
// whole matrix is exact plain NW, so the loop always terminates with an
// optimal alignment.
//
// Semantics are kept identical to the JAX device kernel
// (racon_tpu/ops/align.py): linear gap, int32 scores, tie preference
// DIAG > UP > LEFT, op encoding 0=M (diag), 1=I (up, consumes query),
// 2=D (left, consumes target).
//
// Band coordinates: k = j - i, band k in [klo, khi], column b = k - klo.
// Moving to row i+1: diag neighbour keeps b, up neighbour is b+1 in the
// previous row, left neighbour is b-1 in the same row.

#include <atomic>
#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>
#include <algorithm>

namespace {

constexpr int32_t kNegInf = INT32_MIN / 4;
enum Dir : uint8_t { kDiag = 0, kUp = 1, kLeft = 2 };

struct BandResult {
    int32_t n_ops = -1;
    int32_t score = kNegInf;
};

// One banded pass. ops_out is filled back-to-front and left in
// start->end order on return.
BandResult band_pass(const uint8_t* q, int32_t lq, const uint8_t* t,
                     int32_t lt, int32_t m, int32_t x, int32_t g,
                     int32_t klo, int32_t khi, uint8_t* ops_out) {
    BandResult res;
    const int32_t bandw = khi - klo + 1;

    std::vector<uint8_t> dirs(static_cast<size_t>(lq + 1) * bandw);
    std::vector<int32_t> prev(bandw + 1, kNegInf), cur(bandw + 1, kNegInf);
    // prev/cur have one sentinel slot at the end so the up-neighbour read
    // prev[b + 1] is always in range.

    // Row 0: H[0][j] = j*g for j in [max(0, klo), min(lt, khi)].
    {
        const int32_t jlo = std::max(0, klo), jhi = std::min(lt, khi);
        for (int32_t j = jlo; j <= jhi; ++j) {
            prev[j - klo] = j * g;
        }
    }

    for (int32_t i = 1; i <= lq; ++i) {
        const int32_t jlo = std::max(0, i + klo);
        const int32_t jhi = std::min(lt, i + khi);
        if (jlo > jhi) return res;  // band fell off the matrix
        const uint8_t qc = q[i - 1];
        uint8_t* drow = dirs.data() + static_cast<size_t>(i) * bandw;

        int32_t blo = jlo - i - klo;
        const int32_t bhi = jhi - i - klo;
        // j = 0 boundary handled outside the hot loops.
        if (jlo == 0) {
            cur[blo] = i * g;
            drow[blo] = kUp;
            ++blo;
        }
        // Branchless vectorizable phase: tmp = max(diag, up). For b in
        // [blo, bhi], j = i + klo + b >= 1, so t[j-1] = tj[b] is in range.
        const uint8_t* tj = t + (i + klo - 1);
        const int32_t* pv = prev.data();
        int32_t* cu = cur.data();
        for (int32_t b = blo; b <= bhi; ++b) {
            const int32_t sub = tj[b] == qc ? m : x;
            const int32_t diag = pv[b] + sub;
            const int32_t up = pv[b + 1] + g;
            cu[b] = diag > up ? diag : up;
        }
        // Serial phase: fold in the left-gap chain and label directions.
        int32_t left = (jlo == 0) ? cur[blo - 1] : kNegInf;
        for (int32_t b = blo; b <= bhi; ++b) {
            const int32_t diag = pv[b] + (tj[b] == qc ? m : x);
            int32_t h = cu[b];
            if (left + g > h) h = left + g;
            cu[b] = h;
            left = h;
            drow[b] = (h == diag) ? kDiag
                                  : (h == pv[b + 1] + g ? kUp : kLeft);
        }
        // Sentinels outside the valid window (the next row reads one slot
        // past each side; a full fill per row is wasted bandwidth).
        if (blo - 1 >= 0 && jlo != 0) cur[blo - 1] = kNegInf;
        if (blo - 2 >= 0) cur[blo - 2] = kNegInf;
        if (bhi + 1 < bandw + 1) cur[bhi + 1] = kNegInf;
        std::swap(prev, cur);
    }

    const int32_t bend = lt - lq - klo;
    if (bend < 0 || bend >= bandw) return res;
    res.score = prev[bend];
    if (res.score <= kNegInf / 2) return res;

    // Traceback from (lq, lt).
    int32_t i = lq, j = lt, pos = lq + lt;
    while (i > 0 || j > 0) {
        uint8_t d;
        if (i == 0) {
            d = kLeft;
        } else if (j == 0) {
            d = kUp;
        } else {
            const int32_t b = j - i - klo;
            if (b < 0 || b >= bandw) return res;  // should not happen
            d = dirs[static_cast<size_t>(i) * bandw + b];
        }
        ops_out[--pos] = d;
        if (d != kLeft) --i;
        if (d != kUp) --j;
    }
    res.n_ops = lq + lt - pos;
    if (pos > 0) {
        std::memmove(ops_out, ops_out + pos, res.n_ops);
    }
    return res;
}

}  // namespace

extern "C" {

// Globally align q vs t; writes ops (0=M,1=I,2=D) into ops_out (capacity
// lq + lt). Returns the op count, or -1 on failure. band0 <= 0 selects an
// automatic initial half-width. score_out (optional) receives the score.
int32_t racon_nw_align(const uint8_t* q, int32_t lq, const uint8_t* t,
                       int32_t lt, int32_t m, int32_t x, int32_t g,
                       int32_t band0, uint8_t* ops_out, int32_t* score_out) {
    if (lq < 0 || lt < 0) return -1;
    if (lq == 0) {
        std::memset(ops_out, kLeft, lt);
        if (score_out) *score_out = lt * g;
        return lt;
    }
    if (lt == 0) {
        std::memset(ops_out, kUp, lq);
        if (score_out) *score_out = lq * g;
        return lq;
    }

    int32_t w = band0 > 0 ? band0
                          : std::max<int32_t>(128, std::abs(lt - lq) + 64);
    // The escape bound below needs g < 0 (it divides by -g, and with
    // free gaps no banded score can ever prove exactness): g >= 0 runs
    // the full matrix directly.
    if (g >= 0) w = std::max(lq, lt);
    // Acceptance is a *provable* escape bound (Ukkonen banding
    // generalized to match-bonus scoring), not the untouched-edge
    // heuristic: a balanced long insertion+deletion can route the
    // optimal path outside the band while a sub-optimal in-band path
    // never touches the edge (ADVICE r2 #1; edlib is exact).
    //   Any path leaving the band [min(0,d)-w, max(0,d)+w] (d = lt-lq)
    //   needs >= |d| + 2(w+1) gap ops (reach the edge + return), and has
    //   at most min(lq,lt) matches, so it scores at most
    //     max(m,0)*min(lq,lt) + g*(|d| + 2w + 2).
    //   A banded score >= that bound therefore beats every escaping
    //   path, and the in-band DP is exact over in-band paths.
    // Typical polishing alignments accept on the first pass; the loop
    // terminates at the full matrix regardless.
    const int64_t dgap = std::abs(lt - lq);
    const int64_t mmax = static_cast<int64_t>(std::max(m, 0)) *
                         std::min(lq, lt);
    while (true) {
        const int32_t klo = std::max(std::min(0, lt - lq) - w, -lq);
        const int32_t khi = std::min(std::max(0, lt - lq) + w, lt);
        BandResult res = band_pass(q, lq, t, lt, m, x, g, klo, khi, ops_out);
        if (klo <= -lq && khi >= lt) {
            // Full matrix — exact.
            if (res.n_ops >= 0) {
                if (score_out) *score_out = res.score;
                return res.n_ops;
            }
            return -1;
        }
        if (res.n_ops >= 0) {
            const int64_t escape =
                mmax + static_cast<int64_t>(g) * (dgap + 2 * w + 2);
            if (static_cast<int64_t>(res.score) >= escape) {
                if (score_out) *score_out = res.score;
                return res.n_ops;
            }
            // Jump straight to a width whose escape bound the current
            // (lower-bound) score already beats: the banded score only
            // improves as the band widens, so the next pass is
            // guaranteed to accept. Two passes total instead of a
            // doubling ladder.
            const int64_t n_g = (mmax - res.score + (-g) - 1) / (-g);
            const int64_t w_need = (n_g - dgap) / 2 + 1;
            w = static_cast<int32_t>(
                std::min<int64_t>(std::max<int64_t>(2 * w, w_need),
                                  std::max(lq, lt)));
        } else {
            w *= 2;
        }
    }
}

// Batched form over flat buffers. ops_off[i] must leave q_len[i]+t_len[i]
// capacity per record; ops_len[i] receives each op count (-1 on failure).
// Records fan out over n_threads OS threads (<=0 selects the hardware
// concurrency), the host analogue of the reference's per-overlap thread
// pool (src/polisher.cpp:351-364). Returns 0 on success, first failing
// index + 1 otherwise.
int32_t racon_nw_align_batch(const uint8_t* q, const int64_t* q_off,
                             const int32_t* q_len, const uint8_t* t,
                             const int64_t* t_off, const int32_t* t_len,
                             int32_t n, int32_t m, int32_t x, int32_t g,
                             int32_t band0, int32_t n_threads,
                             uint8_t* ops_out,
                             const int64_t* ops_off, int32_t* ops_len) {
    if (n_threads <= 0) {
        n_threads = static_cast<int32_t>(std::thread::hardware_concurrency());
        if (n_threads <= 0) n_threads = 1;
    }
    n_threads = std::min(n_threads, n);
    std::atomic<int32_t> next(0), rc(0);
    auto worker = [&]() {
        while (true) {
            const int32_t i = next.fetch_add(1);
            if (i >= n) return;
            ops_len[i] = racon_nw_align(q + q_off[i], q_len[i],
                                        t + t_off[i], t_len[i], m, x, g,
                                        band0, ops_out + ops_off[i],
                                        nullptr);
            if (ops_len[i] < 0) {
                int32_t cur = rc.load();
                while ((cur == 0 || i + 1 < cur) &&
                       !rc.compare_exchange_weak(cur, i + 1)) {
                }
            }
        }
    };
    if (n_threads <= 1) {
        worker();
    } else {
        std::vector<std::thread> pool;
        pool.reserve(n_threads);
        for (int32_t k = 0; k < n_threads; ++k) pool.emplace_back(worker);
        for (auto& th : pool) th.join();
    }
    return rc.load();
}

}  // extern "C"
