"""Build the native C++ runtime pieces (g++ -> shared library).

The reference links vendored native libs (edlib et al.) through CMake
(reference: CMakeLists.txt:37); here the native aligner is a single
translation unit compiled on demand and cached next to its source, keyed
by a content hash so edits trigger a rebuild and stale binaries are never
loaded. No pybind11 in this environment — bindings are ctypes
(racon_tpu/native/aligner.py).
"""

from __future__ import annotations

import hashlib
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "nw.cpp")
_CXX = os.environ.get("CXX", "g++")
_FLAGS = ["-O3", "-std=c++17", "-shared", "-fPIC", "-march=native",
          "-funroll-loops", "-Wall", "-Wextra", "-pthread"]


class NativeBuildError(RuntimeError):
    pass


def _source_hash() -> str:
    with open(_SRC, "rb") as f:
        return hashlib.sha256(f.read() + " ".join(_FLAGS).encode()).hexdigest()[:16]


def shared_library_path(rebuild: bool = False) -> str:
    """Path to the compiled library, building it if missing or stale."""
    tag = _source_hash()
    lib = os.path.join(_DIR, f"libracon_nw.{tag}.so")
    if rebuild or not os.path.isfile(lib):
        for old in os.listdir(_DIR):
            if old.startswith("libracon_nw.") and old.endswith(".so"):
                try:
                    os.unlink(os.path.join(_DIR, old))
                except OSError:
                    pass
        cmd = [_CXX, *_FLAGS, _SRC, "-o", lib]
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise NativeBuildError(
                f"[racon_tpu::native] error: build failed\n$ {' '.join(cmd)}\n"
                f"{proc.stderr}")
    return lib
