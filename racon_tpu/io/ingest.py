"""Ingest gate + mmap'd zero-copy index-first sequence readers.

The second half of the ISSUE-12 data plane (the first is
:mod:`racon_tpu.io.inflate`): for *uncompressed* FASTA/FASTQ the
bytes are already random-access on disk, so the fastest reader is no
reader at all — mmap the file, find the record structure with one
vectorized numpy pass (newline positions + header starts; the
``scan_sequence_index`` structural pass from PR 8, now index-first),
and hand every record payload to :class:`~racon_tpu.models.sequence
.Sequence` as a ``memoryview`` slice of the map. ``ops/encode.py``
packs device batches with ``np.frombuffer``, which reads any buffer —
so a single-line record travels mmap → window slice → device encode
with **zero** intermediate ``bytes`` copies and no Python-level
per-line splits.

Zero-copy contract (pinned by tests/test_ingest.py): on the mmap path
the ONLY place a record payload may materialize into ``bytes`` is
:func:`_materialize` / :func:`_materialize_join` — a counting shim.
Multi-line records (wrapped FASTA) must join and therefore count; a
single-line-per-record file counts zero.

Lifetime: the mmap object is deliberately never closed by the readers —
every ``memoryview`` sliced from it keeps it (and the underlying pages)
alive, and closing it under live views would raise ``BufferError``.
The map is dropped when the last record referencing it is.

Gate: ``RACON_TPU_INGEST`` — **default on**; ``0``/``false`` forces the
serial PR-8 readers everywhere (parsers, scan, prefetch, inflate). The
two paths are byte-identical on records, offsets, and polished output
(scripts/ingest_smoke.py and the test differentials gate it).

Fault parity: the serial readers arm ``io/read`` once per *line*; the
indexed readers arm it once per *record* (there are no lines here).
:func:`prefetch_ok` additionally drops ingest *concurrency* (not the
readers) when a fault plan targets an ``io/*`` site, because two files
racing one process-wide site counter would break the injector's
documented determinism.
"""

from __future__ import annotations

import mmap
import os
from racon_tpu.utils import envspec
import threading
from typing import Iterator, List, Optional, Tuple

import numpy as np

from racon_tpu.io import parsers as _p
from racon_tpu.io.parsers import ParseError, Parser
from racon_tpu.models.sequence import Sequence

ENV_INGEST = "RACON_TPU_INGEST"


def ingest_enabled() -> bool:
    """The ingest-subsystem gate: default ON, ``RACON_TPU_INGEST=0``
    (or ``false``) is the serial escape hatch — mirror image of the
    pipeline gate, which defaults off."""
    return envspec.read(ENV_INGEST) not in ("0", "false")


def prefetch_ok() -> bool:
    """Whether background ingest prefetch threads may run: requires the
    gate on AND no fault plan aimed at an ``io/*`` site (concurrent
    files advancing one global site counter would make explicit-index
    drills racy; the drill still exercises the ingest *readers*,
    just serially)."""
    if not ingest_enabled():
        return False
    from racon_tpu.resilience.faults import get_injector
    inj = get_injector()
    if inj is not None and any(s.startswith("io/") for s in inj.sites()):
        return False
    return True


# ------------------------------------------------- zero-copy accounting

_mat_lock = threading.Lock()
_mat_count = 0


def _materialize(view) -> bytes:
    """The counted escape hatch: the only place the mmap path may turn
    a record payload view into ``bytes``."""
    global _mat_count
    with _mat_lock:
        _mat_count += 1
    return bytes(view)


def _materialize_join(views: List) -> bytes:
    """Multi-line record payloads must concatenate — one counted copy."""
    global _mat_count
    with _mat_lock:
        _mat_count += 1
    return b"".join(views)


def materialized_copies() -> int:
    """How many record payloads the mmap path has copied to ``bytes``
    since :func:`reset_materialized` — the zero-copy invariant gauge."""
    with _mat_lock:
        return _mat_count


def reset_materialized() -> None:
    global _mat_count
    with _mat_lock:
        _mat_count = 0


# ------------------------------------------------------ mmap line index

class _LineIndex:
    """One vectorized structural pass over an mmap'd text file: numpy
    newline scan → per-line (start, end) spans, no split, no copies."""

    __slots__ = ("mm", "view", "arr", "starts", "ends", "size")

    def __init__(self, path: str):
        size = os.path.getsize(path)
        self.size = size
        if size == 0:
            self.mm = None
            self.view = memoryview(b"")
            self.arr = np.empty(0, np.uint8)
            self.starts = np.empty(0, np.int64)
            self.ends = np.empty(0, np.int64)
            return
        with open(path, "rb") as fh:
            self.mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
        self.view = memoryview(self.mm)
        self.arr = np.frombuffer(self.mm, np.uint8)
        nl = np.flatnonzero(self.arr == 0x0A).astype(np.int64)
        starts = np.concatenate([np.zeros(1, np.int64), nl + 1])
        ends = np.append(nl, np.int64(size))
        if starts[-1] >= size:  # file ends with '\n': no phantom line
            starts = starts[:-1]
            ends = ends[:-1]
        self.starts = starts
        self.ends = ends

    def span(self, i: int) -> Tuple[int, int]:
        """Line i's content span with trailing CRs stripped (the exact
        ``rstrip(b"\\r")`` the block reader applies)."""
        s = int(self.starts[i])
        e = int(self.ends[i])
        while e > s and self.arr[e - 1] == 0x0D:
            e -= 1
        return s, e

    def first_byte(self, i: int) -> int:
        """Line i's first content byte, or -1 when the line is empty."""
        s, e = int(self.starts[i]), int(self.ends[i])
        return int(self.arr[s]) if e > s else -1


def _decode_name(idx: _LineIndex, s: int, e: int) -> str:
    """Header (sans marker) → name: first whitespace-delimited token,
    bioparser semantics. Names always materialize (they become str)."""
    return _p._first_token(bytes(idx.view[s:e])).decode()


# ----------------------------------------------- index-first readers

class IndexedFastaParser(Parser):
    """mmap index-first FASTA reader: drop-in for
    :class:`~racon_tpu.io.parsers.FastaParser` on plain files, with
    record payloads as zero-copy ``memoryview`` slices (single-line
    records) or one counted join (wrapped records). Record order,
    names, bytes, budget accounting, and error offsets are identical to
    the serial reader — the ``RACON_TPU_INGEST=0`` differential is the
    contract."""

    def _records(self) -> Iterator[Tuple[Sequence, int]]:
        from racon_tpu.resilience.faults import maybe_fault
        idx = _LineIndex(self.path)
        n_lines = len(idx.starts)
        name: Optional[str] = None
        spans: List[Tuple[int, int]] = []
        last_end = 0
        for i in range(n_lines):
            fb = idx.first_byte(i)
            s, e = idx.span(i)
            if fb == 0x3E:  # '>'
                if name is not None:
                    maybe_fault("io/read")
                    self._pos = last_end
                    yield self._emit(idx, name, spans)
                name = _decode_name(idx, s + 1, e)
                spans = []
            elif e > s:
                if name is None:
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed FASTA file "
                        f"{self.path}", offset=s)
                spans.append((s, e))
            last_end = min(int(idx.ends[i]) + 1, idx.size)
        if name is not None:
            maybe_fault("io/read")
            self._pos = last_end
            yield self._emit(idx, name, spans)

    @staticmethod
    def _emit(idx: _LineIndex, name: str,
              spans: List[Tuple[int, int]]) -> Tuple[Sequence, int]:
        if len(spans) == 1:
            s, e = spans[0]
            data = idx.view[s:e]
        elif spans:
            data = _materialize_join([idx.view[s:e] for s, e in spans])
        else:
            data = b""
        return Sequence(name, data), len(name) + len(data)


class IndexedFastqParser(Parser):
    """mmap index-first FASTQ reader (see :class:`IndexedFastaParser`).
    Quality payloads are views too; the all-``!`` and below-``!``
    checks run on the numpy index array without copying."""

    def _records(self) -> Iterator[Tuple[Sequence, int]]:
        from racon_tpu.resilience.faults import maybe_fault
        idx = _LineIndex(self.path)
        n_lines = len(idx.starts)
        i = 0
        while i < n_lines:
            s, e = idx.span(i)
            if e <= s:
                i += 1
                continue
            rec_off = s
            if idx.first_byte(i) != 0x40:  # '@'
                raise ParseError(
                    f"[racon_tpu::io] error: malformed FASTQ file "
                    f"{self.path}", offset=rec_off)
            name = _decode_name(idx, s + 1, e)
            i += 1
            data_spans: List[Tuple[int, int]] = []
            dlen = 0
            while True:
                if i >= n_lines:
                    raise ParseError(
                        f"[racon_tpu::io] error: truncated FASTQ "
                        f"file {self.path} — EOF inside the record "
                        f"starting", offset=rec_off)
                s, e = idx.span(i)
                if idx.first_byte(i) == 0x2B:  # '+'
                    i += 1
                    break
                if e > s:
                    data_spans.append((s, e))
                    dlen += e - s
                i += 1
            qual_spans: List[Tuple[int, int]] = []
            qlen = 0
            while qlen < dlen:
                if i >= n_lines:
                    raise ParseError(
                        f"[racon_tpu::io] error: truncated FASTQ "
                        f"file {self.path} — EOF inside the record "
                        f"starting", offset=rec_off)
                s, e = idx.span(i)
                if e > s:
                    qual_spans.append((s, e))
                    qlen += e - s
                i += 1
            if qlen != dlen:
                raise ParseError(
                    f"[racon_tpu::io] error: quality length mismatch "
                    f"in {self.path} for record '{name}' (sequence "
                    f"{dlen}, quality {qlen})", offset=rec_off)
            bad = any(int(idx.arr[s:e].min()) < 33
                      for s, e in qual_spans if e > s)
            if bad:
                raise ParseError(
                    f"[racon_tpu::io] error: malformed quality string "
                    f"(byte below '!') in {self.path}", offset=rec_off)
            maybe_fault("io/read")
            data = self._payload(idx, data_spans)
            quality = self._payload(idx, qual_spans)
            self._pos = min((int(idx.ends[i - 1]) + 1) if i else 0,
                            idx.size)
            yield Sequence(name, data, quality), len(name) + 2 * dlen

    @staticmethod
    def _payload(idx: _LineIndex, spans: List[Tuple[int, int]]):
        if len(spans) == 1:
            s, e = spans[0]
            return idx.view[s:e]
        if spans:
            return _materialize_join([idx.view[s:e] for s, e in spans])
        return b""


# ----------------------------------------------------- structural scan

def scan_index_mmap(path: str) -> Tuple[int, List[int]]:
    """Index-first ``scan_sequence_index``: same counts, offsets, and
    error contract as the serial structural pass, via the numpy line
    index instead of a streamed line walk."""
    if path.endswith(_p._FASTA_EXTS):
        idx = _LineIndex(path)
        heads = [int(idx.starts[i]) for i in range(len(idx.starts))
                 if idx.first_byte(i) == 0x3E]
        return len(heads), heads
    if path.endswith(_p._FASTQ_EXTS):
        return _scan_fastq_mmap(path)
    raise ParseError(
        f"[racon_tpu::create_polisher] error: file {path} has "
        "unsupported format extension (valid extensions: .fasta, "
        ".fasta.gz, .fa, .fa.gz, .fastq, .fastq.gz, .fq, .fq.gz)!")


def _scan_fastq_mmap(path: str) -> Tuple[int, List[int]]:
    idx = _LineIndex(path)
    n_lines = len(idx.starts)
    offsets: List[int] = []
    i = 0
    while i < n_lines:
        s, e = idx.span(i)
        if e <= s:
            i += 1
            continue
        rec_off = s
        if idx.first_byte(i) != 0x40:
            raise ParseError(
                f"[racon_tpu::io] error: malformed FASTQ file "
                f"{path}", offset=rec_off)
        offsets.append(rec_off)
        i += 1
        dlen = 0
        while True:
            if i >= n_lines:
                raise ParseError(
                    f"[racon_tpu::io] error: truncated FASTQ "
                    f"file {path} — EOF inside the record "
                    f"starting", offset=rec_off)
            s, e = idx.span(i)
            if idx.first_byte(i) == 0x2B:
                i += 1
                break
            dlen += max(e - s, 0)
            i += 1
        qlen = 0
        while qlen < dlen:
            if i >= n_lines:
                raise ParseError(
                    f"[racon_tpu::io] error: truncated FASTQ "
                    f"file {path} — EOF inside the record "
                    f"starting", offset=rec_off)
            s, e = idx.span(i)
            qlen += max(e - s, 0)
            i += 1
        if qlen != dlen:
            raise ParseError(
                f"[racon_tpu::io] error: quality length mismatch in "
                f"{path} (sequence {dlen}, quality {qlen})",
                offset=rec_off)
    return len(offsets), offsets


def indexed_ok(path: str) -> bool:
    """Whether the mmap index-first plane applies: plain (uncompressed)
    file with the gate on."""
    return ingest_enabled() and not path.endswith(".gz")
