"""Chunk-parallel gzip/BGZF inflate plane for the ingest subsystem.

Serial host ingest — one ``gzip.GzipFile`` stream feeding one parser —
is the Amdahl term ROADMAP item 2 names: the device polishes at
321 w/s compute-only while the host inflates the three input files one
block at a time on one core. Decompression is the one ingest cost that
parallelizes cleanly, because zlib releases the GIL: a plain
``ThreadPoolExecutor`` gives real concurrency without pickling a byte
of data across processes.

Reader selection for a ``.gz`` input (:func:`open_gzip_source`;
docs/INGEST.md has the full matrix):

- **BGZF** (bgzip/htslib output): every member carries the ``BC`` extra
  subfield with the compressed block size, so block boundaries are read
  straight out of the headers — no speculative scan — and all blocks
  inflate concurrently on the pool.
- **Multi-member gzip** (concatenated ``gzip.compress`` outputs, pigz
  ``--independent``, block compressors without the BC field): member
  starts are discovered by scanning the mmap'd compressed bytes for
  gzip magic candidates; candidates inflate speculatively in file
  order and a chain walk confirms them — a member is real iff the
  previously confirmed member ends exactly at its offset, so false
  positives (magic bytes inside compressed data) cost one wasted
  inflate and never corrupt the stream.
- **Single-member gzip**: no intra-file parallelism exists, so a
  producer thread streams the inflate through a bounded queue
  (:class:`racon_tpu.pipeline.queues.BoundedQueue`) and decompression
  overlaps the consumer's parsing instead.

Every source yields plain ``bytes`` blocks whose concatenation is
byte-identical to ``gzip.open(path).read()`` — the parsers'
``_block_lines`` consumes either a file object or one of these sources,
which is what makes the serial/parallel differential trivial to gate.

Error contract: mid-member truncation and corrupt deflate streams
raise the offset-bearing :class:`~racon_tpu.io.parsers.ParseError`
carrying the member ordinal and the member's *compressed* byte offset
(unlike parse errors, whose offsets are decompressed-stream positions —
a torn download is located in the file you actually have on disk).

Fault site ``io/inflate`` (:func:`racon_tpu.resilience.faults
.maybe_fault`) arms before every block/member inflate, consulted on the
consuming thread in submission order so explicit-index plans stay
deterministic; a ``torn`` rule here degrades to ``raise`` — the
short-read drill — exactly like any other read-only site.
"""

from __future__ import annotations

import gzip
import mmap
import os
from racon_tpu.utils import envspec
import threading
import time
import zlib
from typing import Iterator, List, Optional, Tuple

from racon_tpu.io.parsers import ParseError
from racon_tpu.resilience.faults import maybe_fault

ENV_WORKERS = "RACON_TPU_INGEST_WORKERS"

_MAGIC = b"\x1f\x8b"
#: gzip magic + CM=8 (deflate) — the member-start candidate pattern.
_MEMBER_MAGIC = b"\x1f\x8b\x08"
#: Compressed-feed granularity for member inflate.
_FEED = 1 << 20
#: In-flight inflate jobs per worker (bounds decompressed buffering).
_LOOKAHEAD = 4


def inflate_workers() -> int:
    """Inflate pool width: ``RACON_TPU_INGEST_WORKERS`` or a core-count
    default (capped — inflate saturates memory bandwidth long before it
    needs every core of a large host)."""
    env = envspec.read(ENV_WORKERS)
    if env:
        try:
            n = int(env)
        except ValueError as exc:
            raise ValueError(
                f"[racon_tpu::io] invalid {ENV_WORKERS}={env!r}") from exc
        if n > 0:
            return n
    return max(2, min(8, os.cpu_count() or 2))


def bgzf_block_size(buf, off: int, size: int) -> Optional[int]:
    """Total compressed length of the BGZF block at ``off`` (BSIZE+1),
    or None when the member there has no ``BC`` extra subfield (not
    BGZF) or the header itself is short/malformed."""
    if off + 18 > size:
        return None
    if buf[off:off + 3] != _MEMBER_MAGIC or not buf[off + 3] & 4:
        return None  # not gzip/deflate, or FEXTRA unset
    xlen = buf[off + 10] | buf[off + 11] << 8
    if off + 12 + xlen > size:
        return None
    p = off + 12
    end = p + xlen
    while p + 4 <= end:
        si1, si2 = buf[p], buf[p + 1]
        slen = buf[p + 2] | buf[p + 3] << 8
        if si1 == 66 and si2 == 67 and slen == 2 and p + 6 <= end:
            return (buf[p + 4] | buf[p + 5] << 8) + 1
        p += 4 + slen
    return None


class _MemberError(Exception):
    """Internal: one member failed to inflate; the chain walk converts
    it to the ordinal-bearing ParseError."""

    def __init__(self, offset: int, reason: str):
        super().__init__(reason)
        self.offset = offset
        self.reason = reason


def _inflate_member(mm, start: int, size: int) -> Tuple[bytes, int, float]:
    """Inflate the complete gzip member starting at ``start``; returns
    (payload, end offset, seconds in zlib). zlib verifies the member
    CRC at eof, so a corrupt payload cannot pass silently."""
    d = zlib.decompressobj(zlib.MAX_WBITS | 16)
    out: List[bytes] = []
    pos = start
    t0 = time.perf_counter()
    try:
        while not d.eof:
            if pos >= size:
                raise _MemberError(start, "truncated mid-member")
            chunk = mm[pos:pos + _FEED]
            out.append(d.decompress(chunk))
            pos += len(chunk)
    except zlib.error as exc:
        raise _MemberError(start, f"corrupt deflate stream ({exc})")
    end = pos - len(d.unused_data)
    return b"".join(out), end, time.perf_counter() - t0


class ByteSource:
    """Iterable-of-blocks context manager; ``mode`` names the plan for
    metrics and the docs/INGEST.md selection matrix."""

    mode = "?"

    def __init__(self, path: str):
        self.path = path

    def blocks(self) -> Iterator[bytes]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[bytes]:
        return self.blocks()

    def __enter__(self) -> "ByteSource":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self) -> None:
        pass

    def _record(self, bytes_in: int, bytes_out: int, seconds: float,
                blocks: int) -> None:
        if blocks:
            from racon_tpu.obs.metrics import record_ingest_inflate
            record_ingest_inflate(self.mode, bytes_in, bytes_out,
                                  seconds, blocks)


class _EmptySource(ByteSource):
    """A zero-byte .gz: the serial reader yields nothing, so do we."""

    mode = "empty"

    def blocks(self) -> Iterator[bytes]:
        return iter(())


class _PooledSource(ByteSource):
    """Shared mmap + worker pool for the parallel (bgzf/members) plans."""

    def __init__(self, path: str, fh, mm):
        super().__init__(path)
        self._fh = fh
        self._mm = mm
        self._pool = None

    def _executor(self):
        if self._pool is None:
            from concurrent.futures import ThreadPoolExecutor
            self._pool = ThreadPoolExecutor(
                max_workers=inflate_workers(),
                thread_name_prefix="racon-inflate")
        return self._pool

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=True, cancel_futures=True)
            self._pool = None
        if self._mm is not None:
            self._mm.close()
            self._mm = None
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class BgzfSource(_PooledSource):
    """All block boundaries come from the BC headers; every block is an
    independent gzip member inflated concurrently, yielded in order."""

    mode = "bgzf"

    def _walk(self) -> List[Tuple[int, int]]:
        mm, size = self._mm, len(self._mm)
        spans: List[Tuple[int, int]] = []
        off = 0
        while off < size:
            bs = bgzf_block_size(mm, off, size)
            if bs is None or off + bs > size:
                what = ("truncated mid-member" if bs is not None
                        else "malformed or truncated header")
                raise ParseError(
                    f"[racon_tpu::io] error: BGZF member {len(spans)} of "
                    f"{self.path} {what} at compressed offset {off}",
                    offset=off)
            spans.append((off, bs))
            off += bs
        return spans

    def blocks(self) -> Iterator[bytes]:
        spans = self._walk()
        pool = self._executor()
        window = inflate_workers() * _LOOKAHEAD
        bytes_out = 0
        inflate_s = 0.0
        n = 0
        pending: List = []
        nxt = 0

        def _submit_one() -> None:
            nonlocal nxt
            maybe_fault("io/inflate")
            pending.append(pool.submit(_inflate_member, self._mm,
                                       spans[nxt][0], len(self._mm)))
            nxt += 1

        try:
            while nxt < len(spans) and nxt < window:
                _submit_one()
            i = 0
            while pending:
                fut = pending.pop(0)
                if nxt < len(spans):
                    _submit_one()
                try:
                    payload, end, dt = fut.result()
                except _MemberError as exc:
                    raise ParseError(
                        f"[racon_tpu::io] error: BGZF member {i} of "
                        f"{self.path} {exc.reason} at compressed offset "
                        f"{exc.offset}", offset=exc.offset) from exc
                if end != spans[i][0] + spans[i][1]:
                    raise ParseError(
                        f"[racon_tpu::io] error: BGZF member {i} of "
                        f"{self.path} ends at {end}, header promised "
                        f"{spans[i][0] + spans[i][1]} (compressed offset "
                        f"{spans[i][0]})", offset=spans[i][0])
                bytes_out += len(payload)
                inflate_s += dt
                n += 1
                i += 1
                if payload:
                    yield payload
        finally:
            self._record(len(self._mm) if self._mm is not None else 0,
                         bytes_out, inflate_s, n)


class MemberSource(_PooledSource):
    """Plain multi-member gzip: candidate starts from a magic scan,
    speculative parallel inflate, chain-walk confirmation."""

    mode = "members"

    def __init__(self, path: str, fh, mm, candidates: List[int]):
        super().__init__(path, fh, mm)
        self._cands = candidates

    def blocks(self) -> Iterator[bytes]:
        mm, size = self._mm, len(self._mm)
        pool = self._executor()
        window = inflate_workers() * _LOOKAHEAD
        futures = {}
        submitted = 0
        idx_of = {c: i for i, c in enumerate(self._cands)}
        bytes_out = 0
        inflate_s = 0.0
        n = 0

        def _submit_to(limit: int) -> None:
            nonlocal submitted
            while submitted < len(self._cands) and submitted <= limit:
                c = self._cands[submitted]
                maybe_fault("io/inflate")
                futures[c] = pool.submit(_inflate_member, mm, c, size)
                submitted += 1

        try:
            cur = 0
            while cur < size:
                i = idx_of.get(cur)
                if i is None:
                    # The previous member ended at bytes that are not a
                    # gzip member start: trailing garbage, or a stream
                    # cut inside the final member's trailer.
                    raise ParseError(
                        f"[racon_tpu::io] error: gzip member {n} of "
                        f"{self.path} is followed by non-gzip bytes at "
                        f"compressed offset {cur} (corrupt or truncated "
                        "multi-member stream)", offset=cur)
                _submit_to(i + window)
                try:
                    payload, end, dt = futures.pop(cur).result()
                except _MemberError as exc:
                    raise ParseError(
                        f"[racon_tpu::io] error: gzip member {n} of "
                        f"{self.path} {exc.reason} at compressed offset "
                        f"{exc.offset}", offset=exc.offset) from exc
                bytes_out += len(payload)
                inflate_s += dt
                n += 1
                cur = end
                if payload:
                    yield payload
        finally:
            self._record(size, bytes_out, inflate_s, n)


class StreamSource(ByteSource):
    """Single-member gzip: no block boundaries to parallelize over, so
    a producer thread inflates ahead through a bounded queue — the
    fallback that still overlaps decompression with downstream parsing
    (the ISSUE-12 MPMC-queue contract)."""

    mode = "stream"

    def __init__(self, path: str, depth: int = 4):
        super().__init__(path)
        self._depth = depth
        self._thread: Optional[threading.Thread] = None
        self._q = None

    def blocks(self) -> Iterator[bytes]:
        from racon_tpu.pipeline.queues import (BoundedQueue, PipelineAborted,
                                               QueueClosed)
        q = BoundedQueue("inflate_stream", self._depth)
        self._q = q
        err: List[BaseException] = []
        stats = {"out": 0, "s": 0.0, "n": 0}

        def _produce() -> None:
            try:
                with gzip.open(self.path, "rb") as f:
                    while True:
                        maybe_fault("io/inflate")
                        t0 = time.perf_counter()
                        data = f.read(_FEED)
                        stats["s"] += time.perf_counter() - t0
                        if not data:
                            break
                        stats["out"] += len(data)
                        stats["n"] += 1
                        q.put(data)
                q.close()
            except PipelineAborted:
                pass
            except BaseException as exc:  # re-raised on the consumer
                err.append(exc)
                q.abort()

        t = threading.Thread(target=_produce, name="racon-inflate-stream",
                             daemon=True)
        self._thread = t
        t.start()
        try:
            while True:
                try:
                    data = q.get()
                except QueueClosed:
                    return
                except PipelineAborted:
                    t.join(timeout=10)
                    if err:
                        raise err[0]
                    raise
                yield data
        finally:
            q.abort()
            t.join(timeout=10)
            self._record(os.path.getsize(self.path), stats["out"],
                         stats["s"], stats["n"])

    def close(self) -> None:
        if self._q is not None:
            self._q.abort()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None


def open_gzip_source(path: str) -> ByteSource:
    """Pick the inflate plan for a ``.gz`` input (selection matrix in
    the module docstring / docs/INGEST.md)."""
    size = os.path.getsize(path)
    if size == 0:
        return _EmptySource(path)
    fh = open(path, "rb")
    try:
        mm = mmap.mmap(fh.fileno(), 0, access=mmap.ACCESS_READ)
    except (ValueError, OSError):
        fh.close()
        return StreamSource(path)
    if bgzf_block_size(mm, 0, size) is not None:
        return BgzfSource(path, fh, mm)
    cands = [0]
    i = mm.find(_MEMBER_MAGIC, 1)
    while i != -1:
        cands.append(i)
        i = mm.find(_MEMBER_MAGIC, i + 1)
    if len(cands) > 1:
        return MemberSource(path, fh, mm, cands)
    mm.close()
    fh.close()
    return StreamSource(path)
