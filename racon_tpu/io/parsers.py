"""Streaming sequence / overlap format parsers (bioparser equivalent).

Covers the reference's five input formats — FASTA, FASTQ, MHAP, PAF, SAM — all
optionally gzip-compressed, with chunked (byte-budgeted) streaming so
genome-scale inputs never have to be fully resident
(reference API surface: bioparser createParser/parse_objects, called at
src/polisher.cpp:78-124, 172-283; 1 GiB chunking constant at
src/polisher.cpp:22).

Parsers yield *record tuples*; the domain constructors live in
racon_tpu.models. This mirrors the reference split where bioparser invokes
format-specific friend constructors (src/sequence.hpp:56-57,
src/overlap.hpp:71-73).

A C++ accelerated scanner can replace the hot tokenizing path later; the
Python implementations here are already line/block based (no per-char
loops) and handle multi-line FASTA and standard 4-line FASTQ.
"""

from __future__ import annotations

import gzip
import io
import os
from typing import Iterator, List, Optional, Tuple

import numpy as np

from racon_tpu.models.sequence import Sequence
from racon_tpu.models.overlap import Overlap
from racon_tpu.resilience.faults import InjectedFault

# Matches the reference's parse chunk size (src/polisher.cpp:22).
CHUNK_SIZE = 1024 * 1024 * 1024

_FASTA_EXTS = (".fasta", ".fa", ".fasta.gz", ".fa.gz")
_FASTQ_EXTS = (".fastq", ".fq", ".fastq.gz", ".fq.gz")
_SEQ_EXTS = _FASTA_EXTS + _FASTQ_EXTS
_OVL_EXTS = (".mhap", ".mhap.gz", ".paf", ".paf.gz", ".sam", ".sam.gz")


def _open(path: str) -> io.BufferedReader:
    if path.endswith(".gz"):
        return gzip.open(path, "rb")  # type: ignore[return-value]
    return open(path, "rb")


def _open_source(path: str):
    """The ingest-aware replacement for :func:`_open` on parser hot
    paths: with the ``RACON_TPU_INGEST`` gate on (default),
    ``.gz`` inputs open as a :class:`racon_tpu.io.inflate.ByteSource`
    — a context-managed *iterable of decompressed blocks* whose
    inflate runs on a worker pool (BGZF / multi-member) or a producer
    thread (single-member stream), all byte-identical to
    ``gzip.open``. :func:`_block_lines` accepts either shape. Gate
    off, or plain files: the classic file object."""
    if path.endswith(".gz"):
        from racon_tpu.io.ingest import ingest_enabled
        if ingest_enabled():
            from racon_tpu.io.inflate import open_gzip_source
            return open_gzip_source(path)
    return _open(path)


def _first_token(line: bytes) -> bytes:
    """Name = characters up to the first whitespace (bioparser semantics)."""
    for i, ch in enumerate(line):
        if ch in (0x20, 0x09):
            return line[:i]
    return line


class ParseError(RuntimeError):
    """Parser failure. ``offset``, when known, is the byte offset into
    the (decompressed) stream where the offending record begins — with
    chunked ``parse(max_bytes)`` streaming, "line number" is meaningless
    to a caller that resumed mid-file, but a byte offset can be handed
    straight to ``dd``/``tail -c`` for inspection."""

    def __init__(self, message: str, offset: Optional[int] = None):
        if offset is not None:
            message = f"{message} (at byte offset {offset})"
        super().__init__(message)
        self.offset = offset


class Parser:
    """Base streaming parser with reset() / parse(max_bytes) interface.

    parse(max_bytes) returns (records, more_remaining) like the reference's
    ``parse_objects(dst, max_bytes) -> bool`` (src/polisher.cpp:173,201,283).
    max_bytes < 0 parses everything.
    """

    def __init__(self, path: str):
        if not os.path.isfile(path):
            raise ParseError(f"[racon_tpu::io] error: unable to open file {path}")
        self.path = path
        self._iter: Optional[Iterator] = None
        self._failed = False
        self._pos = 0

    def reset(self) -> None:
        self._iter = None
        self._failed = False
        self._pos = 0

    def _records(self) -> Iterator[Tuple[object, int]]:
        raise NotImplementedError

    def _lines(self, f) -> Iterator[Tuple[bytes, int, int]]:
        """:func:`_block_lines` plus parser-side bookkeeping: tracks
        the high-water stream offset so a failure raised by the
        underlying ``read()`` itself — a truncated gzip member ends in
        EOFError with no record in hand — still gets a byte offset in
        its :class:`ParseError`, and arms the ``io/read`` fault site so
        stream-level failures are drillable deterministically."""
        from racon_tpu.resilience.faults import maybe_fault
        for ln, nb, off in _block_lines(f):
            self._pos = off + nb
            maybe_fault("io/read")
            yield ln, nb, off

    def parse(self, max_bytes: int = -1) -> Tuple[List[object], bool]:
        """One chunk of records, plus whether more remain.

        Repeated calls are safe to interleave with downstream
        consumption of earlier chunks: every returned record owns fresh
        immutable ``bytes`` (sliced out of the read blocks, never views
        into a shared mutable buffer), so the streaming pipeline's build
        stage can keep parsing while other threads still hold records
        from previous chunks.
        """
        if self._failed:
            raise ParseError(
                f"[racon_tpu::io] error: parser for {self.path} previously "
                "failed; call reset() before reuse")
        if self._iter is None:
            self._iter = self._records()
        out: List[object] = []
        consumed = 0
        try:
            for rec, nbytes in self._iter:
                out.append(rec)
                consumed += nbytes
                if 0 <= max_bytes <= consumed:
                    return out, True
        except ParseError:
            # The parallel inflate plane (io/inflate.py) raises typed,
            # offset-bearing errors of its own (member ordinal +
            # compressed offset); they pass through unchanged but still
            # poison the parser.
            self._failed = True
            raise
        except (gzip.BadGzipFile, EOFError, OSError) as exc:
            # A mislabelled .gz (or truncated stream) must surface as this
            # parser's own error contract, not a raw gzip exception. Mark
            # the parser failed so a retried parse() cannot masquerade as a
            # clean EOF. The offset is the high-water mark of complete
            # lines — the stream broke at or just past it.
            self._failed = True
            raise ParseError(
                f"[racon_tpu::io] error: corrupt or mislabelled input file "
                f"{self.path} ({exc})", offset=self._pos) from exc
        except InjectedFault as exc:
            # The io/read drill (resilience/faults.py) models exactly
            # the stream-level failure above, so it converts the same
            # way — typed, offset-bearing, parser poisoned.
            self._failed = True
            raise ParseError(
                f"[racon_tpu::io] error: read failure in {self.path} "
                f"({exc})", offset=self._pos) from exc
        self._iter = iter(())  # exhausted
        return out, False

    def parse_all(self) -> List[object]:
        self.reset()
        recs, _ = self.parse(-1)
        return recs


def _block_lines(f, block: int = 1 << 22
                 ) -> Iterator[Tuple[bytes, int, int]]:
    """Yield (line, nbytes, offset) via block reads + split; line is
    newline/CR stripped, nbytes is the exact on-stream length including
    the line terminator (for byte-budgeted chunking), offset the byte
    position of the line's start in the decompressed stream (for
    :class:`ParseError` diagnostics).

    Per-line ``readline`` on a gzip stream pays Python call overhead for
    every line — a genome-scale cost (tens of millions of lines at 30x
    human coverage); one 4 MB read + one split amortizes it away.
    """
    if hasattr(f, "read"):
        blocks_iter = iter(lambda: f.read(block), b"")
    else:
        # An ingest ByteSource (io/inflate.py): already an iterable of
        # decompressed blocks — empty blocks are skipped, not EOF.
        blocks_iter = (b for b in f if b)
    tail: List[bytes] = []          # blocks of the current partial line
    pos = 0                         # stream offset of the current line
    for data in blocks_iter:
        if b"\n" not in data:
            # No terminator in this block: defer the join, or a single
            # line longer than the block size (one-contig-per-line
            # drafts) turns quadratic in re-concatenation.
            tail.append(data)
            continue
        parts = (b"".join(tail) + data if tail else data).split(b"\n")
        last = parts.pop()
        tail = [last] if last else []
        for ln in parts:
            nb = len(ln) + 1
            yield ln.rstrip(b"\r"), nb, pos
            pos += nb
    if tail:
        last = b"".join(tail)
        yield last.rstrip(b"\r"), len(last), pos


def scan_sequence_index(path: str) -> Tuple[int, List[int]]:
    """(record count, per-record byte offsets) of a FASTA/FASTQ file
    WITHOUT materializing any sequence — one streaming pass that only
    looks at record structure. Offsets are each record header's byte
    position in the decompressed stream (``dd``/``tail -c`` friendly,
    same convention as :class:`ParseError`).

    The distributed ledger publishes this index in ``meta.json`` once:
    workers that join an already-published ledger used to run a FULL
    parse of the target file just to count records for the shard
    partition (docs/DISTRIBUTED.md's duplication note) — the scan keeps
    the count cheap for the one publishing worker, and every other
    worker skips the pass entirely.
    """
    from racon_tpu.io.ingest import indexed_ok, scan_index_mmap
    if indexed_ok(path) and path.endswith(_SEQ_EXTS):
        return scan_index_mmap(path)
    offsets: List[int] = []
    hw = [0]                 # high-water offset for stream-level errors

    def _tracked(f) -> Iterator[Tuple[bytes, int, int]]:
        for ln, nb, off in _block_lines(f):
            hw[0] = off + nb
            yield ln, nb, off

    try:
        return _scan_index(path, offsets, _tracked)
    except (gzip.BadGzipFile, EOFError, OSError) as exc:
        raise ParseError(
            f"[racon_tpu::io] error: corrupt or truncated sequence "
            f"file {path} ({exc})", offset=hw[0]) from exc


def _scan_index(path: str, offsets: List[int],
                lines_of) -> Tuple[int, List[int]]:
    if path.endswith(_FASTA_EXTS):
        with _open_source(path) as f:
            for line, _, off in lines_of(f):
                if line.startswith(b">"):
                    offsets.append(off)
    elif path.endswith(_FASTQ_EXTS):
        with _open_source(path) as f:
            lines = lines_of(f)
            while True:
                header, _, rec_off = next(lines, (None, 0, 0))
                if header is None:
                    break
                if not header:
                    continue
                if not header.startswith(b"@"):
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed FASTQ file "
                        f"{path}", offset=rec_off)
                offsets.append(rec_off)
                dlen = 0
                while True:
                    line, _, _ = next(lines, (None, 0, 0))
                    if line is None:
                        raise ParseError(
                            f"[racon_tpu::io] error: truncated FASTQ "
                            f"file {path} — EOF inside the record "
                            f"starting", offset=rec_off)
                    if line.startswith(b"+"):
                        break
                    dlen += len(line)
                qlen = 0
                while qlen < dlen:
                    line, _, _ = next(lines, (None, 0, 0))
                    if line is None:
                        raise ParseError(
                            f"[racon_tpu::io] error: truncated FASTQ "
                            f"file {path} — EOF inside the record "
                            f"starting", offset=rec_off)
                    qlen += len(line)
                if qlen != dlen:
                    raise ParseError(
                        f"[racon_tpu::io] error: quality length mismatch "
                        f"in {path} (sequence {dlen}, quality {qlen})",
                        offset=rec_off)
    else:
        raise ParseError(
            f"[racon_tpu::create_polisher] error: file {path} has "
            "unsupported format extension (valid extensions: .fasta, "
            ".fasta.gz, .fa, .fa.gz, .fastq, .fastq.gz, .fq, .fq.gz)!")
    return len(offsets), offsets


class FastaParser(Parser):
    def _records(self) -> Iterator[Tuple[Sequence, int]]:
        name: Optional[bytes] = None
        chunks: List[bytes] = []
        with _open_source(self.path) as f:
            for line, _, off in self._lines(f):
                if line.startswith(b">"):
                    if name is not None:
                        data = b"".join(chunks)
                        yield Sequence(name.decode(), data), len(name) + len(data)
                    name = _first_token(line[1:])
                    chunks = []
                elif line:
                    if name is None:
                        raise ParseError(
                            f"[racon_tpu::io] error: malformed FASTA file "
                            f"{self.path}", offset=off)
                    chunks.append(line)
            if name is not None:
                data = b"".join(chunks)
                yield Sequence(name.decode(), data), len(name) + len(data)


class FastqParser(Parser):
    def _records(self) -> Iterator[Tuple[Sequence, int]]:
        with _open_source(self.path) as f:
            lines = self._lines(f)
            while True:
                header, _, rec_off = next(lines, (None, 0, 0))
                if header is None:
                    return
                if not header:
                    continue
                if not header.startswith(b"@"):
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed FASTQ file "
                        f"{self.path}", offset=rec_off)
                name = _first_token(header[1:])
                # Sequence lines until '+' separator (tolerates multi-line).
                data_chunks: List[bytes] = []
                while True:
                    line, _, _ = next(lines, (None, 0, 0))
                    if line is None:
                        # EOF inside a record: report where the partial
                        # record begins, not just which file broke.
                        raise ParseError(
                            f"[racon_tpu::io] error: truncated FASTQ "
                            f"file {self.path} — EOF inside the record "
                            f"starting", offset=rec_off)
                    if line.startswith(b"+"):
                        break
                    data_chunks.append(line)
                data = b"".join(data_chunks)
                qual_chunks: List[bytes] = []
                qlen = 0
                while qlen < len(data):
                    line, _, _ = next(lines, (None, 0, 0))
                    if line is None:
                        raise ParseError(
                            f"[racon_tpu::io] error: truncated FASTQ "
                            f"file {self.path} — EOF inside the record "
                            f"starting", offset=rec_off)
                    qual_chunks.append(line)
                    qlen += len(line)
                quality = b"".join(qual_chunks)
                if len(quality) != len(data):
                    # Silently mis-sized quality would flow into window
                    # weighting downstream; name the record and where it
                    # begins so the input is fixable.
                    raise ParseError(
                        f"[racon_tpu::io] error: quality length mismatch "
                        f"in {self.path} for record '{name.decode()}' "
                        f"(sequence {len(data)}, quality {len(quality)})",
                        offset=rec_off)
                # Phred bytes below '!' (33) would decode to negative
                # weights; reject here so every downstream consumer (host
                # and device consensus paths) can assume weights >= 0 by
                # construction instead of each clipping differently.
                if quality and int(
                        np.frombuffer(quality, np.uint8).min()) < 33:
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed quality string "
                        f"(byte below '!') in {self.path}", offset=rec_off)
                yield Sequence(name.decode(), data, quality), len(name) + 2 * len(data)


class MhapParser(Parser):
    """MHAP: 12 space-separated columns
    (a_id b_id accuracy shared_minmers a_rc a_begin a_end a_len b_rc b_begin
    b_end b_len) — reference ctor at src/overlap.cpp:15-27."""

    def _records(self) -> Iterator[Tuple[Overlap, int]]:
        with _open_source(self.path) as f:
            for line, nb, off in self._lines(f):
                if not line:
                    continue
                t = line.split()
                if len(t) < 12:
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed MHAP file "
                        f"{self.path}", offset=off)
                yield Overlap.from_mhap(
                    int(t[0]), int(t[1]), float(t[2]), int(t[3]),
                    int(t[4]), int(t[5]), int(t[6]), int(t[7]),
                    int(t[8]), int(t[9]), int(t[10]), int(t[11]),
                ), nb


class PafParser(Parser):
    """PAF: >=12 tab-separated columns (qname qlen qstart qend strand tname
    tlen tstart tend matches alnlen mapq ...) — reference ctor at
    src/overlap.cpp:29-42."""

    def _records(self) -> Iterator[Tuple[Overlap, int]]:
        with _open_source(self.path) as f:
            for line, nb, off in self._lines(f):
                if not line:
                    continue
                t = line.split(b"\t")
                if len(t) < 12:
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed PAF file "
                        f"{self.path}", offset=off)
                yield Overlap.from_paf(
                    t[0].decode(), int(t[1]), int(t[2]), int(t[3]),
                    t[4].decode(), t[5].decode(), int(t[6]), int(t[7]),
                    int(t[8]),
                ), nb


class SamParser(Parser):
    """SAM: 11+ tab-separated columns; header lines (@...) skipped —
    reference ctor at src/overlap.cpp:44-108."""

    def _records(self) -> Iterator[Tuple[Overlap, int]]:
        with _open_source(self.path) as f:
            for line, nb, off in self._lines(f):
                if line.startswith(b"@"):
                    continue
                if not line:
                    continue
                t = line.split(b"\t")
                if len(t) < 11:
                    raise ParseError(
                        f"[racon_tpu::io] error: malformed SAM file "
                        f"{self.path}", offset=off)
                yield Overlap.from_sam(
                    t[0].decode(), int(t[1]), t[2].decode(), int(t[3]),
                    t[5].decode(),
                ), nb


def create_sequence_parser(path: str) -> Parser:
    """Extension-dispatched sequence parser (src/polisher.cpp:78-92).

    Plain (uncompressed) FASTA/FASTQ with the ``RACON_TPU_INGEST`` gate
    on route to the mmap index-first readers (io/ingest.py) — byte-
    identical records with zero-copy payload views; ``.gz`` inputs and
    the gate-off escape hatch use the classic streaming readers (whose
    ``.gz`` open itself routes through the parallel inflate plane when
    the gate is on)."""
    if path.endswith(_FASTA_EXTS):
        from racon_tpu.io.ingest import IndexedFastaParser, indexed_ok
        return IndexedFastaParser(path) if indexed_ok(path) \
            else FastaParser(path)
    if path.endswith(_FASTQ_EXTS):
        from racon_tpu.io.ingest import IndexedFastqParser, indexed_ok
        return IndexedFastqParser(path) if indexed_ok(path) \
            else FastqParser(path)
    raise ParseError(
        f"[racon_tpu::create_polisher] error: file {path} has unsupported format "
        "extension (valid extensions: .fasta, .fasta.gz, .fa, .fa.gz, .fastq, "
        ".fastq.gz, .fq, .fq.gz)!"
    )


def create_overlap_parser(path: str) -> Parser:
    """Extension-dispatched overlap parser (src/polisher.cpp:94-108)."""
    if path.endswith((".mhap", ".mhap.gz")):
        return MhapParser(path)
    if path.endswith((".paf", ".paf.gz")):
        return PafParser(path)
    if path.endswith((".sam", ".sam.gz")):
        return SamParser(path)
    raise ParseError(
        f"[racon_tpu::create_polisher] error: file {path} has unsupported format "
        "extension (valid extensions: .mhap, .mhap.gz, .paf, .paf.gz, .sam, "
        ".sam.gz)!"
    )
