"""Command-line interface, mirroring the reference ``racon`` CLI.

Flags, defaults, help text, and output format follow the reference's
getopt table and help() (src/main.cpp:14-160): polished sequences are
emitted as FASTA on stdout, diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from racon_tpu import __version__

_USAGE = "racon_tpu [options ...] <sequences> <overlaps> <target sequences>"


class _Interrupted(Exception):
    """SIGINT/SIGTERM re-raised as an exception so teardown runs in
    order: pipeline abort-cascade (generator close), checkpoint store
    close (commits are already fsync'd), trace finalization — then a
    conventional 128+signum exit instead of a traceback."""

    def __init__(self, signum: int):
        super().__init__(f"signal {signum}")
        self.signum = signum

_DESCRIPTION = """\
    <sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences used for correction
    <overlaps>
        input file in MHAP/PAF/SAM format (can be compressed with gzip)
        containing overlaps between sequences and target sequences
    <target sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences which will be corrected
"""


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="racon_tpu", usage=_USAGE, description=_DESCRIPTION,
        formatter_class=argparse.RawDescriptionHelpFormatter, add_help=False)
    ap.add_argument("paths", nargs="*", metavar="<file>")
    ap.add_argument("-u", "--include-unpolished", action="store_true",
                    help="output unpolished target sequences")
    ap.add_argument("-f", "--fragment-correction", action="store_true",
                    help="perform fragment correction instead of contig "
                         "polishing (overlaps file should contain dual/self "
                         "overlaps!)")
    ap.add_argument("-w", "--window-length", type=int, default=500,
                    help="default: 500; size of window on which POA is "
                         "performed")
    ap.add_argument("-q", "--quality-threshold", type=float, default=10.0,
                    help="default: 10.0; threshold for average base quality "
                         "of windows used in POA")
    ap.add_argument("-e", "--error-threshold", type=float, default=0.3,
                    help="default: 0.3; maximum allowed error rate used for "
                         "filtering overlaps")
    ap.add_argument("-m", "--match", type=int, default=5,
                    help="default: 5; score for matching bases")
    ap.add_argument("-x", "--mismatch", type=int, default=-4,
                    help="default: -4; score for mismatching bases")
    ap.add_argument("-g", "--gap", type=int, default=-8,
                    help="default: -8; gap penalty (must be negative)")
    ap.add_argument("-t", "--threads", type=int, default=1,
                    help="default: 1; OS threads for the native host "
                         "aligner (<=0 uses all cores); device execution "
                         "is batched, not threaded")
    ap.add_argument("--backend", choices=["auto", "jax", "native"],
                    default="auto",
                    help="default: auto; alignment backend — 'jax' targets "
                         "the TPU/accelerator, 'native' the C++ host "
                         "aligner, 'auto' picks by available hardware")
    ap.add_argument("--dp", type=int, default=0, metavar="N",
                    help="default: 0 (single device); shard consensus "
                         "chunks over a data-parallel mesh of N devices "
                         "(see docs/DISTRIBUTED.md)")
    ap.add_argument("--distributed", action="store_true",
                    help="multi-host: call jax.distributed.initialize() "
                         "(coordinator/process env auto-detected on TPU "
                         "pods) before building the device mesh; combine "
                         "with --dp <total devices>")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    metavar="N",
                    help="default: unset (RACON_TPU_PIPELINE decides); "
                         "N>0 enables the streaming execution pipeline "
                         "with N in-flight chunks per stage (2 = double "
                         "buffering), 0 forces the serial path (see "
                         "docs/PIPELINE.md)")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="write a structured JSONL run trace to PATH "
                         "(same as RACON_TPU_TRACE=PATH; render with "
                         "scripts/obs_report.py — see "
                         "docs/OBSERVABILITY.md)")
    ap.add_argument("--checkpoint-dir", metavar="DIR", default=None,
                    help="checkpoint each polished contig into DIR "
                         "(FASTA shard + manifest, fsync'd per commit) "
                         "so a killed run can continue with --resume "
                         "(see docs/RESILIENCE.md)")
    ap.add_argument("--resume", action="store_true",
                    help="continue from --checkpoint-dir: committed "
                         "contigs re-emit byte-identically from the "
                         "shard, only the rest recompute; refuses if "
                         "inputs or output-affecting options changed")
    ap.add_argument("--cache-dir", metavar="DIR", default=None,
                    help="arm the content-addressed result cache in "
                         "DIR: a run whose inputs + options fingerprint "
                         "matches a stored entry re-emits it "
                         "byte-identically with zero consensus "
                         "dispatches (verify-on-hit; RACON_TPU_CACHE=0 "
                         "disables — see docs/CACHE.md)")
    ap.add_argument("--ledger-dir", metavar="DIR", default=None,
                    help="join (or start) the contig work ledger in "
                         "DIR as one worker of a preemptible fleet: "
                         "targets are sharded, leased, checkpointed "
                         "per shard, and stolen from evicted workers; "
                         "exactly one worker emits the merged FASTA "
                         "(see docs/DISTRIBUTED.md)")
    ap.add_argument("--workers", type=int, default=1, metavar="N",
                    help="default: 1; fleet size hint for the ledger's "
                         "shard partition (~2 shards per worker); only "
                         "the first worker to publish the ledger "
                         "decides")
    ap.add_argument("--worker-id", metavar="ID", default=None,
                    help="default: <hostname>-<pid>; stable identity "
                         "for lease ownership and the events audit "
                         "log")
    ap.add_argument("--lease-s", type=float, default=30.0, metavar="S",
                    help="default: 30.0; shard lease duration — an "
                         "evicted worker's shard becomes stealable S "
                         "seconds after its last renewal (each "
                         "committed contig renews)")
    ap.add_argument("--autoscale", action="store_true",
                    help="supervise an elastic fleet against "
                         "--ledger-dir instead of polishing: spawn "
                         "worker subprocesses (this same command minus "
                         "--autoscale) up to --workers, replace sick "
                         "ones, retire surplus, and emit the merged "
                         "FASTA on stdout (RACON_TPU_AUTOSCALE_* "
                         "tunes the policy; see docs/DISTRIBUTED.md)")
    ap.add_argument("--version", action="store_true",
                    help="prints the version number")
    ap.add_argument("-h", "--help", action="store_true",
                    help="prints the usage")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    # The autoscaler re-executes this same command line per spawned
    # worker, so keep the unparsed form around.
    raw_argv = list(argv) if argv is not None else sys.argv[1:]
    ap = build_parser()
    args = ap.parse_args(argv)

    if args.version:
        print(f"v{__version__}")
        return 0
    if args.help:
        ap.print_help()
        return 0
    if len(args.paths) < 3:
        print("[racon_tpu::] error: missing input file(s)!", file=sys.stderr)
        ap.print_help(sys.stderr)
        return 1
    # Below every early return: --version/--help/usage errors should not
    # pay the jax import the cache setup triggers.
    import time as _time
    _wall_t0 = _time.perf_counter()
    from racon_tpu.obs.trace import configure as configure_trace
    tracer = configure_trace(args.trace)
    from racon_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()

    import os as _os
    from racon_tpu.utils import envspec as _envspec
    metrics_port = _envspec.read("RACON_TPU_METRICS_PORT")
    if metrics_port:
        # Live OpenMetrics pull endpoint (daemon thread, dies with the
        # process): serves this worker's registry; fleet-wide scrapes
        # aggregate the ledger dir via scripts/obs_export.py instead.
        from racon_tpu.obs.export import (fleet_health, render_registry,
                                          serve_metrics)
        from racon_tpu.obs.metrics import registry as _reg
        from racon_tpu.resilience.watchdog import health_snapshot
        if args.ledger_dir:
            # Fleet members (and the supervisor) answer /healthz with
            # the whole fleet's view — live/evicted/retired workers,
            # open shards, autoscaler heartbeat age; a dead supervisor
            # turns the probe 503 so orchestrators restart it.
            _ld = args.ledger_dir
            health = lambda: fleet_health(_ld, base=health_snapshot)
        else:
            health = health_snapshot
        try:
            serve_metrics(int(metrics_port),
                          lambda: render_registry(_reg().snapshot()),
                          health=health)
        except (ValueError, OSError) as exc:
            print(f"[racon_tpu::] error: cannot serve metrics on port "
                  f"{metrics_port!r}: {exc}", file=sys.stderr)
            return 1

    from racon_tpu.models.overlap import PolisherError
    from racon_tpu.io.parsers import ParseError
    from racon_tpu.pipeline import configure as configure_pipeline
    from racon_tpu.pipeline import pipeline_enabled
    from racon_tpu.server.engine import JobHooks, JobSpec, build_polisher
    from racon_tpu.server.engine import polish_job
    from racon_tpu.utils.logger import Logger

    try:
        configure_pipeline(args.pipeline_depth)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 1

    logger = Logger()
    mesh = None
    if args.distributed or args.dp:
        import numpy as _np
        import jax
        if args.dp < 0:
            print(f"[racon_tpu::] error: invalid --dp {args.dp}!",
                  file=sys.stderr)
            return 1
        if args.distributed:
            # Multi-host: every host runs this same command; coordinator
            # address / process count / process id come from the TPU pod
            # runtime environment (docs/DISTRIBUTED.md has the recipe).
            jax.distributed.initialize()
        devs = jax.devices()
        ndp = args.dp if args.dp > 0 else len(devs)
        if ndp > len(devs):
            print(f"[racon_tpu::] error: --dp {ndp} exceeds the "
                  f"{len(devs)} visible devices!", file=sys.stderr)
            return 1
        if args.distributed and ndp != len(devs):
            # A mesh over devs[:ndp] would exclude some hosts' local
            # devices, which the runtime rejects (or deadlocks on);
            # multi-host meshes must span the global device set.
            print(f"[racon_tpu::] error: --distributed requires --dp to "
                  f"match the global device count ({len(devs)}); shard "
                  "hosts with the wrapper instead (docs/DISTRIBUTED.md)",
                  file=sys.stderr)
            return 1
        from jax.sharding import Mesh
        mesh = Mesh(_np.asarray(devs[:ndp]), ("dp",))

    out = sys.stdout.buffer
    store = None
    if args.resume and not args.checkpoint_dir:
        print("[racon_tpu::] error: --resume requires --checkpoint-dir!",
              file=sys.stderr)
        return 1
    if args.ledger_dir and (args.checkpoint_dir or args.resume):
        print("[racon_tpu::] error: --ledger-dir manages per-shard "
              "checkpoints itself; drop --checkpoint-dir/--resume!",
              file=sys.stderr)
        return 1
    if args.ledger_dir and args.cache_dir:
        print("[racon_tpu::] error: --cache-dir is a whole-run store; "
              "it does not compose with --ledger-dir's per-shard "
              "leases!", file=sys.stderr)
        return 1
    if args.ledger_dir and args.workers < 1:
        print(f"[racon_tpu::] error: invalid --workers {args.workers}!",
              file=sys.stderr)
        return 1
    if args.ledger_dir and args.lease_s <= 0:
        print(f"[racon_tpu::] error: invalid --lease-s {args.lease_s}!",
              file=sys.stderr)
        return 1
    if args.autoscale:
        if not args.ledger_dir:
            print("[racon_tpu::] error: --autoscale requires "
                  "--ledger-dir!", file=sys.stderr)
            return 1
        # Supervisor mode: no polishing in this process — spawn and
        # shepherd worker subprocesses (this same command line minus
        # --autoscale) until the merged FASTA lands, then emit it.
        from racon_tpu.distributed.autoscaler import run_supervisor
        from racon_tpu.distributed.ledger import LedgerError
        try:
            return run_supervisor(ledger_dir=args.ledger_dir,
                                  raw_argv=raw_argv,
                                  default_max=args.workers, out=out)
        except LedgerError as exc:
            print(str(exc), file=sys.stderr)
            return 1
        finally:
            tracer.finish()
    # Everything that changes emitted bytes goes into the run
    # fingerprint (checkpoint and ledger identity alike) — single
    # source: JobSpec.identity() (racon_tpu/server/engine.py), which
    # the daemon's job journal shares, so a daemon job and a solo CLI
    # run agree on what "the same run" means. Backend / mesh /
    # pipeline knobs are excluded because the execution paths are
    # bit-identical by design.
    spec = JobSpec(
        args.paths[0], args.paths[1], args.paths[2],
        include_unpolished=args.include_unpolished,
        fragment_correction=args.fragment_correction,
        window_length=args.window_length,
        quality_threshold=args.quality_threshold,
        error_threshold=args.error_threshold, match=args.match,
        mismatch=args.mismatch, gap=args.gap, backend=args.backend,
        threads=args.threads)
    ckpt_config = spec.identity()
    if args.checkpoint_dir:
        from racon_tpu.resilience.checkpoint import (CheckpointError,
                                                     CheckpointStore,
                                                     run_fingerprint)
        try:
            fp = run_fingerprint(ckpt_config, args.paths[:3])
            from racon_tpu.ava import seg_targets_for
            store = (CheckpointStore.resume(args.checkpoint_dir, fp)
                     if args.resume else
                     CheckpointStore.create(
                         args.checkpoint_dir, fp,
                         segment_targets=seg_targets_for(
                             args.fragment_correction)))
        except (CheckpointError, OSError) as exc:
            print(str(exc), file=sys.stderr)
            return 1
        if args.resume and store.committed:
            print(f"[racon_tpu::] resuming: {len(store.committed)} "
                  f"contig(s) already committed in "
                  f"{args.checkpoint_dir}", file=sys.stderr)

    # Serial-CLI Tier-1 cache: armed only by --cache-dir (the daemon
    # arms by default), globally killable via RACON_TPU_CACHE=0.
    result_cache = None
    if args.cache_dir:
        from racon_tpu.cache import cache_enabled
        if cache_enabled():
            from racon_tpu.cache import ResultCache
            try:
                result_cache = ResultCache(args.cache_dir)
            except Exception as exc:
                print(str(exc), file=sys.stderr)
                return 1

    import signal
    import threading
    old_handlers = {}
    if threading.current_thread() is threading.main_thread():
        def _on_signal(signum, frame):
            raise _Interrupted(signum)
        for s in (signal.SIGINT, signal.SIGTERM):
            old_handlers[s] = signal.signal(s, _on_signal)

    from racon_tpu.obs import fleet
    from racon_tpu.obs.metrics import registry as obs_registry
    rc = 0

    obs_dir = _envspec.read(fleet.ENV_OBS_DIR)
    if obs_dir and not args.ledger_dir:
        # Serial runs join the fleet observability plane on request:
        # the same metric shard a ledger worker writes (workers install
        # their own writer under <ledger-dir>/obs at join time).
        from racon_tpu.resilience.checkpoint import run_fingerprint
        fp = run_fingerprint(ckpt_config, args.paths[:3])
        wid = args.worker_id or f"serial-{_os.getpid()}"
        fleet.install_writer(obs_dir, wid, fp)
        tracer.set_context(worker_id=wid, run_fp=fp)

    if not args.ledger_dir:
        # Serial runs spawned by another process (tests, orchestration)
        # adopt its trace context from RACON_TPU_TRACE_CTX; ledger
        # workers adopt inside run_worker (env first, then ledger meta).
        from racon_tpu.obs.trace import adopt_trace_context
        adopt_trace_context(tracer=tracer)

    def make_polisher():
        return build_polisher(spec, logger=logger, mesh=mesh)

    try:
        with tracer.span("run", "racon_tpu"):
            if args.ledger_dir:
                from racon_tpu.distributed.worker import run_worker
                from racon_tpu.io.parsers import scan_sequence_index
                from racon_tpu.resilience.checkpoint import \
                    run_fingerprint
                fp = run_fingerprint(ckpt_config, args.paths[:3])
                # Deferred target count: only the worker that publishes
                # the ledger meta scans the target file; later joiners
                # adopt the published count + offsets (satellite of
                # ROADMAP item 2 — per-worker full parses were pure
                # duplicated I/O).
                rc = run_worker(
                    ledger_dir=args.ledger_dir, fingerprint=fp,
                    scan_targets=lambda: scan_sequence_index(
                        args.paths[2]),
                    worker_id=args.worker_id,
                    workers=args.workers, lease_s=args.lease_s,
                    make_polisher=make_polisher,
                    drop_unpolished=not args.include_unpolished,
                    fragment_correction=args.fragment_correction,
                    window_length=args.window_length,
                    out=out)
            else:
                # The serial frontend is now a thin call into the
                # shared engine loop (racon_tpu/server/engine.py):
                # resume pruning, stored-blob re-emission interleaved
                # with fresh records in input order, and durable
                # per-contig commits all live there — one
                # implementation for CLI, ledger worker, and daemon.
                def _resume_log(n_committed: int, n_skip: int) -> None:
                    if n_skip:
                        print("[racon_tpu::] resume: skipping "
                              f"recompute of {n_skip} window(s)",
                              file=sys.stderr)

                # Cache probe/store only applies to runs starting from
                # scratch — a resumed run's committed prefix already
                # owns the output interleaving.
                fresh = store is None or not store.committed
                hit = None
                if result_cache is not None and fresh:
                    hit = result_cache.load(spec.fingerprint())
                if hit is not None:
                    from racon_tpu.cache import replay_records
                    n = replay_records(hit, emit=out.write, store=store)
                    print(f"[racon_tpu::] cache: re-emitted {n} "
                          f"contig(s) from {args.cache_dir} (zero "
                          f"consensus dispatches)", file=sys.stderr)
                else:
                    captured = [] if (result_cache is not None and
                                      fresh) else None

                    def _capture(tid, rec):
                        if rec is None:
                            captured.append((tid, None, b""))
                        else:
                            captured.append((tid, rec.name.encode(),
                                             rec.data))

                    polish_job(
                        make_polisher,
                        drop_unpolished=not args.include_unpolished,
                        store=store, emit=out.write,
                        hooks=JobHooks(
                            on_resume=_resume_log,
                            after_commit=_capture
                            if captured is not None else None))
                    if captured is not None:
                        result_cache.store(spec.fingerprint(), captured)
    except (PolisherError, ParseError, ValueError) as exc:
        print(str(exc), file=sys.stderr)
        return 1
    except _Interrupted as exc:
        out.flush()
        if args.ledger_dir:
            print(f"[racon_tpu::] interrupted (signal {exc.signum}); "
                  f"committed contigs are safe in {args.ledger_dir} — "
                  "this worker's lease will expire and a survivor (or "
                  "a rerun) will steal its shard", file=sys.stderr)
        elif store is not None:
            print(f"[racon_tpu::] interrupted (signal {exc.signum}); "
                  f"{len(store.committed)} contig(s) committed in "
                  f"{args.checkpoint_dir} — rerun with --resume",
                  file=sys.stderr)
        else:
            print(f"[racon_tpu::] interrupted (signal {exc.signum})",
                  file=sys.stderr)
        # The eviction contract: a SIGTERM'd worker leaves a *final*
        # metric snapshot (and a flight-recorder dump) for the fleet
        # aggregator before dying.
        fleet.flush_final(reason=f"signal-{exc.signum}")
        tracer.finish(metrics=obs_registry().snapshot())
        return 128 + exc.signum
    except Exception as exc:
        # Terminal watchdog breach on the SERIAL path (the ledger loop
        # handles its own self-eviction before returning): this host is
        # wedged — flush what we have and exit the distinct self-evict
        # code so supervisors reschedule elsewhere instead of retrying
        # here. Anything non-terminal propagates unchanged.
        from racon_tpu.resilience.watchdog import (EXIT_SELF_EVICT,
                                                   is_terminal)
        if not is_terminal(exc):
            raise
        out.flush()
        print(f"[racon_tpu::] terminal watchdog breach — {exc}",
              file=sys.stderr)
        fleet.flush_final(reason="watchdog-terminal")
        tracer.finish(metrics=obs_registry().snapshot())
        return EXIT_SELF_EVICT
    finally:
        for s, h in old_handlers.items():
            signal.signal(s, h)
        if store is not None:
            store.close()
    out.flush()
    logger.total("[racon_tpu::Polisher::] total =")
    from racon_tpu.obs.metrics import (pipeline_extras,
                                       set_ingest_fraction, walk_extras)
    from racon_tpu.utils.jaxcache import cache_extras
    from racon_tpu.io.ingest import ingest_enabled
    reg = obs_registry()
    for k, v in cache_extras(reg).items():
        reg.set(k, v)
    for k, v in pipeline_extras(reg).items():
        reg.set(k, v)
    for k, v in walk_extras(reg).items():
        reg.set(k, v)
    if int(reg.get("ingest_records", 0)):
        reg.set("ingest_enabled", int(ingest_enabled()))
        set_ingest_fraction(_time.perf_counter() - _wall_t0, reg)
    fleet.flush_final()
    tracer.finish(metrics=reg.snapshot())
    return rc


if __name__ == "__main__":
    sys.exit(main())
