"""Command-line interface, mirroring the reference ``racon`` CLI.

Flags, defaults, help text, and output format follow the reference's
getopt table and help() (src/main.cpp:14-160): polished sequences are
emitted as FASTA on stdout, diagnostics go to stderr.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional

from racon_tpu import __version__

_USAGE = "racon_tpu [options ...] <sequences> <overlaps> <target sequences>"

_DESCRIPTION = """\
    <sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences used for correction
    <overlaps>
        input file in MHAP/PAF/SAM format (can be compressed with gzip)
        containing overlaps between sequences and target sequences
    <target sequences>
        input file in FASTA/FASTQ format (can be compressed with gzip)
        containing sequences which will be corrected
"""


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="racon_tpu", usage=_USAGE, description=_DESCRIPTION,
        formatter_class=argparse.RawDescriptionHelpFormatter, add_help=False)
    ap.add_argument("paths", nargs="*", metavar="<file>")
    ap.add_argument("-u", "--include-unpolished", action="store_true",
                    help="output unpolished target sequences")
    ap.add_argument("-f", "--fragment-correction", action="store_true",
                    help="perform fragment correction instead of contig "
                         "polishing (overlaps file should contain dual/self "
                         "overlaps!)")
    ap.add_argument("-w", "--window-length", type=int, default=500,
                    help="default: 500; size of window on which POA is "
                         "performed")
    ap.add_argument("-q", "--quality-threshold", type=float, default=10.0,
                    help="default: 10.0; threshold for average base quality "
                         "of windows used in POA")
    ap.add_argument("-e", "--error-threshold", type=float, default=0.3,
                    help="default: 0.3; maximum allowed error rate used for "
                         "filtering overlaps")
    ap.add_argument("-m", "--match", type=int, default=5,
                    help="default: 5; score for matching bases")
    ap.add_argument("-x", "--mismatch", type=int, default=-4,
                    help="default: -4; score for mismatching bases")
    ap.add_argument("-g", "--gap", type=int, default=-8,
                    help="default: -8; gap penalty (must be negative)")
    ap.add_argument("-t", "--threads", type=int, default=1,
                    help="default: 1; OS threads for the native host "
                         "aligner (<=0 uses all cores); device execution "
                         "is batched, not threaded")
    ap.add_argument("--backend", choices=["auto", "jax", "native"],
                    default="auto",
                    help="default: auto; alignment backend — 'jax' targets "
                         "the TPU/accelerator, 'native' the C++ host "
                         "aligner, 'auto' picks by available hardware")
    ap.add_argument("--dp", type=int, default=0, metavar="N",
                    help="default: 0 (single device); shard consensus "
                         "chunks over a data-parallel mesh of N devices "
                         "(see docs/DISTRIBUTED.md)")
    ap.add_argument("--distributed", action="store_true",
                    help="multi-host: call jax.distributed.initialize() "
                         "(coordinator/process env auto-detected on TPU "
                         "pods) before building the device mesh; combine "
                         "with --dp <total devices>")
    ap.add_argument("--pipeline-depth", type=int, default=None,
                    metavar="N",
                    help="default: unset (RACON_TPU_PIPELINE decides); "
                         "N>0 enables the streaming execution pipeline "
                         "with N in-flight chunks per stage (2 = double "
                         "buffering), 0 forces the serial path (see "
                         "docs/PIPELINE.md)")
    ap.add_argument("--trace", metavar="PATH", default=None,
                    help="write a structured JSONL run trace to PATH "
                         "(same as RACON_TPU_TRACE=PATH; render with "
                         "scripts/obs_report.py — see "
                         "docs/OBSERVABILITY.md)")
    ap.add_argument("--version", action="store_true",
                    help="prints the version number")
    ap.add_argument("-h", "--help", action="store_true",
                    help="prints the usage")
    return ap


def main(argv: Optional[List[str]] = None) -> int:
    ap = build_parser()
    args = ap.parse_args(argv)

    if args.version:
        print(f"v{__version__}")
        return 0
    if args.help:
        ap.print_help()
        return 0
    if len(args.paths) < 3:
        print("[racon_tpu::] error: missing input file(s)!", file=sys.stderr)
        ap.print_help(sys.stderr)
        return 1
    # Below every early return: --version/--help/usage errors should not
    # pay the jax import the cache setup triggers.
    from racon_tpu.obs.trace import configure as configure_trace
    tracer = configure_trace(args.trace)
    from racon_tpu.utils.jaxcache import enable_compile_cache
    enable_compile_cache()

    from racon_tpu.models.overlap import PolisherError
    from racon_tpu.io.parsers import ParseError
    from racon_tpu.models.polisher import PolisherType, create_polisher
    from racon_tpu.pipeline import configure as configure_pipeline
    from racon_tpu.pipeline import pipeline_enabled
    from racon_tpu.utils.logger import Logger

    try:
        configure_pipeline(args.pipeline_depth)
    except ValueError as exc:
        print(str(exc), file=sys.stderr)
        return 1

    logger = Logger()
    mesh = None
    if args.distributed or args.dp:
        import numpy as _np
        import jax
        if args.dp < 0:
            print(f"[racon_tpu::] error: invalid --dp {args.dp}!",
                  file=sys.stderr)
            return 1
        if args.distributed:
            # Multi-host: every host runs this same command; coordinator
            # address / process count / process id come from the TPU pod
            # runtime environment (docs/DISTRIBUTED.md has the recipe).
            jax.distributed.initialize()
        devs = jax.devices()
        ndp = args.dp if args.dp > 0 else len(devs)
        if ndp > len(devs):
            print(f"[racon_tpu::] error: --dp {ndp} exceeds the "
                  f"{len(devs)} visible devices!", file=sys.stderr)
            return 1
        if args.distributed and ndp != len(devs):
            # A mesh over devs[:ndp] would exclude some hosts' local
            # devices, which the runtime rejects (or deadlocks on);
            # multi-host meshes must span the global device set.
            print(f"[racon_tpu::] error: --distributed requires --dp to "
                  f"match the global device count ({len(devs)}); shard "
                  "hosts with the wrapper instead (docs/DISTRIBUTED.md)",
                  file=sys.stderr)
            return 1
        from jax.sharding import Mesh
        mesh = Mesh(_np.asarray(devs[:ndp]), ("dp",))

    out = sys.stdout.buffer
    try:
        with tracer.span("run", "racon_tpu"):
            polisher = create_polisher(
                args.paths[0], args.paths[1], args.paths[2],
                PolisherType.kF if args.fragment_correction
                else PolisherType.kC,
                args.window_length, args.quality_threshold,
                args.error_threshold, args.match, args.mismatch, args.gap,
                backend=args.backend, logger=logger, threads=args.threads,
                mesh=mesh)
            polisher.initialize()
            if pipeline_enabled():
                # Streaming path: each contig is written the moment its
                # last window retires, while later windows still flow
                # through the pipeline — emission overlaps compute.
                for seq in polisher.polish_stream(
                        not args.include_unpolished):
                    out.write(b">" + seq.name.encode() + b"\n" +
                              seq.data + b"\n")
            else:
                for seq in polisher.polish(not args.include_unpolished):
                    out.write(b">" + seq.name.encode() + b"\n" +
                              seq.data + b"\n")
    except (PolisherError, ParseError, ValueError) as exc:
        print(str(exc), file=sys.stderr)
        return 1
    out.flush()
    logger.total("[racon_tpu::Polisher::] total =")
    from racon_tpu.obs.metrics import pipeline_extras
    from racon_tpu.obs.metrics import registry as obs_registry
    from racon_tpu.utils.jaxcache import cache_extras
    reg = obs_registry()
    for k, v in cache_extras(reg).items():
        reg.set(k, v)
    for k, v in pipeline_extras(reg).items():
        reg.set(k, v)
    tracer.finish(metrics=reg.snapshot())
    return 0


if __name__ == "__main__":
    sys.exit(main())
