"""Batched Needleman-Wunsch alignment on device.

This is the TPU replacement for both of the reference's alignment engines:

- edlib's global (NW) alignment with path, used to derive CIGARs for
  PAF/MHAP overlaps (reference: src/overlap.cpp:198-213), and
- spoa's sequence-vs-graph kNW aligner inside window consensus
  (reference: src/window.cpp:89-96) — our POA engine anchors every layer
  to the window backbone, so layer alignment is plain sequence-vs-sequence
  NW and batches perfectly over (window, layer) pairs.

TPU-first design notes:
- The DP is a ``lax.scan`` over query rows. The horizontal (gap-in-target)
  dependency within a row is a max-plus prefix scan which, for a *linear*
  gap penalty, reduces to ``lax.cummax`` over ``H[j] - j*gap`` — fully
  vectorized on the VPU instead of a serial inner loop.
- Direction bits (2 effective bits, stored uint8) live in HBM, never on the
  host; traceback runs on device as a vmapped ``lax.while_loop`` and only
  the compact op strings (<= Lq+Lt bytes each) leave the chip.
- Scores are int32; all shapes are static (padded buckets), so one compile
  per bucket shape serves the whole run.

Op encoding (shared with the native C++ aligner, racon_tpu/native/nw.cpp):
  0 = DIAG  (consumes query+target -> CIGAR 'M')
  1 = UP    (consumes query only   -> CIGAR 'I')
  2 = LEFT  (consumes target only  -> CIGAR 'D')
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from racon_tpu.ops.cigar import (DIAG, UP, LEFT,  # noqa: F401 (re-export)
                                 nw_oracle, ops_to_cigar)


def _nw_dirs(q: jnp.ndarray, t: jnp.ndarray, match: int, mismatch: int,
             gap: int) -> jnp.ndarray:
    """Direction matrix uint8[Lq, Lt] for one (padded) alignment.

    H[i, j] = max(H[i-1, j-1] + s, H[i-1, j] + g, H[i, j-1] + g) with
    H[0, j] = j*g, H[i, 0] = i*g. Tie preference DIAG > UP > LEFT.
    """
    Lq, Lt = q.shape[0], t.shape[0]
    jr = jnp.arange(Lt + 1, dtype=jnp.int32)
    row0 = jr * gap

    def step(prev, inp):
        i, qi = inp
        sub = jnp.where(t == qi, match, mismatch).astype(jnp.int32)
        diag = prev[:-1] + sub
        up = prev[1:] + gap
        tmp = jnp.maximum(diag, up)
        # Left-chain closure: H[j] = max_{k<=j}(tmp'[k] + (j-k)*g) with the
        # j=0 boundary folded in as tmp'[0] = i*g.
        f = jnp.concatenate([(i * gap)[None], tmp]) - jr * gap
        h = jax.lax.cummax(f) + jr * gap
        hj = h[1:]
        d = jnp.where(hj == diag, DIAG,
                      jnp.where(hj == up, UP, LEFT)).astype(jnp.uint8)
        return h, d

    ii = jnp.arange(1, Lq + 1, dtype=jnp.int32)
    _, dirs = jax.lax.scan(step, row0, (ii, q.astype(jnp.int32)))
    return dirs


PAD_OP = 3  # emitted after the walk reaches (0, 0)


def _traceback_flat(d1: jnp.ndarray, row_stride: int, b_off: jnp.ndarray,
                    L: int, lq: jnp.ndarray, lt: jnp.ndarray):
    """Walk all direction matrices from (lq, lt) back to (0, 0) at once.

    One fixed-length ``lax.scan`` over the whole batch *emits* one op per
    lane per step (end->start order, PAD_OP once finished): no scatters
    (they serialize terribly on TPU), and the per-step gather is a single
    flat 1-D take. ``d1`` is the flattened direction tensor; a cell
    (b, i, j) lives at ``(i-1)*row_stride + b_off[b] + (j-1)`` — this
    covers both the [B, Lq, Lt] (XLA) and [Lq, B, Lt] (Pallas) layouts.

    Returns rev_ops uint8[B, L]: paths reversed, front-aligned, padded
    with PAD_OP.
    """

    def step(state, _):
        i, j = state
        done = (i == 0) & (j == 0)
        idx = (jnp.maximum(i - 1, 0) * row_stride + b_off
               + jnp.maximum(j - 1, 0))
        dv = jnp.take(d1, idx)
        d = jnp.where(done, PAD_OP,
                      jnp.where(i == 0, LEFT,
                                jnp.where(j == 0, UP, dv))).astype(jnp.uint8)
        i = i - jnp.where((d == DIAG) | (d == UP), 1, 0).astype(i.dtype)
        j = j - jnp.where((d == DIAG) | (d == LEFT), 1, 0).astype(j.dtype)
        return (i, j), d

    (_, _), rev_ops = jax.lax.scan(
        step, (lq.astype(jnp.int32), lt.astype(jnp.int32)), None, length=L)
    return rev_ops.T


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap"))
def nw_align_batch(q: jnp.ndarray, t: jnp.ndarray, lq: jnp.ndarray,
                   lt: jnp.ndarray, *, match: int, mismatch: int, gap: int):
    """Batched global alignment with traceback.

    Args:
      q: uint8[B, Lq] query base codes, zero-padded.
      t: uint8[B, Lt] target base codes, zero-padded.
      lq, lt: int32[B] true lengths.
    Returns:
      ops uint8[B, Lq+Lt] (right-aligned per row), n_ops int32[B].
    """
    B, Lq = q.shape
    Lt = t.shape[1]
    dirs = jax.vmap(
        lambda a, b: _nw_dirs(a, b, match, mismatch, gap))(q, t)
    rev = _traceback_flat(dirs.reshape(-1), Lt,
                          jnp.arange(B, dtype=jnp.int32) * (Lq * Lt),
                          Lq + Lt, lq, lt)
    n = jnp.sum(rev != PAD_OP, axis=1).astype(jnp.int32)
    # Flip to start->end order: right-aligned with PAD_OP in front, so
    # ops[b, L - n[b]:] is the path (same contract as before).
    return jnp.flip(rev, axis=1), n


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap"))
def _nw_align_batch_pallas(q, t, lq, lt, *, match, mismatch, gap):
    """Pallas-forward variant of nw_align_batch (TPU; same contract)."""
    from racon_tpu.ops.pallas.nw_kernel import nw_dirs_pallas
    B, Lq = q.shape
    Lt = t.shape[1]
    dirs = nw_dirs_pallas(q, t, match=match, mismatch=mismatch, gap=gap)
    rev = _traceback_flat(dirs.reshape(-1), B * Lt,
                          jnp.arange(B, dtype=jnp.int32) * Lt,
                          Lq + Lt, lq, lt)
    n = jnp.sum(rev != PAD_OP, axis=1).astype(jnp.int32)
    return jnp.flip(rev, axis=1), n


def pallas_shapes_ok(B: int, Lq: int, Lt: int, match: int,
                     mismatch: int) -> bool:
    from racon_tpu.ops.pallas.nw_kernel import TB, CH
    if not (B % TB == 0 and Lq % CH == 0 and Lt % 128 == 0):
        return False
    # The substitution matrix rides VMEM as int8 (scores must fit) and
    # the pipelined in+out blocks plus the row scratch must stay under
    # the ~16 MiB core VMEM: 2*(CH*TB*Lt * 2 bytes) + TB*Lt*4.
    if not (-128 <= match <= 127 and -128 <= mismatch <= 127):
        return False
    vmem = 4 * CH * TB * Lt + 4 * TB * Lt
    return vmem <= 12 * 1024 * 1024


def nw_align_auto(q, t, lq, lt, *, match, mismatch, gap):
    """Batched alignment choosing the Pallas kernel on TPU when shapes
    allow, the pure-XLA path otherwise. Results are bit-identical."""
    import jax as _jax
    B, Lq = q.shape
    Lt = t.shape[1]
    use_pallas = (_jax.default_backend() in ("tpu", "axon")
                  and pallas_shapes_ok(B, Lq, Lt, match, mismatch))
    fn = _nw_align_batch_pallas if use_pallas else nw_align_batch
    return fn(jnp.asarray(q), jnp.asarray(t), jnp.asarray(lq),
              jnp.asarray(lt), match=match, mismatch=mismatch, gap=gap)


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap"))
def nw_scores(q: jnp.ndarray, t: jnp.ndarray, lq: jnp.ndarray,
              lt: jnp.ndarray, *, match: int, mismatch: int, gap: int):
    """Batched NW final scores only (no traceback storage) — int32[B].

    Used by benchmarks and as the compile-checked forward step: the DP scan
    without direction materialization is the pure-compute core.
    """

    def one(qq, tt, a, b):
        Lt = tt.shape[0]
        jr = jnp.arange(Lt + 1, dtype=jnp.int32)
        row0 = jr * gap

        def step(prev, inp):
            i, qi = inp
            sub = jnp.where(tt == qi, match, mismatch).astype(jnp.int32)
            tmp = jnp.maximum(prev[:-1] + sub, prev[1:] + gap)
            f = jnp.concatenate([(i * gap)[None], tmp]) - jr * gap
            h = jax.lax.cummax(f) + jr * gap
            # Past the true query length, rows must stop evolving so the
            # score can be read from the final carry at column b.
            h = jnp.where(i <= a, h, prev)
            return h, None

        ii = jnp.arange(1, qq.shape[0] + 1, dtype=jnp.int32)
        last, _ = jax.lax.scan(step, row0, (ii, qq.astype(jnp.int32)))
        return last[b]

    return jax.vmap(one)(q, t, lq.astype(jnp.int32), lt.astype(jnp.int32))


# ops_to_cigar / nw_oracle live in racon_tpu.ops.cigar (numpy-only) and are
# re-exported above for callers that already use the device kernel.
