"""Device-resident POA consensus engine — the TPU hot path.

Round-2's engine shipped alignment ops to the host and merged in numpy
every refinement round; on a tunneled TPU (30 MB/s, ~75 ms per
synchronized dispatch — see PROFILE.md) that cost ~10x the compute. This
engine keeps the whole refinement loop on device:

  h2d once:  encoded layer codes/weights, backbone anchors, spans
  per round (no host sync, chained dispatch):
    - job geometry from spans (full-span 1% rule, src/window.cpp:82)
    - shifted target buffer by gather from the current anchors
    - banded NW forward (Pallas kernel on TPU, XLA fallback elsewhere)
    - batched banded traceback (one scan for all lanes)
    - vote extraction + window aggregation + assembly + compaction
      (racon_tpu/ops/device_merge.py) -> next round's anchors + spans,
      all device-side
  d2h once:  compact consensus codes + coverage + lengths + edge stats

Semantics match PoaEngine's numpy path bit-for-bit on integer weights
(differentially tested) on a single device. Banded-alignment exactness is
certified per lane every round by an escape-bound score check (see
racon_tpu/ops/pallas/band_kernel.py): a lane whose banded score cannot
provably beat every band-leaving path flags its window for re-polish on
the unbounded host path. The dp-sharded path (device_round_sharded) is
near-bit-identical to single-device: its one psum may reassociate f32
vote sums, so sub-epsilon ties can break differently (tests accept rare
single-window divergence; see tests/test_device_merge.py).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from racon_tpu.models.window import Window, window_arrays
from racon_tpu.ops.encode import ALPHABET
from racon_tpu.ops import flat as flatmod
from racon_tpu.ops.flat import PAD_OP
from racon_tpu.ops.budget import max_dir_elems
from racon_tpu.utils import envspec

# Per-lane-tensor element budget for the dirs/nxt planes (the column
# walk's flat gather index and the HBM single-buffer ceiling). Derived
# in ONE place — racon_tpu/ops/budget.py — shared with ovl_align so the
# two admission paths can never drift apart again (the former hand-set
# 1.6e9 here vs the re-derived 1.9e9 there silently rejected the 8 kb
# genome overlap geometry by 0.7%; PROFILE.md round 5).
MAX_DIR_ELEMS = max_dir_elems(1)

# Anchor slack for insertion growth across rounds. Consensus length
# tracks backbone length within ~2% on real data; 64 covers that many
# times over at w=500-class windows, and a window whose consensus DOES
# outgrow the padded width raises the sticky ovf flag and re-polishes on
# the unbounded host path — the slack is a throughput knob (walk steps,
# vote channels, and merge gathers all scale with LA), not a correctness
# bound.
LA_GROW = 64


def _round_up(n: int, mult: int) -> int:
    return ((max(n, 1) + mult - 1) // mult) * mult


def _bucket_b(n: int) -> int:
    """Batch-dim bucket: coarse grid so chunks reuse compiled executables."""
    for cap in (128, 256, 512, 1024, 2048):
        if n <= cap:
            return cap
    return _round_up(n, 1024)


# Executable-reuse cap history, per process: every differently-shaped
# device_round executable is a fresh multi-second XLA compile, so later
# runs pad up to a previously-compiled (Lq, LA) pair when one covers them
# within 2x per dim (beyond that, recompiling is cheaper than the padded
# compute). jax's executable cache keys on the same shapes, so a history
# hit is a compile-cache hit. Mutations are lock-guarded: concurrent
# PoaEngine use from multiple threads would otherwise race the sets
# (worst case a missed reuse => redundant compile, never wrong results).
import threading as _threading
_HISTORY_LOCK = _threading.Lock()
_CAP_HISTORY: set = set()
_BAND_HISTORY: set = set()


def run_caps(lq: int, la: int) -> Tuple[int, int]:
    """(lq_cap, la_cap) covering a run's max layer/backbone lengths, on a
    coarse grid."""
    # LA pads on a 128 grid; with the 64-slot growth slack, runs of the
    # same workload (e.g. bench warmup vs measured, lengths ~w..w+6%)
    # still land in one bucket, and the former 256 grid wasted up to 20%
    # of every LA-proportional cost (walk steps, channels, gathers).
    need = (_round_up(lq, 128), _round_up(la + LA_GROW, 128))
    if 128 * need[0] * need[1] > MAX_DIR_ELEMS:
        # Unusable even at the minimum batch bucket (caller falls back to
        # the host path) — don't record it, or it would shadow smaller
        # usable pairs for later runs.
        return need
    with _HISTORY_LOCK:
        best = None
        for c in _CAP_HISTORY:
            if (need[0] <= c[0] <= 2 * need[0] and
                    need[1] <= c[1] <= 2 * need[1] and
                    128 * c[0] * c[1] <= MAX_DIR_ELEMS and
                    (best is None or c[0] * c[1] < best[0] * best[1])):
                best = c
        if best is None:
            best = need
            _CAP_HISTORY.add(need)
        return best


def window_band_delta(w: Window) -> int:
    """Max |lt0 - lq| over a window's layers at round-0 geometry — THE
    band-width input, shared by ChunkPlan (per chunk) and
    PoaEngine._run_band_width (per run) so chunk sizing and chunk
    padding can never disagree. Mirrors _round_core's on-device
    full-span rule (src/window.cpp:82)."""
    L = len(w.backbone)
    if w.n_layers == 0:
        return 0
    offs = L // 100
    b = np.clip(np.asarray(w.layer_begin, np.int64), 0, L - 1)
    e = np.maximum(
        np.minimum(np.asarray(w.layer_end, np.int64), L - 1), b)
    lqs = np.array([len(d) for d in w.layer_data], np.int64)
    full = (b < offs) & (e > L - offs)
    lt0 = np.where(full, L, e - b + 1)
    return int(np.abs(lt0 - lqs).max())


def band_width_for(max_delta: int) -> int:
    """Band slots covering a max length-difference with >=64 slack per
    side, on the 128 grid. 64 keeps the per-lane escape bound easily
    satisfiable on real polishing data (wl >= 64 certifies every lambda
    window) while cutting band cells ~25-33% vs the former 128; lanes
    whose optimum needs a wider corridor fail the bound and re-polish on
    the unbounded host path — exactness never rests on the slack."""
    return _round_up(max_delta + 2 * 64 + 1, 128)


def dir_elems(n_jobs: int, max_lq: int, max_bb: int) -> int:
    """Dirs-tensor element count for a chunk, with ChunkPlan's padding."""
    return (_bucket_b(n_jobs) * _round_up(max_lq, 128) *
            _round_up(max_bb + LA_GROW, 128))


class ChunkPlan:
    """Host-side padded arrays for one device chunk (static shapes).

    All dims pad onto coarse grids — B via ``_bucket_b``, Lq/LA via the
    run-level caps from ``run_caps``, n_win onto multiples of 32 (dummy
    windows with a 1-base zero anchor) — so every chunk of a run, and
    repeated runs in one process, share a single compiled executable.
    """

    def __init__(self, windows: List[Window], la_grow: int = LA_GROW,
                 lq_cap: Optional[int] = None, la_cap: Optional[int] = None,
                 n_shards: int = 1, band_cap: Optional[int] = None):
        self.windows = windows
        jobs_q: List[np.ndarray] = []
        jobs_w: List[np.ndarray] = []
        begin: List[int] = []
        end: List[int] = []
        win: List[int] = []
        anchors: List[np.ndarray] = []
        anchor_w: List[np.ndarray] = []
        for wi, w in enumerate(windows):
            lays, bb, bw = window_arrays(w)
            for codes, wts, b, e in lays:
                jobs_q.append(codes)
                jobs_w.append(wts)
                begin.append(b)
                end.append(e)
                win.append(wi)
            anchors.append(bb)
            anchor_w.append(bw)

        self.n_real_win = len(windows)
        self.n_win = _round_up(len(windows), 32)
        self.n_jobs = len(jobs_q)
        # Each mesh shard needs a 128-lane-aligned slice of the job axis.
        B = _round_up(_bucket_b(self.n_jobs), 128 * n_shards)
        max_lq = max(len(q) for q in jobs_q)
        LA0 = max(len(a) for a in anchors)
        Lq = lq_cap if lq_cap is not None else _round_up(max_lq, 128)
        LA = la_cap if la_cap is not None else _round_up(LA0 + la_grow, 128)
        if max_lq > Lq or LA0 + la_grow > LA:
            raise ValueError("[racon_tpu::ChunkPlan] caps below chunk max")
        self.B, self.Lq, self.LA = B, Lq, LA
        self.steps = Lq + LA

        self.q = np.zeros((B, Lq), np.uint8)
        # Weights ship as uint8 (value+1, 0 = padding) and decode on device
        # — a 4x smaller h2d than f32 weights on a ~30 MB/s tunnel.
        self.qw8 = np.zeros((B, Lq), np.uint8)
        self.lq = np.ones(B, np.int32)
        self.w_read = np.zeros(B, np.float32)
        # Padded lanes point at a dummy extra window (n_win) so their votes
        # aggregate into a discarded row.
        self.win = np.full(B, self.n_win, np.int32)
        self.begin = np.zeros(B, np.int32)
        self.end = np.ones(B, np.int32)
        if self.n_jobs:
            # Bulk fill (one masked scatter per plane, segment means
            # via prefix-sum differences) — the former per-job
            # assignment loop was a genome-scale cost (VERDICT r4
            # weak #6).
            nj = self.n_jobs
            lens = np.fromiter((len(q) for q in jobs_q), np.int64, nj)
            flat_q = np.concatenate(jobs_q)
            flat_w = np.concatenate(jobs_w).astype(np.float64)
            mask = np.arange(Lq)[None, :] < lens[:, None]
            self.q[:nj][mask] = flat_q
            # Weights are non-negative for all parser-fed inputs (the FASTQ
            # parser rejects quality bytes below '!'), so host and device
            # paths agree by construction on CLI data. The clip stays as
            # defense-in-depth for direct-API Windows built with malformed
            # quality, where uint8 wrap would otherwise vote at max weight.
            # Cap 126: the vote extraction packs weights as 7-bit fields
            # (device_merge.extract_votes_cols), and any real Phred weight
            # is <= '~' - '!' = 93.
            self.qw8[:nj][mask] = \
                np.clip(flat_w, 0, 126).astype(np.uint8) + 1
            self.lq[:nj] = lens
            # Segment means via prefix sums (safe for empty segments,
            # unlike reduceat whose clipped offsets corrupt a trailing
            # empty job's neighbor). Bit-equality with the host engine's
            # per-job _Job.w_read (f64 .mean()) holds because weights
            # are integer-valued by the parser contract (Phred ints or
            # 1.0), making every f64 summation order exact; fractional
            # direct-API weights could differ in the last ulp.
            offs = np.concatenate([[0], np.cumsum(lens)[:-1]])
            cs = np.concatenate([[0.0], np.cumsum(flat_w)])
            sums = cs[offs + lens] - cs[offs]
            self.w_read[:nj] = np.where(
                lens > 0, sums / np.maximum(lens, 1), 0.0)
            self.win[:nj] = win
            self.begin[:nj] = begin
            self.end[:nj] = end

        Nw = self.n_win + 1   # + dummy row for padded lanes
        self.bb = np.zeros((Nw, LA), np.uint8)
        self.bbw = np.zeros((Nw, LA), np.float32)
        self.alen = np.ones(Nw, np.int32)
        for wi in range(self.n_real_win):
            L = len(anchors[wi])
            self.bb[wi, :L] = anchors[wi]
            self.bbw[wi, :L] = anchor_w[wi]
            self.alen[wi] = L

        # Static band width for the banded forward: covers every job's
        # round-0 |lt - lq| with >=128 slack each side (later rounds can
        # shift geometry — the in-round escape bound re-certifies every
        # lane every round). 0 disables banding when a band would not
        # beat the full-width kernel.
        W = band_width_for(max((window_band_delta(w) for w in windows),
                               default=0))
        if band_cap is not None and W > band_cap:
            # The caller sized chunks assuming banded dirs of at most
            # band_cap columns from the same shared geometry; a wider
            # chunk here would overflow the int32 dirs budget silently.
            raise ValueError(
                "[racon_tpu::ChunkPlan] band width exceeds the caller's "
                f"sizing cap ({W} > {band_cap})")
        if W + 128 > LA:
            # Band would not beat full width here; don't record W either,
            # or an unusable entry could shadow smaller fitting widths
            # for later chunks (same pitfall run_caps guards against).
            self.band_w = 0
        else:
            # Reuse a previously-compiled band width when one covers
            # this chunk within 2x, fits this LA, and stays under the
            # caller's ceiling (chunk sizing may have assumed banded
            # dirs of at most band_cap columns). band_w is a static arg;
            # workload noise across runs must not force fresh
            # multi-second compiles.
            ceil = min(LA - 128, band_cap) if band_cap else LA - 128
            with _HISTORY_LOCK:
                best = None
                for c in _BAND_HISTORY:
                    if (W <= c <= 2 * W and c <= ceil and
                            (best is None or c < best)):
                        best = c
                if best is None:
                    _BAND_HISTORY.add(W)
                    best = W
            self.band_w = best

    def packed_bufs(self):
        """(job_buf u8[B, 2*Lq+20], win_buf u8[Nw+1, 5*LA+4]) — every
        chunk input concatenated into two byte buffers so each chunk is
        TWO h2d transfers instead of ten. The tunnel's per-transfer
        latency dominated h2d at bench scale (~2.1 s for ~12 MB split
        over 10 arrays x 2 chunks; PROFILE.md round 5). Layout must match
        device_chunk_packed's unpack slicing exactly; the job buffer is
        dp-shardable along axis 0, the window buffer replicates."""
        B, Lq, LA = self.B, self.Lq, self.LA
        job = np.empty((B, 2 * Lq + 20), np.uint8)
        job[:, :Lq] = self.q
        job[:, Lq:2 * Lq] = self.qw8
        sc = job[:, 2 * Lq:]
        sc[:, 0:4] = self.begin.astype(np.int32).view(np.uint8).reshape(B, 4)
        sc[:, 4:8] = self.end.astype(np.int32).view(np.uint8).reshape(B, 4)
        sc[:, 8:12] = self.lq.astype(np.int32).view(np.uint8).reshape(B, 4)
        sc[:, 12:16] = self.win.astype(np.int32).view(np.uint8).reshape(B, 4)
        sc[:, 16:20] = self.w_read.astype(np.float32).view(np.uint8) \
            .reshape(B, 4)
        Nw1 = self.n_win + 1
        winb = np.empty((Nw1, 5 * LA + 4), np.uint8)
        winb[:, :LA] = self.bb
        winb[:, LA:5 * LA] = self.bbw.astype(np.float32).view(np.uint8) \
            .reshape(Nw1, 4 * LA)
        winb[:, 5 * LA:] = self.alen.astype(np.int32).view(np.uint8) \
            .reshape(Nw1, 4)
        return job, winb


def _use_pallas(B: int, Lq: int, LA: int) -> bool:
    import os
    import jax
    from racon_tpu.ops.pallas.flat_kernel import TB, CH
    if envspec.read("RACON_TPU_NO_PALLAS") not in ("", "0", "false"):
        return False                               # debug/safety valve
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return B % TB == 0 and Lq % CH == 0 and LA % 128 == 0


def _packed_byte_slice(tab, start, L: int):
    """Batched contiguous byte slice via i32-packed dynamic_slice.

    Equivalent to ``vmap(lambda s: dynamic_slice(tab, (s,), (L,)))`` for
    a uint8 table with ``start >= 0`` and ``start + L <= tab.size``, but
    each per-lane DMA moves L/4 + 1 int32 words instead of L bytes. The
    band/tbuf build is bound by per-lane DMA *descriptor* latency, which
    scales with element count, not bytes (PROFILE.md round 5's tband
    cost) — packing 4 cells per word cuts it ~4x. The start&3 phase is
    recovered from four STATIC byte slices with three selects; a
    per-element phase gather here would reintroduce exactly the cost the
    slice-mode build removed (scripts/ablate_gather_pack.py).
    """
    import jax
    import jax.numpy as jnp

    # Worst case is phase 3: bytes [3, 3 + L) of the fetched window, so
    # the window must span L + 3 bytes -> L // 4 + 2 words.
    n4 = L // 4 + 2
    # Round the table up to whole words plus two words of slack so the
    # word slice covering any start phase stays in range:
    #   (start >> 2) + n4 <= floor((start + L) / 4) + 2
    #                     <= floor(size / 4) + 2.
    pad = (-tab.shape[0]) % 4 + 8
    tabp = jnp.concatenate([tab, jnp.zeros((pad,), tab.dtype)])
    w32 = jax.lax.bitcast_convert_type(tabp.reshape(-1, 4), jnp.int32)
    ws = jax.vmap(
        lambda s: jax.lax.dynamic_slice(w32, (s,), (n4,)))(start >> 2)
    by = jax.lax.bitcast_convert_type(ws, jnp.uint8).reshape(
        ws.shape[0], n4 * 4)
    ph = start & 3
    out = by[:, 0:L]
    for r_ in (1, 2, 3):
        out = jnp.where((ph == r_)[:, None], by[:, r_:r_ + L], out)
    return out


def _lane_fwd(bb, alen, begin, end, q, lq, win, *,
              match, mismatch, gap, Lq, LA, pallas, band_w=0,
              nxt_k=2):
    """Job geometry + NW forward for every lane of one refinement round
    (traced body, one shard's view): the half of _lane_votes that ends
    at the packed direction planes, before any serialized traceback.

    Returns ``(dirs, nxt, nxt2, lt, t_off, klo, esc0)``: the forward's
    packed cell plane plus the k-step predecessor planes (``nxt`` /
    ``nxt2`` are None below their depth; see docs/KERNELS.md), the
    per-lane geometry vectors the walk re-uses verbatim (``klo`` is
    None on the flat path), and ``esc0`` — the band-escape certificate
    term, f32[B], already resolved from ``hlast`` here so the decoupled
    walk dispatch never needs the score plane (None on the flat path,
    whose only inexactness signal is walk saturation).

    The fused round (_lane_votes) and the decoupled walk dispatch
    (ops/colwalk.py walk_chunk_packed) both build on this body, so the
    split is bit-identical by construction.
    """
    import jax
    import jax.numpy as jnp

    B = q.shape[0]
    L = jnp.take(alen, win)                             # anchor len per job
    b_c = jnp.clip(begin, 0, L - 1)
    e_c = jnp.clip(end, b_c, L - 1)
    # uint32 offset = 0.01 * L, strict end > L - offset (window.cpp:82).
    # Integer floor-div matches the host's f64 `int(0.01 * L)` exactly for
    # all realistic L (f64 0.01 is slightly above 1/100, so truncation
    # equals floor division); f32 on device would disagree near multiples
    # of 100 (e.g. L=300).
    offs = L // 100
    full = (b_c < offs) & (e_c > L - offs)
    t_off = jnp.where(full, 0, b_c).astype(jnp.int32)
    lt = jnp.where(full, L, e_c - b_c + 1).astype(jnp.int32)

    flat = bb.reshape(-1)
    if band_w:
        # Diagonal band (racon_tpu/ops/pallas/band_kernel.py): per-lane
        # geometry pre-baked into a shifted target buffer; exactness per
        # lane is certified by the same escape bound as the native
        # aligner, and failing lanes route their windows to the host
        # redo path via the sticky ovf flag.
        from racon_tpu.ops.pallas.band_kernel import (
            fw_dirs_band, fw_dirs_band_xla, band_geometry)
        klo, wl = band_geometry(lq, lt, band_w)
        PW = band_w + Lq
        y = jnp.arange(PW, dtype=jnp.int32)[None, :]
        rel = klo[:, None] + y                     # slice-relative index
        okb = (rel >= 0) & (rel < lt[:, None])
        # Per-lane slices are CONTIGUOUS runs of the anchor table, so a
        # batched dynamic_slice (slice-mode gather) replaces the element
        # gather — 26 ms vs 55 ms at bench shapes (PROFILE.md) — and the
        # i32-packed variant moves 4 cells per descriptor word on top
        # (_packed_byte_slice); the padding margins make every start
        # index in-range, the okb mask reproduces the clip semantics
        # bit-for-bit (every phase-spill byte it could expose is masked).
        tab = jnp.concatenate(
            [jnp.zeros((PW,), flat.dtype), flat,
             jnp.zeros((PW,), flat.dtype)])
        start = win * LA + t_off + klo + PW
        sl = _packed_byte_slice(tab, start, PW)
        tband = jnp.where(okb, sl, 7).astype(jnp.uint8)
        fwd = fw_dirs_band if pallas else fw_dirs_band_xla
        if nxt_k >= 4:
            dirs, nxt, nxt2, hlast = fwd(tband, q.T, klo, lq,
                                         match=match, mismatch=mismatch,
                                         gap=gap, W=band_w, nxt_k=4)
        else:
            dirs, nxt, hlast = fwd(tband, q.T, klo, lq,
                                   match=match, mismatch=mismatch,
                                   gap=gap, W=band_w)
            nxt2 = None
            if nxt_k < 2:           # single-step reference walk
                nxt = None
        # Escape bound (see nw.cpp): banded score must beat any path
        # that leaves the band, else the lane's window is re-polished on
        # the unbounded host path. Any out-of-band path carries at least
        # |lt-lq| + 2(wl+1) gap ops; those consume query/target bases
        # unpaired, so its diagonal-op count is at most
        # min(lq,lt) - (wl+1) and its score at most
        #   max(m,0)*(min(lq,lt) - wl - 1) + g*(|lt-lq| + 2wl + 2).
        # (The former bound omitted the "- wl - 1" term; at narrow
        # bands that looseness re-routed most REAL windows to the host:
        # 92/96 lambda windows at W=128, round-5 measurement.)
        xend = jnp.clip(lt - lq - klo, 0, band_w - 1)
        score = jnp.take_along_axis(hlast, xend[:, None], axis=1)[:, 0]
        bound = (jnp.maximum(match, 0) * (jnp.minimum(lq, lt) - wl - 1) +
                 gap * (jnp.abs(lt - lq) + 2 * wl + 2))
        esc0 = ((score < bound) | (wl < 16)).astype(jnp.float32)
        return dirs, nxt, nxt2, lt, t_off, klo, esc0
    else:
        # Full-width absolute coordinates: tbuf[b, x] = anchor slice
        # (same batched dynamic_slice trick as the banded path).
        x = jnp.arange(LA, dtype=jnp.int32)[None, :]
        ok = x < lt[:, None]
        tab = jnp.concatenate(
            [flat, jnp.zeros((LA,), flat.dtype)])
        start = win * LA + t_off
        sl = _packed_byte_slice(tab, start, LA)
        tbuf = jnp.where(ok, sl, 7).astype(jnp.uint8)
        if pallas:
            from racon_tpu.ops.pallas.flat_kernel import fw_dirs_pallas
            dirs = fw_dirs_pallas(tbuf, q.T,
                                  match=match, mismatch=mismatch, gap=gap)
        else:
            dirs = flatmod.fw_dirs_xla(tbuf, q.T,
                                       match=match, mismatch=mismatch,
                                       gap=gap)
        return dirs, None, None, lt, t_off, None, None


def _lane_walk(dirs, nxt, nxt2, lt, t_off, klo, esc0, q, qw8, lq,
               w_read, *, LA, pallas, band_w=0):
    """Column-walk traceback + vote extraction over _lane_fwd's planes
    (traced body, one shard's view) — the serialized-gather half of a
    round, the part the decoupled walk dispatch takes off the critical
    path.

    Returns (votes dict for dm.aggregate_votes, esc_w f32[B]) exactly
    as _lane_votes always has: ``esc0`` (the forward's escape term)
    plus walk saturation.
    """
    import jax.numpy as jnp
    from racon_tpu.ops import device_merge as dm
    from racon_tpu.ops.colwalk import col_walk

    if band_w:
        cols = col_walk(dirs, lq, lt, klo, t_off, LA=LA,
                        layout="band_t" if pallas else "band", nxt=nxt,
                        nxt2=nxt2)
    else:
        cols = col_walk(dirs, lq, lt, None, t_off, LA=LA, layout="flat")
    votes = dm.extract_votes_cols(cols, q, qw8, w_read, lt, t_off, LA)
    # Saturated up-run counters make the walk inexact for that lane —
    # same redo route as the band escape bound.
    sat_w = cols["sat"].astype(jnp.float32)
    esc_w = sat_w if esc0 is None else esc0 + sat_w
    return votes, esc_w


def _lane_votes(bb, alen, begin, end, q, qw8, lq, w_read, win, *,
                match, mismatch, gap, Lq, LA, pallas, band_w=0,
                nxt_k=2):
    """Job geometry + NW forward + column-walk + vote extraction for
    every lane of one refinement round (traced body, one shard's view).

    The shared front half of a round: the fixed-round engine
    (_round_core) and the convergence scheduler's detecting round
    (racon_tpu/sched/rounds.py) both consume its output, so the two
    dispatch paths run one implementation of the alignment contract.
    Internally it is _lane_fwd (geometry + forward planes) composed
    with _lane_walk (traceback + votes) — the decoupled walk dispatch
    runs the same two bodies split across two executables, which is
    what makes it bit-identical to this fused form.

    ``nxt_k`` (static; 2 or 4) selects the banded walk's predecessor
    depth — at 4 the forward also emits the u16 ``nxt2`` hop plane and
    the column walk undoes four anchor positions per dependent gather
    (budget.walk_k_for picks it per geometry; the flat path has no nxt
    plane and ignores it).

    Returns (votes dict of per-job channels for dm.aggregate_votes,
    esc_w f32[B] — positive where the banded walk's exactness
    certificate failed and the lane's window must re-polish on the
    redo path).
    """
    dirs, nxt, nxt2, lt, t_off, klo, esc0 = _lane_fwd(
        bb, alen, begin, end, q, lq, win, match=match, mismatch=mismatch,
        gap=gap, Lq=Lq, LA=LA, pallas=pallas, band_w=band_w, nxt_k=nxt_k)
    return _lane_walk(dirs, nxt, nxt2, lt, t_off, klo, esc0, q, qw8, lq,
                      w_read, LA=LA, pallas=pallas, band_w=band_w)


def _remap_state(codes, total, map_b, map_e, bb, alen, begin, end, win,
                 LA: int):
    """Next-round anchors (dummy row re-appended) and spans remapped
    through the merge's coordinate maps — the shared back half of a
    round's state update (``bb``/``alen``/``begin``/``end`` are the
    round's INPUT state; returns the new anchor table, lengths, and
    per-lane spans)."""
    import jax
    import jax.numpy as jnp

    L = jnp.take(alen, win)                             # anchor len per job
    new_bb = jnp.concatenate([codes, bb[-1:]], axis=0)
    new_alen = jnp.concatenate(
        [jnp.clip(total, 1, LA), alen[-1:]], axis=0).astype(jnp.int32)
    mb_flat = map_b.reshape(-1)
    me_flat = map_e.reshape(-1)
    winc = jnp.minimum(win, map_b.shape[0] - 1)
    nb = jnp.where(begin < L,
                   jnp.take(mb_flat, winc * LA + jnp.clip(begin, 0, LA - 1)),
                   0).astype(jnp.int32)
    tot_j = jnp.take(jnp.clip(total, 1, LA), winc)
    ne = jnp.where(end < L,
                   jnp.take(me_flat, winc * LA + jnp.clip(end, 0, LA - 1)),
                   tot_j - 1).astype(jnp.int32)
    return new_bb, new_alen, nb, ne


def _round_core(bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf, *,
                match, mismatch, gap, ins_scale, Lq, n_win,
                LA, pallas, band_w=0, nxt_k=2, detect=False,
                axis_name=None):
    """One alignment + merge round (traced body, single shard's view).

    Returns (new_bb, new_bbw, new_alen, new_begin, new_end, cov, ovf,
    conv). ``ovf`` is a sticky per-window flag: consensus outgrew the
    padded anchor width this round (or any earlier one) and was
    truncated — the host must re-run those windows (the host path is
    unbounded). ``conv`` is the per-window fixed-point flag
    (device_merge.converged_windows) when ``detect`` is on, all-False
    otherwise — the adaptive round exit in device_chunk_packed skips
    remaining non-final rounds once every window is conv or ovf.

    Under shard_map the job (B) axis is sharded over ``axis_name`` while
    window arrays are replicated; the only collective is one psum of the
    per-window vote accumulators (jobs of one window may live on any
    shard) — windows are otherwise independent, matching the reference's
    per-window fan-out (src/polisher.cpp:457-469).
    """
    votes, esc_w = _lane_votes(
        bb, alen, begin, end, q, qw8, lq, w_read, win, match=match,
        mismatch=mismatch, gap=gap, Lq=Lq, LA=LA, pallas=pallas,
        band_w=band_w, nxt_k=nxt_k)
    return _merge_round(votes, esc_w, bb, bbw, alen, begin, end, win,
                        ovf, ins_scale=ins_scale, n_win=n_win, LA=LA,
                        detect=detect, axis_name=axis_name)


def _merge_round(votes, esc_w, bb, bbw, alen, begin, end, win, ovf, *,
                 ins_scale, n_win, LA, detect=False, axis_name=None):
    """Vote aggregation through state remap — the back half of a round
    (traced body). Shared verbatim by the fused round (_round_core
    above) and the decoupled walk dispatch (ops/colwalk.py
    walk_chunk_packed), so the two paths assemble consensus through one
    implementation; see _round_core for the output contract."""
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops import device_merge as dm

    # The band-escape per-window sum rides aggregate_votes' membership
    # matrix and the same single psum as the votes.
    acc = dm.aggregate_votes(votes, win, n_win + 1, extras={"_esc": esc_w})
    if axis_name is not None:
        acc = {k: jax.lax.psum(v, axis_name) for k, v in acc.items()}
    wesc = acc.pop("_esc", None)
    acc = {k: v[:-1] for k, v in acc.items()}       # drop padded-lane row
    acc = dm.add_backbone(acc, bb[:-1], bbw[:-1], alen[:-1])
    asm = dm.assemble(acc, alen[:-1], ins_scale)
    codes, cov, total = dm.compact(asm, LA)
    map_b, map_e = dm.coord_maps(asm, alen[:-1], LA)

    new_bb, new_alen, nb, ne = _remap_state(
        codes, total, map_b, map_e, bb, alen, begin, end, win, LA)
    new_bbw = jnp.zeros_like(bbw)
    ovf = ovf | (total > LA)
    if wesc is not None:
        ovf = ovf | (wesc[:-1] > 0)
    if detect:
        # Same fixed-point predicate as the convergence scheduler
        # (sched/rounds.py): span-change flags ride one extra membership
        # matmul (and one extra psum under dp — nb/ne only exist after
        # the coordinate maps, so they cannot ride the votes' psum).
        chg = ((nb != begin) | (ne != end)).astype(jnp.float32)
        wchg = dm.aggregate_flags(chg, win, n_win + 1)
        if axis_name is not None:
            wchg = jax.lax.psum(wchg, axis_name)
        conv = dm.converged_windows(codes, total, bb[:-1], alen[:-1],
                                    wchg[:-1])
    else:
        conv = jnp.zeros(n_win, dtype=bool)
    return new_bb, new_bbw, new_alen, nb, ne, cov, ovf, conv


device_round = functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "ins_scale", "Lq",
                     "n_win", "LA", "pallas", "band_w", "nxt_k",
                     "detect"))(_round_core)


def round_band_width(band_w: int, r: int) -> int:
    """Band width for refinement round ``r`` of a chunk.

    Round 0 aligns against the raw backbone and needs the full chunk
    band; later rounds align against a near-converged consensus whose
    spans were remapped through the previous merge, so the optimum hugs
    the diagonal and a narrower band suffices — exactness is still
    certified per lane per round by the escape bound, with failures
    taking the host redo route. 192 (not 128): at wl ~= 95 the
    tightened bound sits ~1000 below real noisy-read scores, where
    W=128's wl ~= 63 made it marginal and re-routed 58/96 lambda
    windows (round-5 measurement; Mosaic only needs W % 8, not % 128).

    Shared by every dispatch path (device_chunk_packed, the
    RACON_TPU_TIMING=1 per-round path, and the convergence scheduler)
    so profiling and scheduling always execute the production program.
    """
    return band_w if (r == 0 or not band_w) else min(band_w, 192)


def _make_round_fn(*, match, mismatch, gap, ins_scale, Lq, n_win, LA,
                   pallas, band_w, mesh, nxt_k=2, detect=False):
    """One round callable: plain _round_core, or its dp-sharded shard_map
    when a mesh is given (the single place the sharding contract lives).

    Job-axis arrays shard over "dp", window arrays replicate, and the
    only collective is _round_core's one psum of the per-window vote
    accumulators. check_vma=False: the Pallas kernels' out_shapes carry
    no varying-mesh-axes annotation, which the checker (TPU path only)
    rejects; the in/out specs below state the contract explicitly.
    """
    core = functools.partial(
        _round_core, match=match, mismatch=mismatch, gap=gap,
        ins_scale=ins_scale, Lq=Lq, n_win=n_win, LA=LA, pallas=pallas,
        band_w=band_w, nxt_k=nxt_k, detect=detect,
        axis_name=None if mesh is None else "dp")
    if mesh is None:
        return core
    from jax.sharding import PartitionSpec as P
    from racon_tpu.utils.jaxcompat import shard_map
    rep = P()
    job = P("dp")
    return shard_map(
        core, mesh=mesh,
        in_specs=(rep, rep, rep, job, job, job, job, job, job, job, rep),
        out_specs=(rep, rep, rep, job, job, rep, rep, rep),
        check_vma=False)


def _unpack_job(job_buf, Lq: int):
    """Slice ChunkPlan.packed_bufs()' job byte layout back into per-lane
    arrays (traced body): ``(q, qw8, begin, end, lq, win, w_read)``.
    Split out of _unpack_bufs so the decoupled walk dispatch (which
    carries its round state as live device arrays, not the win buffer)
    can recover the round-invariant job fields from the same layout
    contract."""
    import jax
    import jax.numpy as jnp

    def i32(col):
        return jax.lax.bitcast_convert_type(col, jnp.int32)

    q = job_buf[:, :Lq]
    qw8 = job_buf[:, Lq:2 * Lq]
    sc = job_buf[:, 2 * Lq:]
    B = job_buf.shape[0]
    begin = i32(sc[:, 0:4].reshape(B, 1, 4))[:, 0]
    end = i32(sc[:, 4:8].reshape(B, 1, 4))[:, 0]
    lq = i32(sc[:, 8:12].reshape(B, 1, 4))[:, 0]
    win = i32(sc[:, 12:16].reshape(B, 1, 4))[:, 0]
    w_read = jax.lax.bitcast_convert_type(
        sc[:, 16:20].reshape(B, 1, 4), jnp.float32)[:, 0]
    return q, qw8, begin, end, lq, win, w_read


def _unpack_bufs(job_buf, win_buf, Lq: int, LA: int):
    """Slice ChunkPlan.packed_bufs()' concatenated byte layouts back into
    round-state arrays (traced body). The layout contract lives here and
    in packed_bufs, nowhere else.

    Returns (q, qw8, begin, end, lq, win, w_read, bb, bbw, alen).
    """
    import jax
    import jax.numpy as jnp

    def i32(col):
        return jax.lax.bitcast_convert_type(col, jnp.int32)

    q, qw8, begin, end, lq, win, w_read = _unpack_job(job_buf, Lq)
    Nw1 = win_buf.shape[0]
    bb = win_buf[:, :LA]
    bbw = jax.lax.bitcast_convert_type(
        win_buf[:, LA:5 * LA].reshape(Nw1, LA, 4), jnp.float32)
    alen = i32(win_buf[:, 5 * LA:].reshape(Nw1, 1, 4))[:, 0]
    return q, qw8, begin, end, lq, win, w_read, bb, bbw, alen


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "ins_scale", "Lq",
                     "n_win", "LA", "pallas", "band_w", "rounds",
                     "adaptive", "mesh", "nxt_k"))
def device_chunk_packed(job_buf, win_buf, *, match, mismatch, gap,
                        ins_scale, Lq, n_win, LA, pallas, band_w, rounds,
                        adaptive=False, mesh=None, nxt_k=2):
    """One chunk end to end in ONE jit dispatch from TWO byte buffers.

    Inputs arrive as ChunkPlan.packed_bufs()' concatenated layouts (two
    h2d transfers instead of ten — per-transfer tunnel latency dominated
    h2d at bench scale) and every refinement round plus the output
    packing runs inside a single executable (each synchronized dispatch
    costs ~13 ms; PROFILE.md round 5). With ``mesh``, each round is the
    dp-sharded shard_map of device_round_sharded sequenced inside the
    same program (one psum per round, as before); the job buffer is
    sharded along jobs, the window buffer replicated.

    ``ins_scale`` may be a float or a per-round tuple of length
    ``rounds`` (PoaEngine passes a schedule — see its ins_scale_final).

    ``adaptive`` (static; dispatch_chunk gates it on RACON_TPU_ADAPTIVE
    and the schedule shape) rewrites the unrolled round chain as
    round 0, a while_loop over the replayable middle rounds (shared
    band width and scale — one trace), and the final round. The loop
    exits as soon as EVERY window is converged or overflowed: skipped
    middle rounds are exact replays for converged windows (the
    convergence scheduler's proof, sched/rounds.py) and discarded work
    for overflowed ones (host redo), so the packed output is
    bit-identical to the full chain while a converged chunk pays
    3 rounds instead of ``rounds``. Requires rounds >= 3 and uniform
    non-final scales; the caller checks both.
    """
    import jax.numpy as jnp

    (q, qw8, begin, end, lq, win, w_read, bb, bbw, alen) = \
        _unpack_bufs(job_buf, win_buf, Lq, LA)
    state, cov, rexec0 = _rounds_before_final(
        bb, bbw, alen, begin, end, q, qw8, lq, w_read, win,
        match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
        Lq=Lq, n_win=n_win, LA=LA, pallas=pallas, band_w=band_w,
        rounds=rounds, adaptive=adaptive, mesh=mesh, nxt_k=nxt_k)
    bb, bbw, alen, begin, end, ovf = state
    scales = ins_scale if isinstance(ins_scale, tuple) \
        else (ins_scale,) * rounds
    # Final round always runs (final-scale assembly).
    final = _make_round_fn(
        match=match, mismatch=mismatch, gap=gap, ins_scale=scales[-1],
        Lq=Lq, n_win=n_win, LA=LA, pallas=pallas,
        band_w=round_band_width(band_w, rounds - 1), mesh=mesh,
        nxt_k=nxt_k, detect=False)
    bb, bbw, alen, begin, end, cov, ovf, conv = final(
        bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf)
    return _pack_body(bb[:-1], cov, alen[:-1], ovf, rexec0 + 1,
                      jnp.int32(rounds))


def _rounds_before_final(bb, bbw, alen, begin, end, q, qw8, lq, w_read,
                         win, *, match, mismatch, gap, ins_scale, Lq,
                         n_win, LA, pallas, band_w, rounds, adaptive,
                         mesh, nxt_k):
    """Refinement rounds 0 .. rounds-2 of a chunk (traced body): the
    shared prefix of the fused program (device_chunk_packed) and the
    forward-only program (device_chunk_fwd), factored out so the
    decoupled walk path replays the exact round chain the fused path
    compiles — same calls, same order, same jaxpr prefix.

    Returns ``((bb, bbw, alen, begin, end, ovf), cov, rexec0)`` where
    ``rexec0`` (traced int32) counts the rounds executed so far — the
    caller's final round adds one.
    """
    import jax
    import jax.numpy as jnp

    ovf = jnp.zeros(n_win, dtype=bool)
    conv = jnp.zeros(n_win, dtype=bool)
    cov = None

    scales = ins_scale if isinstance(ins_scale, tuple) \
        else (ins_scale,) * rounds

    def make_round(bw, sc, det):
        return _make_round_fn(
            match=match, mismatch=mismatch, gap=gap, ins_scale=sc,
            Lq=Lq, n_win=n_win, LA=LA, pallas=pallas, band_w=bw,
            mesh=mesh, nxt_k=nxt_k, detect=det)

    if not adaptive:
        for r in range(rounds - 1):
            bw = round_band_width(band_w, r)
            bb, bbw, alen, begin, end, cov, ovf, conv = \
                make_round(bw, scales[r], False)(
                    bb, bbw, alen, begin, end, q, qw8, lq, w_read, win,
                    ovf)
        rexec0 = jnp.int32(rounds - 1)
    else:
        # Round 0 (full band): detection cannot fire — its input anchor
        # carries backbone quality weights and is not a replayable state
        # (device_merge.converged_windows).
        bb, bbw, alen, begin, end, cov, ovf, conv = \
            make_round(round_band_width(band_w, 0), scales[0], False)(
                bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf)
        # Middle rounds 1..rounds-2: one executable (round_band_width is
        # constant for r >= 1 and the non-final scales are uniform).
        # Padded dummy windows (zero anchors, no lanes) reproduce their
        # state from round 1 on, so the all-windows predicate terminates.
        mid = make_round(round_band_width(band_w, 1), scales[1], True)

        def cond(c):
            k = c[0]
            return (k < rounds - 1) & ~jnp.all(c[7] | c[8])

        def body(c):
            k, bb, bbw, alen, begin, end, cov, ovf, conv = c
            bb, bbw, alen, begin, end, cov, ovf, conv = mid(
                bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf)
            return (k + 1, bb, bbw, alen, begin, end, cov, ovf, conv)

        (k, bb, bbw, alen, begin, end, cov, ovf, conv) = \
            jax.lax.while_loop(cond, body, (jnp.int32(1), bb, bbw, alen,
                                            begin, end, cov, ovf, conv))
        rexec0 = k
    return (bb, bbw, alen, begin, end, ovf), cov, rexec0


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "ins_scale", "Lq",
                     "n_win", "LA", "pallas", "band_w", "rounds",
                     "adaptive", "nxt_k"))
def device_chunk_fwd(job_buf, win_buf, *, match, mismatch, gap,
                     ins_scale, Lq, n_win, LA, pallas, band_w, rounds,
                     adaptive=False, nxt_k=2):
    """The forward/refinement half of a chunk in one jit dispatch: all
    non-final rounds fully fused (identical chain to
    device_chunk_packed, including the adaptive while_loop), then the
    FINAL round's geometry + NW forward only — its serialized traceback
    walk is NOT run here.

    Returns the packed direction planes plus everything the standalone
    walk dispatch (ops/colwalk.py walk_chunk_packed) needs to finish the
    chunk byte-identically: ``(dirs, nxt, nxt2, lt, t_off, klo, esc0,
    bb, bbw, alen, begin, end, ovf, rexec0)`` — the plane tuple from
    _lane_fwd at the final round's band width, the carried round state
    ENTERING the final round, and the executed-round count so far
    (None leaves where depth/layout elides a plane; jit treats them as
    empty pytree nodes). Every refinement round before the final one
    already consumed its own walk inside this program — only the last
    walk has no dependent anchor state, which is exactly why it alone
    can leave the critical path (pipeline/streaming.py walk stage).

    Single-device only: the decoupled path is gated off under a dp mesh
    (the walk-side vote psum would need the mesh threaded through a
    second executable for no overlap win — the per-shard walk still
    serializes on the same chips).
    """
    (q, qw8, begin, end, lq, win, w_read, bb, bbw, alen) = \
        _unpack_bufs(job_buf, win_buf, Lq, LA)
    state, _cov, rexec0 = _rounds_before_final(
        bb, bbw, alen, begin, end, q, qw8, lq, w_read, win,
        match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
        Lq=Lq, n_win=n_win, LA=LA, pallas=pallas, band_w=band_w,
        rounds=rounds, adaptive=adaptive, mesh=None, nxt_k=nxt_k)
    bb, bbw, alen, begin, end, ovf = state
    dirs, nxt, nxt2, lt, t_off, klo, esc0 = _lane_fwd(
        bb, alen, begin, end, q, lq, win, match=match, mismatch=mismatch,
        gap=gap, Lq=Lq, LA=LA, pallas=pallas,
        band_w=round_band_width(band_w, rounds - 1), nxt_k=nxt_k)
    return (dirs, nxt, nxt2, lt, t_off, klo, esc0,
            bb, bbw, alen, begin, end, ovf, rexec0)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "ins_scale", "Lq",
                     "n_win", "LA", "pallas", "band_w", "mesh", "nxt_k"))
def device_round_sharded(bb, bbw, alen, begin, end, q, qw8, lq, w_read,
                         win, ovf, *, match, mismatch, gap, ins_scale, Lq,
                         n_win, LA, pallas, band_w, mesh, nxt_k=2):
    """device_round with the job axis sharded over the mesh's "dp" axis.

    Window arrays (anchors, lengths, ovf) stay replicated; each chip
    aligns and votes its job shard, one psum merges the per-window
    accumulators, and the (replicated) assembly/compaction runs
    redundantly per chip — zero-collective except that psum, as windows
    are independent (SURVEY.md section 7 step 6)."""
    fn = _make_round_fn(
        match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
        Lq=Lq, n_win=n_win, LA=LA, pallas=pallas, band_w=band_w,
        mesh=mesh, nxt_k=nxt_k)
    return fn(bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf)


def _pack_body(codes, cov, alen, ovf, rounds_exec, rounds_sched):
    """Flatten codes/cov/lengths/overflow into one uint8 buffer for a
    single d2h transfer (each synchronized pull pays ~13 ms tunnel
    latency). The byte layout is the contract collect_chunk unpacks.
    ``rounds_exec``/``rounds_sched`` (int32 scalars, 8 trailing bytes)
    record how many refinement rounds the chunk actually executed vs.
    had scheduled — the adaptive early exit's telemetry rides the same
    pull."""
    import jax
    import jax.numpy as jnp
    c16 = jnp.clip(cov, 0, 32767).astype(jnp.int16)
    tail = alen.astype(jnp.int32)
    rr = jnp.stack([jnp.asarray(rounds_exec).astype(jnp.int32),
                    jnp.asarray(rounds_sched).astype(jnp.int32)])
    return jnp.concatenate([
        codes.reshape(-1),
        jax.lax.bitcast_convert_type(c16, jnp.uint8).reshape(-1),
        jax.lax.bitcast_convert_type(tail, jnp.uint8).reshape(-1),
        ovf.astype(jnp.uint8),
        jax.lax.bitcast_convert_type(rr, jnp.uint8).reshape(-1),
    ])


_pack_out = functools.partial(__import__("jax").jit)(_pack_body)


def put_chunk_bufs(plan: ChunkPlan, mesh=None) -> Tuple[object, object]:
    """Start the (async) h2d of a chunk's two packed byte buffers.

    ``jax.device_put`` returns immediately, so calling this for chunk
    i+1 before chunk i's results sync overlaps the transfer with
    compute — the primitive behind both the scheduler's prefetch
    (sched/scheduler.py::put_chunk) and the streaming pipeline's h2d
    stage (racon_tpu/pipeline/streaming.py). The recorded seconds cover
    only the synchronous serialization/enqueue portion.
    """
    import time
    import jax
    from racon_tpu.obs.metrics import record_h2d
    from racon_tpu.resilience.retry import call as retry_call

    job_h, win_h = plan.packed_bufs()

    def _put():
        t0 = time.perf_counter()
        if mesh is None:
            job_buf, win_buf = jax.device_put((job_h, win_h))
        else:
            from jax.sharding import NamedSharding, PartitionSpec
            job_buf = jax.device_put(
                job_h, NamedSharding(mesh, PartitionSpec("dp")))
            win_buf = jax.device_put(
                win_h, NamedSharding(mesh, PartitionSpec()))
        record_h2d(job_h.nbytes + win_h.nbytes,
                   time.perf_counter() - t0, name="h2d/chunk")
        return job_buf, win_buf

    # The transfer retries whole: device_put is idempotent from the
    # host buffers, and a RetryExhausted here is the degradation signal
    # the engine catches to route the chunk to the host path.
    from racon_tpu.ops.budget import transfer_deadline_s
    return retry_call(
        "h2d/chunk", _put,
        deadline_s=transfer_deadline_s(job_h.nbytes + win_h.nbytes,
                                       "h2d"))


def dispatch_chunk(plan: ChunkPlan, *, match: int, mismatch: int,
                   gap: int, ins_scale: float, rounds: int,
                   stats: Optional[dict] = None, mesh=None,
                   bufs: Optional[Tuple[object, object]] = None):
    """Ship a chunk to the device and chain all refinement rounds —
    returns the (still in-flight) packed output array. No host sync:
    the caller may dispatch further chunks before collecting, so h2d of
    chunk i+1 overlaps chunk i's compute.

    ``stats`` (optional dict) accumulates phase wall times under keys
    "h2d" / "compute" / "d2h" / "chunks". Phase edges force a tiny d2h
    (jax.block_until_ready is a no-op on the axon backend), so
    collecting stats serializes the pipeline and adds two tunnel
    round-trips per chunk; production runs pass None and pay nothing.
    RACON_TPU_TIMING=1 additionally prints each round's time to stderr.

    ``bufs`` takes a pre-transferred :func:`put_chunk_bufs` result so a
    caller can overlap the h2d with earlier compute; None ships the
    buffers here. Honored on the production path only (the verbose
    per-round path ships separate arrays).
    """
    import os
    import sys
    import time
    import jax
    import jax.numpy as jnp

    verbose = envspec.read("RACON_TPU_TIMING") not in ("", "0")
    collect = stats is not None or verbose

    def sync(x, tag, t0):
        np.asarray(jnp.ravel(x)[:1])
        dt = time.perf_counter() - t0
        if verbose:
            print(f"[racon_tpu::run_chunk] {tag}: {dt:.3f}s",
                  file=sys.stderr, flush=True)
        if stats is not None:
            key = tag.split("/")[0]
            stats[key] = stats.get(key, 0.0) + dt
        return time.perf_counter()

    ndp = mesh.shape["dp"] if mesh is not None else 1
    pallas = _use_pallas(plan.B // ndp, plan.Lq, plan.LA)
    band_w = (0 if envspec.read("RACON_TPU_NO_BAND")
              not in ("", "0", "false") else plan.band_w)
    # Walk depth for this chunk's banded forwards. Selected at the
    # round-0 (widest) band so every round of the chunk shares one k:
    # the k=4 nxt2 plane must fit the element budget at the largest
    # per-round geometry. The flat fallback has no nxt planes at all.
    from racon_tpu.ops.budget import walk_k_for
    nxt_k = walk_k_for(plan.B // ndp * plan.Lq * band_w) if band_w else 1
    from racon_tpu.ops.colwalk import chain_len
    from racon_tpu.obs.metrics import record_h2d, registry as obs_registry
    obs_registry().set("walk_chain_len",
                       chain_len(plan.LA, nxt_k if band_w else 1))
    t0 = time.perf_counter()
    if not verbose:
        # Production path: TWO h2d byte buffers, then the whole chunk
        # (all rounds + output packing) as ONE dispatch — per-transfer
        # and per-dispatch tunnel latency otherwise dominate. Stats
        # collection syncs once on each phase edge.
        if bufs is None:
            bufs = put_chunk_bufs(plan, mesh=mesh)
        job_buf, win_buf = bufs
        if collect:
            # Sync on BOTH buffers: device_put is async, and an
            # in-flight job_buf would otherwise bleed into "compute".
            t0 = sync(job_buf, "h2d/job", t0)
            t0 = sync(win_buf, "h2d", t0)
        from racon_tpu.resilience.retry import call as retry_call
        # Adaptive early exit: only meaningful with at least one
        # skippable middle round, and only sound when every non-final
        # round shares one scale (the replay argument; PoaEngine's
        # schedule satisfies this by construction).
        sc = ins_scale if isinstance(ins_scale, tuple) \
            else (ins_scale,) * rounds
        adaptive = (envspec.read("RACON_TPU_ADAPTIVE")
                    not in ("0", "false")
                    and rounds >= 3 and len(set(sc[:-1])) <= 1)
        from racon_tpu.ops.budget import dispatch_deadline_s
        # Deadline scales with the chunk's forward-plane work: B reads
        # x Lq rows x band (or full LA) columns, once per round.
        cells = (plan.B * plan.Lq * (band_w if band_w else plan.LA)
                 * max(rounds, 1))
        packed = retry_call(
            "dispatch/chunk", device_chunk_packed, job_buf, win_buf,
            match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
            Lq=plan.Lq, n_win=plan.n_win, LA=plan.LA,
            pallas=pallas, band_w=band_w, rounds=rounds,
            adaptive=adaptive, mesh=mesh, nxt_k=nxt_k,
            deadline_s=dispatch_deadline_s(cells))
        obs_registry().inc("device_dispatches")
        if collect:
            t0 = sync(packed, "compute", t0)
        if stats is not None:
            stats["chunks"] = stats.get("chunks", 0) + 1
            stats["_t_pack"] = time.perf_counter()
        return packed

    # Verbose path: separate arrays + one dispatch per round so each
    # round's wall time stays attributable (RACON_TPU_TIMING=1).
    host_args = (plan.bb, plan.bbw, plan.alen, plan.begin, plan.end,
                 plan.q, plan.qw8, plan.lq, plan.w_read, plan.win)
    if mesh is None:
        rnd = device_round
    else:
        rnd = functools.partial(device_round_sharded, mesh=mesh)

    def _put():
        t_put = time.perf_counter()
        if mesh is None:
            out = jax.device_put(host_args)
        else:
            from jax.sharding import NamedSharding, PartitionSpec
            rep = NamedSharding(mesh, PartitionSpec())
            job = NamedSharding(mesh, PartitionSpec("dp"))
            shardings = (rep, rep, rep, job, job, job, job, job, job,
                         job)
            out = tuple(jax.device_put(a, s)
                        for a, s in zip(host_args, shardings))
        record_h2d(sum(a.nbytes for a in host_args),
                   time.perf_counter() - t_put, name="h2d/chunk")
        return out

    # Same watchdog/retry envelope as the packed path: the verbose
    # timing path must not reopen the unguarded-transfer hole that
    # fail-slow hardening closed (choke-point rule CHK001).
    from racon_tpu.resilience.retry import call as retry_call
    from racon_tpu.ops.budget import transfer_deadline_s
    dev_args = retry_call(
        "h2d/chunk", _put,
        deadline_s=transfer_deadline_s(
            sum(a.nbytes for a in host_args), "h2d"))
    bb, bbw, alen, begin, end, q, qw8, lq, w_read, win = dev_args
    t0 = sync(alen, "h2d", t0)
    cov = None
    ovf = jnp.zeros(plan.n_win, dtype=bool)
    scales = ins_scale if isinstance(ins_scale, tuple) \
        else (ins_scale,) * rounds
    for r in range(rounds):
        bb, bbw, alen, begin, end, cov, ovf, _ = rnd(
            bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, ovf,
            match=match, mismatch=mismatch, gap=gap,
            ins_scale=scales[r], Lq=plan.Lq, n_win=plan.n_win,
            LA=plan.LA, pallas=pallas,
            band_w=round_band_width(band_w, r), nxt_k=nxt_k)
        obs_registry().inc("device_dispatches")
        t0 = sync(cov, f"compute/round{r}", t0)
    if stats is not None:
        stats["chunks"] = stats.get("chunks", 0) + 1
        stats["_t_pack"] = time.perf_counter()

    return _pack_out(bb[:-1], cov, alen[:-1], ovf,
                     jnp.int32(rounds), jnp.int32(rounds))


def chunk_statics(plan: ChunkPlan, *, ins_scale, rounds: int) -> dict:
    """The per-chunk static selections dispatch_chunk makes (pallas /
    band width / walk depth / adaptive gate), as one dict — the
    decoupled path computes them ONCE here and threads the same values
    through both its executables, so the fwd and walk programs can
    never disagree about layout or depth. Single-device form (ndp=1):
    the decoupled walk is gated off under a mesh."""
    pallas = _use_pallas(plan.B, plan.Lq, plan.LA)
    band_w = (0 if envspec.read("RACON_TPU_NO_BAND")
              not in ("", "0", "false") else plan.band_w)
    from racon_tpu.ops.budget import walk_k_for
    nxt_k = walk_k_for(plan.B * plan.Lq * band_w) if band_w else 1
    sc = ins_scale if isinstance(ins_scale, tuple) \
        else (ins_scale,) * rounds
    adaptive = (envspec.read("RACON_TPU_ADAPTIVE")
                not in ("0", "false")
                and rounds >= 3 and len(set(sc[:-1])) <= 1)
    return {"pallas": pallas, "band_w": band_w, "nxt_k": nxt_k,
            "adaptive": adaptive}


def walk_plane_bytes_for(plan: ChunkPlan, *, ins_scale, rounds: int,
                         statics: Optional[dict] = None) -> int:
    """Device-resident bytes of the walk-input planes one queued chunk
    holds across the decoupled handoff — budget.walk_plane_bytes at the
    FINAL round's band width (the only round whose planes outlive their
    dispatch). The streaming executor's admission check compares this
    against budget.walk_queue_depth's aggregate cap."""
    from racon_tpu.ops.budget import walk_plane_bytes
    st = statics if statics is not None else \
        chunk_statics(plan, ins_scale=ins_scale, rounds=rounds)
    band_w = st["band_w"]
    W = round_band_width(band_w, rounds - 1) if band_w else plan.LA
    return walk_plane_bytes(plan.B, plan.Lq, W,
                            st["nxt_k"] if band_w else 1)


def dispatch_chunk_fwd(plan: ChunkPlan, *, match: int, mismatch: int,
                       gap: int, ins_scale, rounds: int,
                       bufs: Optional[Tuple[object, object]] = None):
    """Ship a chunk's forward/refinement half (device_chunk_fwd) —
    returns ``(fwd_out, meta)`` where ``fwd_out`` is the still-in-flight
    plane/state tuple and ``meta`` the static selections plus the live
    ``job_buf`` that ops/colwalk.py::dispatch_walk needs to finish the
    chunk. Same "dispatch/chunk" retry site and geometry deadline as
    the fused dispatch (it IS the chunk's forward dispatch); the walk
    dispatch adds its own "dispatch/walk" envelope.

    Single-device only (no ``mesh``): the streaming executor falls back
    to the fused path under dp — see device_chunk_fwd's docstring.
    """
    from racon_tpu.obs.metrics import registry as obs_registry
    from racon_tpu.ops.budget import dispatch_deadline_s
    from racon_tpu.ops.colwalk import chain_len
    from racon_tpu.resilience.retry import call as retry_call

    st = chunk_statics(plan, ins_scale=ins_scale, rounds=rounds)
    band_w = st["band_w"]
    obs_registry().set("walk_chain_len",
                       chain_len(plan.LA, st["nxt_k"] if band_w else 1))
    if bufs is None:
        bufs = put_chunk_bufs(plan)
    job_buf, win_buf = bufs
    cells = (plan.B * plan.Lq * (band_w if band_w else plan.LA)
             * max(rounds, 1))
    fwd_out = retry_call(
        "dispatch/chunk", device_chunk_fwd, job_buf, win_buf,
        match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
        Lq=plan.Lq, n_win=plan.n_win, LA=plan.LA,
        pallas=st["pallas"], band_w=band_w, rounds=rounds,
        adaptive=st["adaptive"], nxt_k=st["nxt_k"],
        deadline_s=dispatch_deadline_s(cells))
    obs_registry().inc("device_dispatches")
    meta = dict(st, job_buf=job_buf, ins_scale=ins_scale, rounds=rounds)
    return fwd_out, meta


def collect_chunk(plan: ChunkPlan, packed, stats: Optional[dict] = None
                  ) -> Tuple[List[Optional[bytes]],
                             List[Optional[np.ndarray]]]:
    """Pull a dispatched chunk's packed output and unpack per window.

    Returns (consensus codes bytes per window, coverage arrays). A
    window whose consensus outgrew the padded anchor width (sticky
    ``ovf`` flag) yields ``None`` in both lists — the caller must re-run
    it on the unbounded host path instead of shipping a silently
    truncated string.
    """
    import time
    from racon_tpu.obs.metrics import record_d2h
    from racon_tpu.resilience.retry import call as retry_call

    def _pull():
        t0 = time.perf_counter()
        ph = np.asarray(packed)
        # The pull blocks until the chunk's compute drains too, so this
        # is "time blocked in d2h", an upper bound on pure transfer
        # (metrics module docstring discusses the bandwidth-estimate
        # semantics).
        record_d2h(ph.nbytes, time.perf_counter() - t0, name="d2h/chunk")
        return ph

    from racon_tpu.ops.budget import transfer_deadline_s
    # Packed output layout (below): Nw*LA codes + 2*Nw*LA cov(int16)
    # + 4*Nw alen + Nw ovf + 8 adaptive-round bytes.
    out_bytes = 3 * plan.n_win * plan.LA + 5 * plan.n_win + 8
    ph = retry_call("d2h/chunk", _pull,
                    deadline_s=transfer_deadline_s(out_bytes, "d2h"))
    if stats is not None and "_t_pack" in stats:
        stats["d2h"] = stats.get("d2h", 0.0) + \
            (time.perf_counter() - stats.pop("_t_pack"))
    Nw, LA = plan.n_win, plan.LA
    codes_h = ph[:Nw * LA].reshape(Nw, LA)
    cov_h = ph[Nw * LA:3 * Nw * LA].view(np.int16).reshape(Nw, LA)
    alen_h = ph[3 * Nw * LA:3 * Nw * LA + 4 * Nw].view(np.int32)[:Nw]
    base = 3 * Nw * LA + 4 * Nw
    ovf_h = ph[base:base + Nw] != 0
    rex = int(ph[base + Nw:base + Nw + 4].view(np.int32)[0])
    rsch = int(ph[base + Nw + 4:base + Nw + 8].view(np.int32)[0])
    from racon_tpu.obs.metrics import registry as obs_registry
    reg = obs_registry()
    reg.inc("adaptive_rounds_executed", rex)
    reg.inc("adaptive_rounds_scheduled", rsch)
    if rex < rsch:
        reg.inc("adaptive_early_exits")
    if stats is not None:
        stats["rounds_exec"] = stats.get("rounds_exec", 0) + rex
        stats["rounds_sched"] = stats.get("rounds_sched", 0) + rsch

    out_codes: List[Optional[bytes]] = []
    out_cov: List[Optional[np.ndarray]] = []
    for wi in range(plan.n_real_win):
        if ovf_h[wi]:
            out_codes.append(None)
            out_cov.append(None)
            continue
        L = int(alen_h[wi])
        out_codes.append(codes_h[wi, :L].tobytes())
        out_cov.append(cov_h[wi, :L].astype(np.int32))
    return out_codes, out_cov


def run_chunk(plan: ChunkPlan, *, match: int, mismatch: int, gap: int,
              ins_scale: float, rounds: int, stats: Optional[dict] = None,
              mesh=None
              ) -> Tuple[List[Optional[bytes]], List[Optional[np.ndarray]]]:
    """dispatch_chunk + collect_chunk, back to back (sequential form)."""
    packed = dispatch_chunk(plan, match=match, mismatch=mismatch, gap=gap,
                            ins_scale=ins_scale, rounds=rounds,
                            stats=stats, mesh=mesh)
    return collect_chunk(plan, packed, stats=stats)
