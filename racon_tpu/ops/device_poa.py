"""Device-resident POA consensus engine — the TPU hot path.

Round-2's engine shipped alignment ops to the host and merged in numpy
every refinement round; on a tunneled TPU (30 MB/s, ~75 ms per
synchronized dispatch — see PROFILE.md) that cost ~10x the compute. This
engine keeps the whole refinement loop on device:

  h2d once:  encoded layer codes/weights, backbone anchors, spans
  per round (no host sync, chained dispatch):
    - job geometry from spans (full-span 1% rule, src/window.cpp:82)
    - shifted target buffer by gather from the current anchors
    - banded NW forward (Pallas kernel on TPU, XLA fallback elsewhere)
    - batched banded traceback (one scan for all lanes)
    - vote extraction + window aggregation + assembly + compaction
      (racon_tpu/ops/device_merge.py) -> next round's anchors + spans,
      all device-side
  d2h once:  compact consensus codes + coverage + lengths + edge stats

Semantics match PoaEngine's numpy path bit-for-bit on integer weights
(differentially tested); the banded alignment equals the native adaptive
aligner's first pass wherever the traceback stays off the artificial band
edge (flagged lanes are counted and reported).
"""

from __future__ import annotations

import functools
from typing import List, Optional, Tuple

import numpy as np

from racon_tpu.models.window import Window, window_arrays
from racon_tpu.ops.encode import ALPHABET
from racon_tpu.ops import flat as flatmod
from racon_tpu.ops.flat import PAD_OP

# Keep Lq * B * Lt under int32 flat-index range for the traceback gather.
MAX_DIR_ELEMS = 1_600_000_000

LA_GROW = 128      # anchor slack for insertion growth across rounds


def _round_up(n: int, mult: int) -> int:
    return ((max(n, 1) + mult - 1) // mult) * mult


def dir_elems(n_jobs: int, max_lq: int, max_bb: int) -> int:
    """Dirs-tensor element count for a chunk, with ChunkPlan's padding."""
    return (_round_up(n_jobs, 128) * _round_up(max_lq, 32) *
            _round_up(max_bb + LA_GROW, 128))


class ChunkPlan:
    """Host-side padded arrays for one device chunk (static shapes)."""

    def __init__(self, windows: List[Window], la_grow: int = LA_GROW,
                 b_mult: int = 128):
        self.windows = windows
        jobs_q: List[np.ndarray] = []
        jobs_w: List[np.ndarray] = []
        begin: List[int] = []
        end: List[int] = []
        win: List[int] = []
        anchors: List[np.ndarray] = []
        anchor_w: List[np.ndarray] = []
        for wi, w in enumerate(windows):
            lays, bb, bw = window_arrays(w)
            for codes, wts, b, e in lays:
                jobs_q.append(codes)
                jobs_w.append(wts)
                begin.append(b)
                end.append(e)
                win.append(wi)
            anchors.append(bb)
            anchor_w.append(bw)

        self.n_win = len(windows)
        self.n_jobs = len(jobs_q)
        B = _round_up(self.n_jobs, b_mult)
        Lq = _round_up(max(len(q) for q in jobs_q), 32)
        LA0 = max(len(a) for a in anchors)
        LA = _round_up(LA0 + la_grow, 128)
        self.B, self.Lq, self.LA = B, Lq, LA
        self.steps = Lq + LA

        self.q = np.zeros((B, Lq), np.uint8)
        # Weights ship as uint8 (value+1, 0 = padding) and decode on device
        # — a 4x smaller h2d than f32 weights on a ~30 MB/s tunnel.
        self.qw8 = np.zeros((B, Lq), np.uint8)
        self.lq = np.ones(B, np.int32)
        self.w_read = np.zeros(B, np.float32)
        # Padded lanes point at a dummy extra window (n_win) so their votes
        # aggregate into a discarded row.
        self.win = np.full(B, self.n_win, np.int32)
        self.begin = np.zeros(B, np.int32)
        self.end = np.ones(B, np.int32)
        for b in range(self.n_jobs):
            ql = len(jobs_q[b])
            self.q[b, :ql] = jobs_q[b]
            self.qw8[b, :ql] = jobs_w[b].astype(np.uint8) + 1
            self.lq[b] = ql
            self.w_read[b] = float(jobs_w[b].astype(np.float64).mean()) \
                if ql else 0.0
            self.win[b] = win[b]
            self.begin[b] = begin[b]
            self.end[b] = end[b]

        Nw = self.n_win + 1   # + dummy row for padded lanes
        self.bb = np.zeros((Nw, LA), np.uint8)
        self.bbw = np.zeros((Nw, LA), np.float32)
        self.alen = np.ones(Nw, np.int32)
        for wi in range(self.n_win):
            L = len(anchors[wi])
            self.bb[wi, :L] = anchors[wi]
            self.bbw[wi, :L] = anchor_w[wi]
            self.alen[wi] = L


def _use_pallas(B: int, Lq: int, LA: int) -> bool:
    import jax
    from racon_tpu.ops.pallas.flat_kernel import TB, CH
    if jax.default_backend() not in ("tpu", "axon"):
        return False
    return B % TB == 0 and Lq % CH == 0 and LA % 128 == 0


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "ins_scale", "Lq", "steps",
                     "n_win", "LA", "pallas"))
def device_round(bb, bbw, alen, begin, end, q, qw8, lq, w_read, win, *,
                 match, mismatch, gap, ins_scale, Lq, steps, n_win,
                 LA, pallas):
    """One alignment + merge round, fully on device.

    Returns (new_bb, new_bbw, new_alen, new_begin, new_end, cov).
    """
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops import device_merge as dm

    B = q.shape[0]
    L = jnp.take(alen, win)                             # anchor len per job
    b_c = jnp.clip(begin, 0, L - 1)
    e_c = jnp.clip(end, b_c, L - 1)
    # uint32 offset = 0.01 * L, strict end > L - offset (window.cpp:82).
    offs = (0.01 * L.astype(jnp.float32)).astype(jnp.int32)
    full = (b_c < offs) & (e_c > L - offs)
    t_off = jnp.where(full, 0, b_c).astype(jnp.int32)
    lt = jnp.where(full, L, e_c - b_c + 1).astype(jnp.int32)

    # Target buffer in absolute coordinates: tbuf[b, x] = anchor slice.
    x = jnp.arange(LA, dtype=jnp.int32)[None, :]
    ok = x < lt[:, None]
    flat = bb.reshape(-1)
    gidx = (win[:, None] * LA + jnp.clip(t_off[:, None] + x, 0, LA - 1))
    tbuf = jnp.where(ok, jnp.take(flat, gidx), 7).astype(jnp.uint8)

    if pallas:
        from racon_tpu.ops.pallas.flat_kernel import fw_dirs_pallas
        dirs = fw_dirs_pallas(tbuf, q.T,
                              match=match, mismatch=mismatch, gap=gap)
    else:
        dirs = flatmod.fw_dirs_xla(tbuf, q.T,
                                   match=match, mismatch=mismatch, gap=gap)
    rev = flatmod.fw_traceback(dirs, lq, lt, steps)
    ops = jnp.flip(rev, axis=1)

    qw = jnp.maximum(qw8.astype(jnp.float32) - 1.0, 0.0)
    votes = dm.extract_votes(ops, q, qw, w_read, lt, t_off, LA)
    acc = dm.aggregate_votes(votes, win, n_win + 1)
    acc = {k: v[:-1] for k, v in acc.items()}       # drop padded-lane row
    acc = dm.add_backbone(acc, bb[:-1], bbw[:-1], alen[:-1])
    asm = dm.assemble(acc, alen[:-1], ins_scale)
    codes, cov, total = dm.compact(asm, LA)
    map_b, map_e = dm.coord_maps(asm, alen[:-1], LA)

    # Next-round anchors (dummy row re-appended) and remapped spans.
    new_bb = jnp.concatenate([codes, bb[-1:]], axis=0)
    new_bbw = jnp.zeros_like(bbw)
    new_alen = jnp.concatenate(
        [jnp.clip(total, 1, LA), alen[-1:]], axis=0).astype(jnp.int32)
    mb_flat = map_b.reshape(-1)
    me_flat = map_e.reshape(-1)
    winc = jnp.minimum(win, map_b.shape[0] - 1)
    nb = jnp.where(begin < L,
                   jnp.take(mb_flat, winc * LA + jnp.clip(begin, 0, LA - 1)),
                   0).astype(jnp.int32)
    tot_j = jnp.take(jnp.clip(total, 1, LA), winc)
    ne = jnp.where(end < L,
                   jnp.take(me_flat, winc * LA + jnp.clip(end, 0, LA - 1)),
                   tot_j - 1).astype(jnp.int32)
    return new_bb, new_bbw, new_alen, nb, ne, cov


@functools.partial(__import__("jax").jit)
def _pack_out(codes, cov, alen):
    """Flatten codes/cov/lengths into one uint8 buffer for a single d2h
    transfer (each synchronized pull pays ~75 ms tunnel latency)."""
    import jax
    import jax.numpy as jnp
    c16 = jnp.clip(cov, 0, 32767).astype(jnp.int16)
    tail = alen.astype(jnp.int32)
    return jnp.concatenate([
        codes.reshape(-1),
        jax.lax.bitcast_convert_type(c16, jnp.uint8).reshape(-1),
        jax.lax.bitcast_convert_type(tail, jnp.uint8).reshape(-1),
    ])


def run_chunk(plan: ChunkPlan, *, match: int, mismatch: int, gap: int,
              ins_scale: float, rounds: int
              ) -> Tuple[List[bytes], List[np.ndarray]]:
    """Execute all refinement rounds for a chunk; one h2d, one d2h.

    Returns (consensus codes bytes per window, coverage arrays).
    """
    import jax
    import jax.numpy as jnp

    pallas = _use_pallas(plan.B, plan.Lq, plan.LA)
    dev_args = jax.device_put((plan.bb, plan.bbw, plan.alen, plan.begin,
                               plan.end, plan.q, plan.qw8, plan.lq,
                               plan.w_read, plan.win))
    bb, bbw, alen, begin, end, q, qw8, lq, w_read, win = dev_args
    cov = None
    for _ in range(rounds):
        bb, bbw, alen, begin, end, cov = device_round(
            bb, bbw, alen, begin, end, q, qw8, lq, w_read, win,
            match=match, mismatch=mismatch, gap=gap, ins_scale=ins_scale,
            Lq=plan.Lq, steps=plan.steps, n_win=plan.n_win,
            LA=plan.LA, pallas=pallas)

    # One synchronized pull: everything packed into a single uint8 buffer.
    Nw, LA = plan.n_win, plan.LA
    packed = _pack_out(bb[:-1], cov, alen[:-1])
    ph = np.asarray(packed)
    codes_h = ph[:Nw * LA].reshape(Nw, LA)
    cov_h = ph[Nw * LA:3 * Nw * LA].view(np.int16).reshape(Nw, LA)
    alen_h = ph[3 * Nw * LA:].view(np.int32)[:Nw]

    out_codes: List[bytes] = []
    out_cov: List[np.ndarray] = []
    for wi in range(plan.n_win):
        L = int(alen_h[wi])
        out_codes.append(codes_h[wi, :L].tobytes())
        out_cov.append(cov_h[wi, :L].astype(np.int32))
    return out_codes, out_cov
