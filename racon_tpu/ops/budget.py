"""Device-path element and VMEM budgets — derived once, shared.

The consensus engine (racon_tpu/ops/device_poa.py) and the overlap
aligner (racon_tpu/ops/ovl_align.py) both admit work against a cap on
the forward kernels' per-plane cell count (B * Lq * W elements of the
dirs/nxt tensors). Round 5 shipped that cap as two hand-maintained
literals — 1.6e9 in the consensus engine, 1.9e9 re-derived in the
overlap aligner — and the 0.7% gap silently routed EVERY 8 kb genome
overlap (128 x 8192 x 1536 = 1.61e9 elements) to the native fallback
(PROFILE.md round 5). This module derives the cap from the actual
constraints so the two paths cannot drift apart again:

1. **int32 flat index.** The column walk (racon_tpu/ops/colwalk.py) and
   the legacy traceback address the cell tensors through a flattened
   int32 index, so the element count must stay below 2^31.
2. **HBM single-buffer ceiling.** The runtime rejects single buffers of
   2 GB and above, so the element count times the cell byte width must
   stay below 2^31 bytes. At uint8 cells (both planes of the dual-column
   layout ship as SEPARATE uint8 tensors, each under the cap on its own)
   this coincides with (1); a packed uint16 cell layout would halve the
   admissible geometry here — which is exactly why the dual-column
   metadata is a second u8 plane and not a widened cell word.

A 10% margin keeps slack for XLA padding/layout overhead while still
admitting the genome geometry the 1.6e9 literal rejected.

VMEM admission for the band kernel's long-read tiles lives here too
(:func:`vmem_est`), consumed by ovl_align's tile picker and bucket
admission. tests/test_budget.py pins the boundary geometries.

Round 8 adds the **nxt-k term**: at walk depth k=4 the band forwards
emit a third plane, ``nxt2`` — uint16 cells packing the 2nd and 3rd
predecessor hops — so the column walk undoes FOUR anchor positions per
dependent gather. The u16 plane halves the admissible element count
(constraint 2: ``max_dir_elems(2)``), so k is selected PER GEOMETRY by
:func:`walk_k_for`: geometries whose plane would breach the u16 cap
(the 8 kb genome overlap among them) degrade to the k=2 dual-column
layout rather than being rejected. ``RACON_TPU_WALK_K`` (1/2/4,
default 4) caps the selection; 2 reproduces the PR 5 behavior exactly.
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec

# Constraint (1): flat gather/scatter indices are int32 on device.
INT32_INDEX_ELEMS = 2 ** 31
# Constraint (2): single HBM buffer allocations below 2 GB.
BUFFER_BYTES = 2 ** 31
# Headroom for XLA padding/layout overhead.
_MARGIN_NUM, _MARGIN_DEN = 9, 10


def max_dir_elems(cell_bytes: int = 1) -> int:
    """Element cap for ONE forward-kernel cell plane of ``cell_bytes``-
    wide cells. ``max_dir_elems(1)`` (~1.93e9) admits the 8 kb-read
    genome overlap geometry (1.61e9); ``max_dir_elems(2)`` (~0.97e9)
    would not — see the module docstring on why the dual-column walk
    ships a second u8 plane instead of u16 cells."""
    if cell_bytes < 1:
        raise ValueError("[racon_tpu::budget] cell_bytes must be >= 1")
    cap = min(INT32_INDEX_ELEMS, BUFFER_BYTES // cell_bytes)
    return cap * _MARGIN_NUM // _MARGIN_DEN


# Usable fraction of the ~16 MiB per-core VMEM scoped limit.
VMEM_BUDGET = 12 * 1024 * 1024


def vmem_est(W: int, Lq: int, ch: int, nxt_k: int = 2) -> int:
    """Band-kernel VMEM block-byte model at long-read geometry: the
    (W+Lq, 128) int32 target window (int16 would halve it, but Mosaic
    requires 8-aligned dynamic sublane slices below 32 bits), the
    double-buffered (ch, W, 128) u8 dirs AND nxt blocks (the dual-column
    walk's second plane doubled this term), and four W-tall 128-lane i32
    rows (prev + packed NUC scratch + hlast + working row). Lane blocks
    always pad to 128 on TPU, so shrinking the batch below 128 lanes
    saves nothing — ch and the admission cap are the only levers.

    ``nxt_k >= 4`` adds the double-buffered (ch, W, 128) u16 ``nxt2``
    block (2nd+3rd predecessor hops): +4*ch bytes per W lane-slot. The
    k=2 default keeps every pre-round-8 admission decision byte-stable.
    """
    planes = 8 * ch if nxt_k >= 4 else 4 * ch
    return 128 * (4 * (W + Lq) + W * (planes + 16))


# --------------------------------------------------- walk depth (nxt-k)

WALK_K_ENV = "RACON_TPU_WALK_K"


def walk_k_env() -> int:
    """The requested walk depth from ``RACON_TPU_WALK_K``: 4 (default,
    quad-column), 2 (PR 5 dual-column), or 1 (single-step reference).
    Anything else is a hard error — a typo silently degrading the walk
    would be invisible until a profile regression."""
    raw = envspec.read(WALK_K_ENV).strip()
    if not raw:
        return 4
    try:
        k = int(raw)
    except ValueError:
        k = -1
    if k not in (1, 2, 4):
        raise ValueError(
            f"[racon_tpu::budget] {WALK_K_ENV}={raw!r} invalid — "
            "supported walk depths are 1, 2 and 4")
    return k


def walk_k_for(elems: int, env_k=None) -> int:
    """Admissible walk depth for a geometry of ``elems`` cells per
    plane: the env-requested k, degraded to 2 when the u16 ``nxt2``
    plane would breach ``max_dir_elems(2)`` (the 2 GB single-buffer
    ceiling at 2-byte cells). Degradation — not rejection — keeps every
    k=2-admissible geometry on device; the chain is just longer there."""
    k = walk_k_env() if env_k is None else int(env_k)
    if k >= 4 and elems > max_dir_elems(2):
        return 2
    return k


# ------------------------------------- decoupled-walk in-flight queue

WALK_QUEUE_ENV = "RACON_TPU_WALK_QUEUE"

# Aggregate device-resident budget for QUEUED walk-input planes (the
# dirs/nxt/nxt2 tensors a decoupled chunk parks between its forward and
# walk dispatches — pipeline/streaming.py walk stage). Same 9/10-margin
# discipline as the single-buffer caps above: the queue shares HBM with
# the live forward's own planes, so it gets one buffer's worth, not the
# whole device.
WALK_QUEUE_BYTES = BUFFER_BYTES * 9 // 10


def walk_plane_bytes(B: int, Lq: int, W: int, nxt_k: int) -> int:
    """Device-resident bytes of ONE chunk's walk-input planes at lanes
    B, query padding Lq, (band or anchor) width W and walk depth nxt_k:
    the u8 dirs plane, plus the u8 ``nxt`` plane at k >= 2, plus the u16
    ``nxt2`` plane at k >= 4. The per-lane scalars (lt/t_off/klo/esc0)
    and carried round state are noise next to these and are not
    counted."""
    per = 1 + (1 if nxt_k >= 2 else 0) + (2 if nxt_k >= 4 else 0)
    return int(B) * int(Lq) * int(W) * per


def walk_queue_depth(plane_bytes: int, want: int) -> int:
    """Admissible in-flight walk-queue depth: the requested depth
    ``want``, clamped so ``depth * plane_bytes <= WALK_QUEUE_BYTES``.
    0 means the decoupled path is off (the streaming executor falls
    back to fused dispatches); a geometry too large for even one queued
    chunk clamps to 0 rather than admitting an over-budget plane."""
    if want <= 0:
        return 0
    if plane_bytes <= 0:
        return int(want)
    return min(int(want), WALK_QUEUE_BYTES // int(plane_bytes))


def walk_queue_env(default: int) -> int:
    """The requested walk-queue depth from ``RACON_TPU_WALK_QUEUE``
    (empty -> ``default``, usually the pipeline depth). Non-integers
    and negatives are hard errors — same typo discipline as
    walk_k_env."""
    raw = envspec.read(WALK_QUEUE_ENV).strip()
    if not raw:
        return int(default)
    try:
        d = int(raw)
    except ValueError:
        d = -1
    if d < 0:
        raise ValueError(
            f"[racon_tpu::budget] {WALK_QUEUE_ENV}={raw!r} invalid — "
            "expected a non-negative integer queue depth")
    return d


# ---------------------------------------------------------------------------
# Per-tile admission tiers for the TILED band forward (ultralong reads).
#
# The untiled overlap path admits a whole read only when
# 128 * round_up(Lq) * W fits max_dir_elems(1) — which caps reads at
# ~9 kb at the W=1024 overlap band. The tiled path runs the SAME band
# kernel over query-axis tiles of T rows, carrying the DP frontier
# between tiles, so the per-dispatch VMEM working set depends on
# (W, T, ch) only. Two budgets remain read-length dependent:
#
#   * element cap  — the walk still addresses the STITCHED dirs/nxt
#     tensors ([Lq, W, B]) through one flat int32 index, so
#     B * round_up(Lq, T) * W <= max_dir_elems(1) must hold. Lanes (B)
#     become the lever: fewer lanes per chunk buys longer reads.
#   * VMEM         — vmem_est(W, T, ch) <= VMEM_BUDGET per tile, since
#     the kernel's tband window block is (W + T) tall, not (W + Lq).
#
# Each tier is (lanes, W, T, ch), ordered preferred-first (more lanes
# amortize dispatch better; wider bands certify more error). With the
# 1.93e9 u8 cap the tiers admit reads up to:
#
#   (64, 1536, 2048, 4): vmem 7.75 MiB, Lq <= 19,660 -> 18 kb class
#   (16, 2048, 2048, 4): vmem 10.0 MiB, Lq <= 58,982 -> 57 kb class
#   ( 8, 2048, 4096, 4): vmem 11.0 MiB, Lq <= 117,964 -> 114 kb class
#
# covering the 50-100 kb ONT ultralong range that motivated the tiling
# (ROADMAP item 3). tests/test_budget.py pins every tier against all
# three budgets.
# ---------------------------------------------------------------------------

TILE_TIERS = (
    (64, 1536, 2048, 4),
    (16, 2048, 2048, 4),
    (8, 2048, 4096, 4),
)


class TilePlan:
    """Admission result for one tiled overlap job: chunk geometry plus
    the padded query length / tile count the dispatch will use.
    ``nxt_k`` is the per-tier walk depth (4 when the u16 nxt2 plane and
    its VMEM block both fit this tier's geometry, else 2)."""

    __slots__ = ("lanes", "W", "T", "ch", "Lq", "n_tiles", "nxt_k")

    def __init__(self, lanes, W, T, ch, Lq, n_tiles, nxt_k=2):
        self.lanes = lanes
        self.W = W
        self.T = T
        self.ch = ch
        self.Lq = Lq
        self.n_tiles = n_tiles
        self.nxt_k = nxt_k

    def key(self):
        return (self.lanes, self.W, self.T, self.ch, self.nxt_k)

    def __repr__(self):  # pragma: no cover - debugging aid
        return ("TilePlan(lanes=%d, W=%d, T=%d, ch=%d, Lq=%d, "
                "n_tiles=%d, nxt_k=%d)"
                % (self.lanes, self.W, self.T, self.ch, self.Lq,
                   self.n_tiles, self.nxt_k))


def tile_plan(lq: int, lt: int, tiers=None):
    """Pick the first tier that admits an (lq, lt) overlap job under all
    three budgets, or None when no tier fits (caller falls back to the
    native aligner).

    Admission conditions per tier (lanes, W, T, ch):

    * ``|lt - lq| <= W // 2`` — the banded recurrence needs the start
      AND end corners inside every per-tile band; re-centering can only
      track drift when the length imbalance leaves clearance on both
      sides of the band.
    * ``lanes * round_up(lq, T) * W <= max_dir_elems(1)`` — flat int32
      walk index / 2 GB buffer over the stitched dirs (and nxt) plane.
    * ``vmem_est(W, T, ch) <= VMEM_BUDGET`` — per-tile kernel blocks.

    Admission itself is k-independent (a tier admitted at k=2 is never
    lost to the deeper walk); the plan's ``nxt_k`` upgrades to 4 only
    when the u16 nxt2 plane ALSO fits both the element and VMEM budgets
    at this tier's geometry.
    """
    if tiers is None:
        tiers = TILE_TIERS
    lq = max(int(lq), 1)
    lt = max(int(lt), 1)
    cap = max_dir_elems(1)
    for lanes, W, T, ch in tiers:
        if abs(lt - lq) > W // 2:
            continue
        Lq = -(-lq // T) * T
        if lanes * Lq * W > cap:
            continue
        if vmem_est(W, T, ch) > VMEM_BUDGET:
            continue
        nxt_k = walk_k_for(lanes * Lq * W)
        if nxt_k >= 4 and vmem_est(W, T, ch, 4) > VMEM_BUDGET:
            nxt_k = 2
        return TilePlan(lanes, W, T, ch, Lq, Lq // T, max(nxt_k, 1))
    return None


# ---------------------------------------------------------------------------
# Ava shape-bucket budget (racon_tpu/ava/planner.py, docs/AVA.md).
#
# In the all-vs-all regime every read is a target AND a query, so the
# device sees as many distinct overlap geometries as the run has
# distinct read lengths — millions, where kC polishing sees dozens.
# Each distinct padded geometry is a compile. The planner absorbs the
# diversity by quantizing lengths to a bucket quantum and coarsening
# (doubling the quantum) until the distinct-bucket count fits the
# compile budget below; the quantum ties to the consensus window
# length so bucketing never out-resolves the window granularity the
# engine already pads to.
# ---------------------------------------------------------------------------

ENV_AVA_COMPILE_BUDGET = "RACON_TPU_AVA_COMPILE_BUDGET"
_AVA_COMPILE_BUDGET_DEFAULT = 8


def ava_compile_budget() -> int:
    """Max distinct shape buckets (== compile keys) the ava planner may
    plan (``RACON_TPU_AVA_COMPILE_BUDGET``, default 8). Invalid or
    non-positive values are a hard error — a typo silently exploding
    compiles is exactly what the budget exists to prevent."""
    raw = envspec.read(ENV_AVA_COMPILE_BUDGET).strip()
    if not raw:
        return _AVA_COMPILE_BUDGET_DEFAULT
    try:
        n = int(raw)
    except ValueError:
        n = -1
    if n < 1:
        raise ValueError(
            f"[racon_tpu::budget] {ENV_AVA_COMPILE_BUDGET}={raw!r} "
            "invalid — expected a positive bucket count")
    return n


def ava_bucket_quantum(window_length: int) -> int:
    """Starting length-bucket granularity for the ava planner: a power
    of two near ``window_length / 8`` (64 for the default 500-base
    window), floored at 16. Finer than this out-resolves the engine's
    own window padding; the planner doubles it as needed to meet the
    compile budget."""
    w = max(1, int(window_length))
    return 1 << max(4, (w // 8).bit_length())


# ---------------------------------------------------------------------------
# Watchdog deadline derivation (fail-slow detection, resilience/watchdog.py).
#
# A deadline must be generous enough that legitimate work — a cold
# compile (~45 s observed), a congested 0.25 MB/s tunnel hour, a d2h
# pull blocking on a full chunk's compute — never breaches it, yet
# finite so a wedged call converts into DispatchTimeout within bounded
# time. Each site class gets an env-tunable BASE covering its fixed
# costs, plus a geometry term scaled by a pessimistic FLOOR rate:
#
#   transfer:  base(direction) + nbytes / (RACON_TPU_DEADLINE_MBPS MB/s)
#   dispatch:  base + cells / (RACON_TPU_DEADLINE_CELLS_PER_S cells/s)
#
# all multiplied by RACON_TPU_DEADLINE_SCALE. A base <= 0 disables the
# deadline for that class (guard runs inline). Invalid env values are a
# hard ValueError, same contract as RACON_TPU_WALK_K above.
# ---------------------------------------------------------------------------

DEADLINE_H2D_ENV = "RACON_TPU_DEADLINE_H2D"
DEADLINE_D2H_ENV = "RACON_TPU_DEADLINE_D2H"
DEADLINE_DISPATCH_ENV = "RACON_TPU_DEADLINE_DISPATCH"
DEADLINE_MBPS_ENV = "RACON_TPU_DEADLINE_MBPS"
DEADLINE_CELLS_ENV = "RACON_TPU_DEADLINE_CELLS_PER_S"
DEADLINE_SCALE_ENV = "RACON_TPU_DEADLINE_SCALE"

#: Base deadlines, seconds. d2h is the largest because a result pull
#: blocks on the whole chunk's residual compute, not just the wire.
_DEADLINE_BASE_DEFAULTS = {
    DEADLINE_H2D_ENV: 60.0,
    DEADLINE_D2H_ENV: 300.0,
    DEADLINE_DISPATCH_ENV: 300.0,
}
#: Floor tunnel bandwidth (MB/s) for the byte-proportional term —
#: PROFILE.md's worst observed hour is 1.4 MB/s; 0.25 leaves 5x slack.
_DEADLINE_MBPS_DEFAULT = 0.25
#: Floor device throughput (dirs cells/s) for the dispatch term. The
#: CPU interpret path — the slowest executor these kernels ever run
#: on — still clears this by orders of magnitude.
_DEADLINE_CELLS_DEFAULT = 2e6


def _deadline_env(name: str, default: float) -> float:
    raw = envspec.read(name).strip()
    if not raw:
        return default
    try:
        return float(raw)
    except ValueError:
        raise ValueError(
            f"[racon_tpu::budget] {name}={raw!r} invalid — expected a "
            "number of seconds (<= 0 disables this deadline class)")


def _deadline_scale() -> float:
    s = _deadline_env(DEADLINE_SCALE_ENV, 1.0)
    if s <= 0:
        raise ValueError(
            f"[racon_tpu::budget] {DEADLINE_SCALE_ENV} must be > 0 "
            "(disable per class with the base vars instead)")
    return s


def transfer_deadline_s(nbytes: int, direction: str) -> float:
    """Watchdog deadline for one h2d/d2h transfer of ``nbytes``.
    0.0 disables (base env var <= 0)."""
    if direction not in ("h2d", "d2h"):
        raise ValueError(
            f"[racon_tpu::budget] unknown transfer direction "
            f"{direction!r}")
    env = DEADLINE_H2D_ENV if direction == "h2d" else DEADLINE_D2H_ENV
    base = _deadline_env(env, _DEADLINE_BASE_DEFAULTS[env])
    if base <= 0:
        return 0.0
    mbps = _deadline_env(DEADLINE_MBPS_ENV, _DEADLINE_MBPS_DEFAULT)
    if mbps <= 0:
        raise ValueError(
            f"[racon_tpu::budget] {DEADLINE_MBPS_ENV} must be > 0")
    return (base + max(int(nbytes), 0) / (mbps * 1e6)) * _deadline_scale()


def dispatch_deadline_s(cells: int) -> float:
    """Watchdog deadline for one device dispatch whose forward planes
    total ``cells`` dirs cells (B * Lq-or-LA * W-class geometry; 0 for
    geometry-free sites like the scheduler's flag pulls — the pull syncs
    on compute, so it shares this class's base). 0.0 disables."""
    base = _deadline_env(DEADLINE_DISPATCH_ENV,
                         _DEADLINE_BASE_DEFAULTS[DEADLINE_DISPATCH_ENV])
    if base <= 0:
        return 0.0
    rate = _deadline_env(DEADLINE_CELLS_ENV, _DEADLINE_CELLS_DEFAULT)
    if rate <= 0:
        raise ValueError(
            f"[racon_tpu::budget] {DEADLINE_CELLS_ENV} must be > 0")
    return (base + max(int(cells), 0) / rate) * _deadline_scale()
