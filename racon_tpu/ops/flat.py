"""Full-width batched NW forward + traceback in absolute target coordinates.

The device engine's production alignment path. An earlier diagonal-banded
variant needed a per-row rotated view of the target, and `pltpu.roll`
with a dynamic shift silently corrupts rows wider than 512 lanes on the
current Mosaic stack (PROFILE.md #6), so it was dropped in favor of
absolute coordinates, which remove the rotation entirely:
lane j-1 of every row is target position j, the substitution input is a
*static* VMEM block, and padding needs no masking at all — cells beyond a
job's true lt are garbage DP over padding that the traceback (which starts
at (lq, lt) and only moves down-left) never visits.

This is exact NW (same recurrence/tie-breaking as ops/align.py and the
native aligner nw.cpp) — no band-edge heuristics, no touched flags.
Replaces: spoa's sequence-vs-graph kNW (reference src/window.cpp:89-96)
in backbone-anchored batched form.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from racon_tpu.ops.cigar import DIAG, UP, LEFT

PAD_OP = 3
_NEG = -(2 ** 30)
# UP-run saturation in the packed cell byte (4-bit field; single source
# of truth — the Pallas kernels import it). Set to device_merge.K_INS + 1
# so a saturated counter (u == U_SAT) exactly marks runs LONGER than the
# K_INS pileup slots the device merge keeps; such lanes raise the sticky
# redo flag and their windows re-polish on the unbounded host path. Small
# U_SAT is a throughput lever: the vote extraction's packed-word gather
# spans K_INS + 1 = U_SAT query/weight offsets (device_merge.py). 11 is
# the measured sweet spot on the reference lambda dataset: per-window max
# insertion-run length is <= 10 on all 96 windows (so zero redos), while
# 8 would redo 8/96 and 5 would redo 68/96 (round-5 measurement).
U_SAT = 11


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap"))
def fw_dirs_xla(tbuf: jnp.ndarray, qT: jnp.ndarray, *, match: int,
                mismatch: int, gap: int) -> jnp.ndarray:
    """Direction tensor uint8[Lq, B, Lt] via a row scan (CPU / fallback).

    tbuf: uint8[B, Lt] targets (any filler beyond each job's lt).
    qT:   uint8[Lq, B] queries (transposed).
    """
    B, Lt = tbuf.shape
    jr = jnp.arange(Lt, dtype=jnp.int32)[None, :]
    jg = (jr + 1) * gap
    t32 = tbuf.astype(jnp.int32)
    # H[0][j] = j*gap. Derived from t32 (not a fresh constant) so the
    # scan carry is device-varying under shard_map.
    P0 = jg + jnp.zeros_like(t32[:, :1])
    U0 = jnp.zeros((B, Lt), jnp.int32)
    C0 = jnp.full((B, Lt), LEFT, jnp.int32)

    def step(carry, inp):
        P, Up, Cp = carry
        i, qrow = inp
        sub = jnp.where(t32 == qrow[:, None], match, mismatch)
        Pshift = jnp.concatenate(
            [jnp.full((B, 1), (i - 1) * gap, jnp.int32), P[:, :-1]], axis=1)
        diag = Pshift + sub
        up = P + gap
        tmp = jnp.maximum(diag, up)
        # Left-gap chain with the H[i][0] = i*gap boundary folded in: its
        # one-left-move path to column 1 is i*gap + gap, injected at lane 0.
        f = jax.lax.cummax(jnp.maximum(tmp, (i + 1) * gap + jnp.where(
            jr == 0, 0, _NEG)) - jg, axis=1)
        h = f + jg
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT))
        # UP-chain metadata for the column-walk traceback (colwalk.py):
        # absolute coordinates, so the UP predecessor is the same lane.
        isup = d == UP
        U = jnp.where(isup, jnp.minimum(Up + 1, U_SAT), 0)
        C = jnp.where(isup, Cp, d)
        packed = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        return (h, U, C), packed

    ii = jnp.arange(1, qT.shape[0] + 1, dtype=jnp.int32)
    _, dirs = jax.lax.scan(step, (P0, U0, C0), (ii, qT.astype(jnp.int32)))
    return dirs


def fw_traceback(dirs: jnp.ndarray, lq: jnp.ndarray, lt: jnp.ndarray,
                 steps: int):
    """Batched walk from (lq, lt) to (0, 0); rev_ops uint8[B, steps]."""
    Lq, B, Lt = dirs.shape
    d1 = dirs.reshape(-1)
    lane = jnp.arange(B, dtype=jnp.int32)

    def step(state, _):
        i, j = state
        done = (i == 0) & (j == 0)
        idx = (jnp.maximum(i - 1, 0) * (B * Lt) + lane * Lt
               + jnp.maximum(j - 1, 0))
        dv = jnp.take(d1, idx) & 3        # low bits of the packed cell
        d = jnp.where(done, PAD_OP,
                      jnp.where(i == 0, LEFT,
                                jnp.where(j == 0, UP, dv))).astype(jnp.uint8)
        i = i - jnp.where((d == DIAG) | (d == UP), 1, 0).astype(i.dtype)
        j = j - jnp.where((d == DIAG) | (d == LEFT), 1, 0).astype(j.dtype)
        return (i, j), d

    (_, _), rev_ops = jax.lax.scan(
        step, (lq.astype(jnp.int32), lt.astype(jnp.int32)), None,
        length=steps)
    return rev_ops.T
