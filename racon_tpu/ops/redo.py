"""On-device wide-band redo: second pass for flagged windows.

Windows whose consensus outgrew the chunk's padded anchor width, whose
banded optimum failed the escape certificate, or whose walk saturated an
up-run counter come back from collect_chunk as ``None`` entries (the
sticky ``ovf`` flag). Through PR 7 every such window bounced to the
unbounded HOST consensus (PoaEngine._redo_trunc) — correct, but it
breaks SPMD cleanliness: one straggler window serializes the whole
process behind a CPU re-polish.

This module re-runs the flagged subset ON DEVICE first, through the
same ChunkPlan / dispatch_chunk / collect_chunk machinery with two
budgets widened:

* **anchor slack** — ``la_grow`` quadruples (4 * LA_GROW = 256 growth
  slots), so a consensus that legitimately outgrew the first pass's LA
  padding fits the redo's;
* **band width** — the plan's band doubles (2x-W), clamped to the
  LA - 128 ceiling the banded kernel needs; past the clamp the redo
  runs FULL-WIDTH (band_w = 0), which cannot fail the escape
  certificate at all.

Windows still flagged after the wide pass are returned to the caller
for the host fallback. Exactly two classes can remain: saturated
up-run counters (the packed-byte U field caps at U_SAT — no band width
changes the alignment's up-runs; see ops/colwalk.py) and windows whose
consensus outgrew even the quadrupled slack. Neither occurs at bench
geometry, so the host redo becomes a final fallback that never fires
there — the redo smoke (scripts/redo_smoke.py) pins exactly that, and
byte-identity with the host path rides the engine's existing
device == host contract (the redo runs the same program, just wider).

``RACON_TPU_REDO=0`` disables the device pass (PR 5/7 behavior: every
flagged window host-repolishes). Counters: obs record_redo publishes
``redo_device_windows`` / ``redo_host_windows`` / ``redo_passes``.

Redo dispatches stay FUSED forward+walk even when the streaming
executor runs the decoupled-walk stage (ops/colwalk.py): a redo is
rare tail work serialized behind the chunk it repairs — there is no
following forward dispatch to hide its walk behind, so decoupling it
would add a dispatch boundary for zero overlap.
"""

from __future__ import annotations

import os
from racon_tpu.utils import envspec
from typing import List, Optional, Tuple

import numpy as np

REDO_ENV = "RACON_TPU_REDO"


def redo_enabled() -> bool:
    """The wide-band device redo is on unless RACON_TPU_REDO=0 (the
    host consensus redo is the fallback either way — off just means
    every flagged window takes it)."""
    return envspec.read(REDO_ENV) not in ("0", "false")


def _widen(plan) -> None:
    """Widen a redo ChunkPlan's band in place: 2x the first-pass width,
    full-width past the LA - 128 ceiling (a band that wide would not
    beat the full kernel, and full width cannot fail the certificate)."""
    if plan.band_w:
        w2 = 2 * plan.band_w
        plan.band_w = w2 if w2 + 128 <= plan.LA else 0


def device_redo(windows: List, *, match: int, mismatch: int, gap: int,
                ins_scale, rounds: int, mesh=None, jobs_cap: int = 2048,
                stats: Optional[dict] = None, log=None
                ) -> Tuple[List[Tuple[object, bytes, np.ndarray]], List]:
    """Re-run flagged windows through a wide-band device pass.

    Returns ``(resolved, remaining)``: ``resolved`` is a list of
    (window, consensus codes bytes, coverage array) the caller applies;
    ``remaining`` the windows that must take the host path (still
    flagged after the wide pass, over the element budget even at the
    minimum chunk, or a retry-exhausted dispatch).
    """
    from racon_tpu.obs.trace import get_tracer
    from racon_tpu.ops.device_poa import (ChunkPlan, LA_GROW,
                                          MAX_DIR_ELEMS, collect_chunk,
                                          dispatch_chunk)
    from racon_tpu.resilience.retry import RetryExhausted

    tracer = get_tracer()
    ndp = mesh.shape["dp"] if mesh is not None else 1
    resolved: List[Tuple[object, bytes, np.ndarray]] = []
    remaining: List = []

    # Redo sets are small (a handful of windows per run at realistic
    # noise), so chunking stays simple: greedy groups under the job cap,
    # each its own plan — the widened geometry is a fresh executable
    # anyway, and sharing the first pass's caps would defeat the point.
    groups: List[List] = []
    cur: List = []
    jobs = 0
    for w in windows:
        if cur and jobs + w.n_layers > jobs_cap:
            groups.append(cur)
            cur, jobs = [], 0
        cur.append(w)
        jobs += w.n_layers
    if cur:
        groups.append(cur)

    for k, ws in enumerate(groups):
        plan = ChunkPlan(ws, la_grow=4 * LA_GROW, n_shards=ndp)
        _widen(plan)
        cols = plan.band_w if plan.band_w else plan.LA
        if plan.B // ndp * plan.Lq * cols > MAX_DIR_ELEMS:
            # The widened geometry overflows the flat-index budget even
            # for this (already minimal) group: host path, not a
            # silently narrower redo.
            remaining.extend(ws)
            continue
        try:
            with tracer.span("chunk", f"redo{k}", windows=len(ws),
                             lanes=plan.B, jobs=plan.n_jobs):
                packed = dispatch_chunk(
                    plan, match=match, mismatch=mismatch, gap=gap,
                    ins_scale=ins_scale, rounds=rounds, stats=stats,
                    mesh=mesh)
                codes, covs = collect_chunk(plan, packed, stats=stats)
        except RetryExhausted:
            remaining.extend(ws)
            continue
        for w, c, cv in zip(ws, codes, covs):
            if c is None:
                remaining.append(w)
            else:
                resolved.append((w, c, cv))
    return resolved, remaining
