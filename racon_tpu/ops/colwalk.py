"""Column-walk traceback: per-anchor-position vote channels in one pass.

The legacy traceback (flat.fw_traceback / band_kernel.fw_traceback_band)
walks the alignment op by op — Lq + LA dependent steps, each paying an
XLA gather dispatch — and hands a [B, steps] op string to extract_votes,
which then needs a flip, two full cumsums, a counting kernel and stacked
gathers to re-key ops by target column (PROFILE.md round-5 measurements:
~150 ms/round of the ~380 ms total, all of it XLA gather/cumsum
dispatch overhead, not arithmetic).

This walk exploits the block structure of a global alignment: in forward
order the ops partition into blocks ``[UP run at gap j][DIAG/LEFT
consuming column j]``. The forward kernels pack, per DP cell,

    byte = dir | consumer_dir << 2 | up_run << 4

where ``up_run`` is the (saturating) length of the consecutive-UP chain
ending at the cell and ``consumer_dir`` is the direction of the first
non-UP cell above that chain — both propagate down the chain inside the
forward kernel for a few extra vector ops per row. One packed-byte read
per anchor position then undoes a whole block.

The scan runs directly on the anchor-position grid p = 0..LA+1
(``reverse=True`` so ys land at their p rows with no flip): each lane
activates while j = p - t_off is inside [0, lt] and undoes gap j plus
the consumer of column j-1 in that step. Emissions are therefore already
keyed by anchor position — extract_votes_cols consumes them with zero
re-keying gathers.

Exactness: ``up_run`` saturates at U_SAT (= device_merge.K_INS + 1), so
a saturated counter exactly marks insertion runs longer than the K_INS
pileup slots the device merge keeps. Such runs are rare on polishing
windows (a run of length r costs r*|gap| against the anchor), and
correctness does not rest on that: saturated lanes raise a sticky flag
and their windows are re-polished on the unbounded host path (the same
redo route as the band escape bound).
``consumer_dir`` propagates unsaturated, and a chain that reaches row 0
stores LEFT — exactly the i==0 forced-LEFT walk of the legacy traceback
(top-row deletions, reference edlib semantics at src/overlap.cpp:198).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from racon_tpu.ops.cigar import DIAG, UP, LEFT  # noqa: F401 (UP: doc)
from racon_tpu.ops.flat import PAD_OP, U_SAT


def chain_len(LA: int, k: int) -> int:
    """Serialized dependent-gather count of the column walk at anchor
    padding LA and walk depth k (1 = single-step, 2 = dual-column nxt
    plane, 4 = quad-column nxt + nxt2 planes): ceil((LA + 2) / k)
    positions-per-gather groups over the LA + 2 anchor positions. This
    is the walk's HBM latency chain — the quantity the nxt planes
    exist to divide (PROFILE.md rounds 5/8; bench ships it as the
    ``walk_chain_len`` extra)."""
    if k not in (1, 2, 4):
        raise ValueError("[racon_tpu::colwalk] walk depth must be 1/2/4")
    return -(-(int(LA) + 2) // int(k))


def col_walk(cells, lq, lt, klo, t_off, *, LA: int, layout: str,
             nxt=None, nxt2=None, tile_klo=None, tile_len: int = 0,
             emit=None):
    """Walk packed cells over the anchor-position grid.

    Args:
      cells: uint8 packed-cell tensor from a forward kernel.
      lq, lt: int32[B] per-lane query / target lengths.
      klo: int32[B] band origin (band layouts) or None (flat / when
        ``tile_klo`` supplies per-tile origins).
      t_off: int32[B] anchor offset of each lane's target slice.
      LA: static anchor padding length; the scan runs LA + 2 steps.
      layout: "band_t" [Lq, W, B] (Pallas band), "band" [Lq, B, W]
        (XLA band twin), "flat" [Lq, B, Lt] (both flat kernels).
      nxt: optional matching uint8 tensor of predecessor metadata
        (band kernels' second output plane): the nxt byte of cell
        (i, j) packs the (up_run << 2 | consumer_dir) of the cell the
        walk visits next after undoing (i, j)'s block. When given, the
        walk undoes TWO anchor positions per dependent gather — the
        scan's latency chain (serialized per-column HBM gathers,
        PROFILE.md round 5's top remaining cost) halves. Bit-identical
        to the single-column walk for every lane the exactness
        certificates admit; flagged lanes (saturation / escape bound)
        may emit differently but are re-polished on the redo path in
        both modes (their ``sat``/escape flags themselves are
        identical).
      nxt2: optional matching uint16 tensor of deep predecessor
        metadata (band kernels' ``nxt_k=4`` plane): low byte packs hop
        2's ``(up_run << 2 | consumer_dir)``, high byte hop 3's. With
        both planes the walk undoes FOUR anchor positions per dependent
        gather (the nxt/nxt2 reads share the cells gather's index, so
        they ride the same dependent step). Requires ``nxt``.
      tile_klo: optional int32[n_tiles, B] per-TILE band origins from
        the tiled ultralong forward (ops/ovl_align.py): stored row r
        belongs to tile r // tile_len and its band slots map to target
        columns through THAT tile's origin. The lookup is an extra
        independent gather per position — it rides the same dependent
        step as the cells gather, so the dual-column latency chain is
        unchanged. Requires ``tile_len`` > 0; ``klo`` is ignored.
      emit: emission dtype of the returned channels (default int16 — the
        consensus path's pinned layout). The tiled overlap path passes
        int32: qstart/qi_c hold absolute query indices, which overflow
        int16 past 32 kb. (The jax 0.9 reverse-scan miscompile below is
        specific to TUPLES of narrow-dtype ys; a single stacked ys is
        safe at either width.)

    Returns dict of anchor-indexed arrays (all [B, LA+2] of ``emit``
    dtype except ``sat`` bool[B]); row p describes the walk step at
    j = p - t_off:
      ins_len[p] — insertion-run length at gap j
      qstart[p]  — query index of the first inserted base at gap j
      op_c[p]    — direction consuming column j - 1 (PAD_OP at j == 0)
      qi_c[p]    — exclusive query-consumed count of that consumer
      sat        — True where a saturated up_run made the walk inexact;
                   the caller must re-polish those lanes' windows on the
                   host path.
    """
    if layout == "band_t":
        Lq, W, B = cells.shape
    elif layout == "band":
        Lq, B, W = cells.shape
    else:
        Lq, B, W = cells.shape           # W = Lt for flat layouts
    if nxt2 is not None and nxt is None:
        raise ValueError("[racon_tpu::colwalk] nxt2 requires nxt")
    c1 = cells.reshape(-1)
    n1 = None if nxt is None else nxt.reshape(-1)
    n2_1 = None if nxt2 is None else nxt2.reshape(-1)
    lane = jnp.arange(B, dtype=jnp.int32)
    lt = lt.astype(jnp.int32)
    lq = lq.astype(jnp.int32)
    t_off = t_off.astype(jnp.int32)
    if emit is None:
        emit = jnp.int16
    if tile_klo is not None:
        if tile_len <= 0:
            raise ValueError("[racon_tpu::colwalk] tile_klo needs tile_len")
        tk1 = tile_klo.astype(jnp.int32).reshape(-1)
        n_tiles = tile_klo.shape[0]

    def cell_idx(i, jc):
        # Flat index of cell (i, jc)'s packed byte: row i-1 of the
        # stored tensor (row 0 of the DP matrix has no stored cells).
        r = jnp.maximum(i - 1, 0)
        if layout == "flat":
            col = jnp.maximum(jc - 1, 0)
            return r * (B * W) + lane * W + col
        if tile_klo is None:
            kl = klo
        else:
            tl = jnp.clip(r // tile_len, 0, n_tiles - 1)
            kl = jnp.take(tk1, tl * B + lane)
        x = jnp.clip(jc - i - kl, 0, W - 1)
        if layout == "band_t":
            return r * (B * W) + x * B + lane
        return r * (B * W) + lane * W + x

    def undo(i, sat, p, u_raw, cdir_raw):
        # Undo one anchor position given the (up_run, consumer_dir) pair
        # of cell (i, j) — however it was fetched (direct gather, or the
        # nxt plane of the PREVIOUS position's gather in dual mode).
        j = p - t_off
        active = (j >= 0) & (j <= lt)
        jc = jnp.clip(j, 0, lt)
        readable = active & (i >= 1) & (jc >= 1)
        u = jnp.where(readable, u_raw, 0)
        cdir = jnp.where(readable, cdir_raw, LEFT)
        newsat = readable & (u == U_SAT)
        is_j0 = active & (j == 0)
        # Gap j: the whole UP run in one step; at j == 0 every remaining
        # query base is a leading insertion (legacy walk's j==0 forcing).
        # That run is exact (no cell read) but extract_votes_cols' pileup
        # spans only U_SAT - 1 = K_INS columns, so leading runs longer
        # than that take the same redo route as saturated cells.
        newsat = newsat | (is_j0 & (i > U_SAT - 1))
        u_eff = jnp.where(is_j0, i, u)
        top = i - u_eff
        cons = jnp.where(top <= 0, LEFT, cdir)
        cons = jnp.where(is_j0, PAD_OP, cons)
        qi = top - jnp.where(cons == DIAG, 1, 0)
        i_next = jnp.where(active, jnp.where(is_j0, 0, qi), i)
        out = jnp.stack([u_eff, top, cons, qi], axis=-1).astype(emit)
        return i_next, sat | newsat, out

    def substep(i, sat, p):
        j = p - t_off
        jc = jnp.clip(j, 0, lt)
        pv = jnp.take(c1, cell_idx(i, jc)).astype(jnp.int32)
        return undo(i, sat, p, pv >> 4, (pv >> 2) & 3)

    def dual_substep(i, sat, p_hi):
        # Positions p_hi and p_hi - 1 off ONE dependent gather: the
        # cells byte undoes p_hi as usual, and the nxt byte fetched at
        # the SAME index carries the (u, cdir) the p_hi - 1 step needs
        # (by the nxt contract it describes cell (i_mid, j - 1)). The
        # one exception is the entry edge: while j_hi > lt the hi step
        # is inactive and the clipped gather already read cell
        # (i, lt) — exactly the byte the lo step's own gather would
        # fetch — so the lo step unpacks the CELLS byte there instead.
        j = p_hi - t_off
        active_hi = (j >= 0) & (j <= lt)
        jc = jnp.clip(j, 0, lt)
        idx = cell_idx(i, jc)
        pv = jnp.take(c1, idx).astype(jnp.int32)
        nv = jnp.take(n1, idx).astype(jnp.int32)
        i, sat, out_hi = undo(i, sat, p_hi, pv >> 4, (pv >> 2) & 3)
        u_lo = jnp.where(active_hi, nv >> 2, pv >> 4)
        c_lo = jnp.where(active_hi, nv & 3, (pv >> 2) & 3)
        i, sat, out_lo = undo(i, sat, p_hi - 1, u_lo, c_lo)
        return i, sat, out_hi, out_lo

    def quad_substep(i, sat, p_hi):
        # Positions p_hi .. p_hi - 3 off ONE dependent gather: the
        # cells/nxt/nxt2 bytes at a single index give the gathered
        # cell's own (u, cdir) plus hops 1-3 of its predecessor chain.
        # Entry edge, generalized from dual_substep: position m (j_m =
        # j_hi - m) is inactive while j_m > lt, so the FIRST active
        # position is a = clip(j_hi - lt, 0, 3), the clipped gather
        # already read cell (i, lt) — the byte position a's own gather
        # would fetch — and position m > a needs hop m - a. Positions
        # m < a are inactive (undo masks them; hop choice is
        # don't-care), and once active the window stays active within
        # the quad until j < 0 / the j == 0 finisher, both of which
        # undo() forces without reading the hop data.
        j = p_hi - t_off
        jc = jnp.clip(j, 0, lt)
        idx = cell_idx(i, jc)
        pv = jnp.take(c1, idx).astype(jnp.int32)
        nv = jnp.take(n1, idx).astype(jnp.int32)
        n2v = jnp.take(n2_1, idx).astype(jnp.int32)
        hops_u = (pv >> 4, nv >> 2, (n2v >> 2) & 0xF, (n2v >> 10) & 0xF)
        hops_c = ((pv >> 2) & 3, nv & 3, n2v & 3, (n2v >> 8) & 3)
        a = jnp.clip(j - lt, 0, 3)
        outs = []
        for m in range(4):
            if m == 0:
                u_m, c_m = hops_u[0], hops_c[0]
            else:
                hop = jnp.clip(m - a, 0, 3)
                u_m, c_m = hops_u[min(m, 3)], hops_c[min(m, 3)]
                for hh in range(min(m, 3) - 1, -1, -1):
                    u_m = jnp.where(hop == hh, hops_u[hh], u_m)
                    c_m = jnp.where(hop == hh, hops_c[hh], c_m)
            i, sat, out = undo(i, sat, p_hi - m, u_m, c_m)
            outs.append(out)
        return i, sat, outs

    UNROLL = 4

    def step(carry, p0):
        # Several columns per scan iteration: the walk is a serialized
        # chain of tiny per-column ops whose cost is per-iteration
        # dispatch overhead, not arithmetic — unrolling divides the
        # iteration count (PROFILE.md round 5). With the nxt plane, each
        # iteration is UNROLL // 2 dependent gathers instead of UNROLL;
        # with nxt2 as well, ONE dependent gather covers the whole
        # iteration (PROFILE.md round 8).
        i, sat = carry
        outs = []
        if nxt is None:
            for k in reversed(range(UNROLL)):
                i, sat, out = substep(i, sat, p0 + k)
                outs.append(out)
        elif nxt2 is None:
            for k in (UNROLL - 1, UNROLL - 3):
                i, sat, hi, lo = dual_substep(i, sat, p0 + k)
                outs.append(hi)
                outs.append(lo)
        else:
            i, sat, outs = quad_substep(i, sat, p0 + UNROLL - 1)
        # ONE stacked int16 ys, not a tuple of int16 arrays: a reverse
        # scan emitting a TUPLE of int16 ys miscompiles under XLA CPU jit
        # in jax 0.9 (wrong values vs disable_jit; int32 tuples and
        # stacked int16 both compile correctly — verified empirically,
        # see tests/test_colwalk.py which would catch a recurrence).
        return (i, sat), jnp.stack(outs[::-1], axis=0)

    # Iteration count rounds up; an uneven grid's extra positions
    # p > LA + 1 are provably inactive (t_off + lt <= LA for every lane)
    # and are sliced off below.
    T = (LA + 1 + UNROLL) // UNROLL
    ps = jnp.arange(0, UNROLL * T, UNROLL, dtype=jnp.int32)
    (_, sat), ys = jax.lax.scan(
        step, (lq, jnp.zeros(lq.shape, bool)), ps, reverse=True)
    # ys: [T, U, B, 4] with ys[t, k] describing p = U*t + k.
    ch = jnp.transpose(ys.reshape(-1, B, 4), (1, 0, 2))[:, :LA + 2]
    return {"ins_len": ch[..., 0], "qstart": ch[..., 1],
            "op_c": ch[..., 2], "qi_c": ch[..., 3], "sat": sat}


@functools.partial(
    jax.jit,
    static_argnames=("ins_scale", "Lq", "n_win", "LA", "pallas",
                     "band_w", "rounds"))
def walk_chunk_packed(job_buf, dirs, nxt, nxt2, lt, t_off, klo, esc0,
                      bb, bbw, alen, begin, end, ovf, rexec0, *,
                      ins_scale, Lq, n_win, LA, pallas, band_w, rounds):
    """The standalone walk half of a chunk: consume device_chunk_fwd's
    plane/state tuple and finish the chunk's FINAL round — column walk,
    vote merge, consensus assembly — producing the exact packed output
    buffer device_chunk_packed would have (collect_chunk unpacks both).

    Bit-identity to the fused program is by construction, not by
    tolerance: this composes the SAME traced bodies (_lane_walk,
    _merge_round, _pack_body from ops/device_poa.py) the fused program
    inlines, on the planes the shared _lane_fwd produced; the round
    state (bb/bbw/alen/begin/end/ovf) crosses the program boundary as
    live device arrays, never leaving the device. ``match/mismatch/gap``
    are absent on purpose — the forward already folded the scoring
    bound into ``esc0``.

    Compiled per shape bucket like every chunk executable; ``ins_scale``
    here is the FINAL round's scale (a scalar static, not the tuple).
    """
    # Lazy import: device_poa imports this module's col_walk/chain_len
    # inside functions only, so the cycle never materializes at import.
    from racon_tpu.ops.device_poa import (_lane_walk, _merge_round,
                                          _pack_body, _unpack_job)

    # Round-invariant job fields come back out of the SAME byte layout
    # the forward dispatch shipped; the packed begin/end are the round-0
    # spans and are superseded by the carried state's begin/end.
    q, qw8, _b0, _e0, lq, win, w_read = _unpack_job(job_buf, Lq)
    votes, esc_w = _lane_walk(dirs, nxt, nxt2, lt, t_off, klo, esc0,
                              q, qw8, lq, w_read, LA=LA, pallas=pallas,
                              band_w=band_w)
    new_bb, _bbw, new_alen, _nb, _ne, cov, ovf, _conv = _merge_round(
        votes, esc_w, bb, bbw, alen, begin, end, win, ovf,
        ins_scale=ins_scale, n_win=n_win, LA=LA, detect=False,
        axis_name=None)
    return _pack_body(new_bb[:-1], cov, new_alen[:-1], ovf,
                      rexec0 + 1, jnp.int32(rounds))


def dispatch_walk(plan, fwd_out, meta):
    """Ship the decoupled walk for a chunk whose forward half was
    dispatched by ops/device_poa.py::dispatch_chunk_fwd. Returns the
    packed output buffer (still in flight) for collect_chunk.

    Its own fault/retry envelope: site ``dispatch/walk`` with a
    geometry deadline over ONE round's cells at the final band width —
    the walk's serialized gather chain is bounded by that plane, not by
    the whole chunk's round budget.
    """
    from racon_tpu.obs.metrics import registry as obs_registry
    from racon_tpu.ops.budget import dispatch_deadline_s
    from racon_tpu.ops.device_poa import round_band_width
    from racon_tpu.resilience.retry import call as retry_call

    band_w = meta["band_w"]
    rounds = meta["rounds"]
    W = round_band_width(band_w, rounds - 1) if band_w else plan.LA
    sc = meta["ins_scale"]
    scales = sc if isinstance(sc, tuple) else (sc,) * rounds
    packed = retry_call(
        "dispatch/walk", walk_chunk_packed, meta["job_buf"], *fwd_out,
        ins_scale=scales[-1], Lq=plan.Lq, n_win=plan.n_win, LA=plan.LA,
        pallas=meta["pallas"], band_w=band_w, rounds=rounds,
        deadline_s=dispatch_deadline_s(plan.B * plan.Lq * W))
    obs_registry().inc("device_dispatches")
    return packed
