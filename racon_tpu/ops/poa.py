"""Batched POA consensus engine — the spoa replacement.

The reference runs one spoa partial-order-alignment graph per window on a
CPU thread: each layer is NW-aligned against the *evolving* graph and
merged into it, then consensus is the heaviest bundle through the DAG
(reference: src/window.cpp:61-137; engine creation src/polisher.cpp:151-155).
A sequence-vs-DAG DP with data-dependent predecessor sets serializes
horribly on a TPU, so this engine restructures the computation:

1. **Anchor to the backbone.** Every layer is globally aligned to its
   window-relative backbone slice (the reference's subgraph range,
   src/window.cpp:92-97). All alignments share the same static target, so
   they batch perfectly over (window, layer) pairs — the entire hot loop
   becomes one ``nw_align_batch`` call on device (or one native FFI call
   on host), instead of C sequential graph alignments per window.
2. **Merge columns on host.** Because all reads share backbone
   coordinates, spoa's graph degenerates into a deterministic structure:
   at most one node per (position, base) — mismatches merge by base
   exactly as spoa's aligned-node rings do — plus insertion chains keyed
   by (gap, inserted sequence), which merges reads carrying an identical
   insertion at an identical spot (deterministic because all reads align
   to the same target with the same tie-breaking).
3. **Consensus by weighted column vote**: per position the heaviest of
   {A, C, G, T, N, deletion}; per gap the inserted segments from all
   reads form a left-justified mini-pileup whose columns are emitted
   while the weight of reads extending the insertion beats the weight of
   reads that have stopped (crossed directly or ran out of inserted
   bases). This is the heaviest path through the merged DAG (the DAG is
   chain-shaped, so the global heaviest path decomposes per column).

Weights follow spoa's: per-base Phred (quality - 33) when quality exists,
1 otherwise; the backbone caries its quality or the reference's dummy
``'!'`` (= weight 0, src/polisher.cpp:141, 383). Per-base consensus
coverage (number of sequences through the chosen node, backbone included)
feeds the kTGS trim in ``Window.apply_consensus``.
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Tuple

import numpy as np

from racon_tpu.models.window import Window, sorted_layer_order, \
    window_arrays
from racon_tpu.ops.encode import encode_bases, decode_bases, ALPHABET
from racon_tpu.ops.cigar import DIAG, UP, LEFT

# Tie-break epsilon, shared by the host (f64) and device (f32) merges so
# they stay bit-comparable. 1e-3 survives f32 accumulation at realistic
# weight sums (exact ties between integer-weight votes are the common
# case); read-mean and crossing weights are fractional, so margins below
# 1e-3 can in principle flip — accepted as tie-break noise (golden
# edit-distance bounds in tests/test_polisher.py hold).
_EPS = 1e-3


class _Job:
    """One layer-vs-backbone-slice alignment job."""
    __slots__ = ("win", "q", "w", "w_read", "t", "t_off", "ops")

    def __init__(self, win: int, q: np.ndarray, w: np.ndarray,
                 t: np.ndarray, t_off: int):
        self.win = win
        self.q = q                      # uint8 base codes (query layer)
        self.w = w                      # float32 per-base weights
        # float64 mean so the native/C++ and device engines can reproduce
        # it exactly (float32 pairwise mean is numpy-internal).
        self.w_read = float(w.astype(np.float64).mean()) if len(w) else 0.0
        self.t = t                      # uint8 base codes (backbone slice)
        self.t_off = t_off              # backbone offset of the slice
        self.ops: Optional[np.ndarray] = None

    @property
    def t_len(self) -> int:
        return len(self.t)


def _round_up(n: int, mult: int = 128) -> int:
    return ((max(n, 1) + mult - 1) // mult) * mult


class _DeviceSlicePlan:
    """One consensus slice's device decomposition: balanced chunk groups
    sharing run-level caps, plus the windows that must take the host
    path (jumbo/wide geometry, or everything when ``overflow_msg`` is
    set). Produced by PoaEngine._plan_device_slice and consumed both by
    the serial path and the streaming pipeline, so the two can never
    disagree on chunk composition."""
    __slots__ = ("groups", "host", "lq_cap", "la_cap", "band_cap",
                 "n_shards", "overflow_msg")

    def __init__(self, lq_cap: int, la_cap: int, band_cap: Optional[int],
                 n_shards: int):
        self.groups: List[List[Window]] = []
        self.host: List[Window] = []
        self.lq_cap = lq_cap
        self.la_cap = la_cap
        self.band_cap = band_cap
        self.n_shards = n_shards
        self.overflow_msg: Optional[str] = None


class PoaEngine:
    """Batched consensus over windows.

    backend:
      "jax"    — device NW kernel (TPU; also runs on CPU via XLA)
      "native" — C++ banded NW through ctypes (fast host path)
      "auto"   — "jax" when an accelerator is present, else "native"
    """

    def __init__(self, match: int = 5, mismatch: int = -4, gap: int = -8,
                 backend: str = "auto", device_batch: int = 4096,
                 refine_rounds: int = 3, ins_scale: float = 0.2,
                 ins_scale_final: Optional[float] = 0.6, mesh=None,
                 log=sys.stderr, threads: int = 1):
        if gap >= 0:
            raise ValueError(
                "[racon_tpu::PoaEngine] error: gap penalty must be negative!")
        self.match, self.mismatch, self.gap = match, mismatch, gap
        self.device_batch = device_batch
        # Refinement replays spoa's evolving-graph advantage in batched
        # form: the first vote's consensus becomes the anchor for a second
        # alignment round, so insertions scattered across adjacent gaps by
        # backbone errors consolidate onto real columns.
        self.refine_rounds = refine_rounds
        # Insertion-vs-crossing vote scale (<1 counters the systematic
        # deficit insertion columns suffer from alignment scatter) for
        # all refinement rounds but the last. The admit-generously /
        # prune-strictly structure replaces round 4's per-weight-regime
        # calibration (a fitted ins_scale_unit): scattered insertion
        # candidates need a low bar to get INTO the anchor, after which
        # later rounds re-judge them as regular columns (deletion vs
        # base weight, no scale involved) — so the LAST round's scale
        # (ins_scale_final) only gates leftover scatter noise and can be
        # strict. One setting serves both weight regimes: 0.2/0.6
        # improves every lambda acceptance config over the old per-
        # regime pair (PAF+FASTQ 1288->1211, PAF+FASTA 1626->1578,
        # SAM+FASTQ 1305->1252, SAM+FASTA 1973->1913) and was validated
        # on held-out configs it was not chosen on (w=1000 1235 vs
        # golden 1289; scores (1,-1,-1) 1158 vs golden 1321).
        self.ins_scale = ins_scale
        self.ins_scale_final = ins_scale_final
        self.log = log
        if backend == "auto":
            backend = "jax" if _accelerator_present() else "native"
        self.backend = backend
        # Optional jax.sharding.Mesh: the device engine shards every
        # chunk's job axis over the mesh's "dp" devices
        # (racon_tpu/ops/device_poa.py::device_round_sharded); with an
        # "sp" axis, over-budget alignment jobs additionally route
        # through the sequence-parallel NW (see _align).
        self.mesh = mesh
        # Single-chip DP-matrix cell budget: above this, a job's dirs
        # tensor would not fit the minimum device chunk (MAX_DIR_ELEMS
        # at the 128-job bucket, racon_tpu/ops/device_poa.py) and the
        # job routes to sp when an "sp" mesh axis exists. Overridable
        # for tests.
        from racon_tpu.ops.device_poa import MAX_DIR_ELEMS
        self.sp_cell_budget = MAX_DIR_ELEMS // 128
        # OS threads for the native host aligner (reference -t).
        self.threads = threads
        # Optional dict: run_chunk accumulates phase wall times into it
        # ("h2d"/"compute"/"d2h"/"chunks"); None = no timing syncs.
        self.stats = None
        # Filled by the device path when the convergence scheduler runs
        # (racon_tpu/sched/): per-round freeze histogram, survivor
        # fractions, repack overhead. Accumulates across
        # consensus_windows calls of one run; the polisher logs it and
        # bench.py serializes it into extras.
        self.sched_telemetry = None
        self._native = None

    # ------------------------------------------------------------ public API

    def consensus_windows(self, windows: List[Window]) -> int:
        """Fill ``consensus`` for every window; returns #polished.

        Windows with fewer than backbone+2 sequences keep their backbone
        and stay unpolished (src/window.cpp:63-66).
        """
        active: List[Window] = []
        for w in windows:
            if w.n_layers < 2:
                w.set_backbone_consensus()
            else:
                active.append(w)
        if not active:
            return 0
        # backend "jax": device-resident engine; with a mesh, chunks shard
        # their job axis over the mesh's "dp" devices
        # (device_poa.device_round_sharded — one psum per round).
        from racon_tpu.obs.metrics import record_windows
        if self.backend == "jax":
            dev, host, lq_max, la_max = self._partition_device(active)
            n = 0
            if dev:
                n += self._consensus_device(dev, lq_max, la_max)
            if host:
                n += self._consensus_host(host, force_native=True)
            record_windows(n)
            return n
        n = self._consensus_host(active)
        record_windows(n)
        return n

    def _partition_device(self, windows: List[Window]):
        """Split windows into device-engine vs host-path sets.

        The full-width device kernel computes exact NW for any geometry,
        so a window falls back to the host path only when (a) it alone
        overflows the chunk's dirs-element cap, or (b) it is a jumbo
        outlier (>4x the run's median layer/backbone length) that would
        inflate the shared run-level padding caps for every chunk.

        Returns (dev, host, dev_lq_max, dev_la_max) — the maxima feed
        run_caps without a second scan over all layer lists.
        """
        from racon_tpu.ops.device_poa import dir_elems, MAX_DIR_ELEMS
        lqs = np.array([max(len(d) for d in w.layer_data)
                        for w in windows])
        las = np.array([len(w.backbone) for w in windows])
        lq_lim = 4 * max(float(np.median(lqs)), 1.0)
        la_lim = 4 * max(float(np.median(las)), 1.0)
        dev, host = [], []
        lq_max = la_max = 1
        for w, lq, la in zip(windows, lqs, las):
            if (dir_elems(w.n_layers, int(lq), int(la)) > MAX_DIR_ELEMS
                    or lq > lq_lim or la > la_lim):
                host.append(w)
            else:
                dev.append(w)
                lq_max = max(lq_max, int(lq))
                la_max = max(la_max, int(la))
        return dev, host, lq_max, la_max

    def _plan_device_slice(self, active: List[Window], lq_max: int,
                           la_max: int) -> "_DeviceSlicePlan":
        """Decompose one slice of device windows into balanced chunk
        groups plus a host-fallback set — THE single decomposition both
        the serial path below and the streaming pipeline
        (racon_tpu/pipeline/streaming.py) run, so the two produce
        identical chunks (and therefore identical output) by
        construction."""
        from racon_tpu.ops.device_poa import (run_caps, _bucket_b,
                                              MAX_DIR_ELEMS)
        # One (Lq, LA) cap pair for the whole run (cap-history reuse):
        # every chunk shares a single compiled device_round executable
        # instead of paying a multi-second XLA compile per shape.
        lq_cap, la_cap = run_caps(lq_max, la_max)
        # The dirs tensor that actually bounds chunk size is banded
        # (B x Lq x W) whenever every chunk will band: size chunks by
        # the run-level band width then, not the full LA — about 2x more
        # jobs per dispatch at w=500 geometry.
        from racon_tpu.utils import envspec as _envspec
        band_off = (_envspec.read("RACON_TPU_NO_BAND")
                    not in ("", "0", "false"))
        w_run = self._run_band_width(active, la_cap)
        dirs_cols = la_cap if (band_off or not w_run) else w_run
        jobs_cap = self.device_batch
        while jobs_cap > 128 and \
                _bucket_b(jobs_cap) * lq_cap * dirs_cols > MAX_DIR_ELEMS:
            jobs_cap //= 2
        n_shards = self.mesh.shape["dp"] if self.mesh is not None else 1
        sp = _DeviceSlicePlan(lq_cap, la_cap, w_run or None, n_shards)
        if _bucket_b(jobs_cap) * lq_cap * dirs_cols > MAX_DIR_ELEMS:
            # Even a minimum-bucket chunk overflows the int32 flat-index
            # range at these caps (pathological mixed geometry): host path.
            sp.host = list(active)
            sp.overflow_msg = (
                f"[racon_tpu::PoaEngine] run geometry (Lq={lq_cap}, "
                f"LA={la_cap}) overflows the device index budget even "
                f"at the minimum chunk size; polishing {len(active)} "
                "window(s) on the host path")
            return sp
        # Windows too wide for any chunk at these caps take the host path
        # ("not ws" below would otherwise admit them into an over-cap
        # bucket, wrapping the traceback's int32 flat index).
        sp.host = [w for w in active if w.n_layers > jobs_cap]
        if sp.host:
            active = [w for w in active if w.n_layers <= jobs_cap]
        # Balance jobs across the minimum number of chunks: equal-size
        # chunks land in one B bucket (one compiled executable) where a
        # greedy full-then-remainder split would produce two.
        total_jobs = sum(w.n_layers for w in active)
        n_chunks = max(1, -(-total_jobs // jobs_cap))
        target = -(-total_jobs // n_chunks)
        i = 0
        while i < len(active):
            ws: List[Window] = []
            jobs = 0
            while i < len(active) and \
                    (not ws or jobs + active[i].n_layers <= target):
                ws.append(active[i])
                jobs += active[i].n_layers
                i += 1
            sp.groups.append(ws)
        return sp

    def _make_chunk_plan(self, sp: "_DeviceSlicePlan", ws: List[Window]):
        from racon_tpu.ops.device_poa import ChunkPlan
        return ChunkPlan(ws, lq_cap=sp.lq_cap, la_cap=sp.la_cap,
                         n_shards=sp.n_shards, band_cap=sp.band_cap)

    def _apply_group(self, ws: List[Window], codes, covs,
                     trunc: List[Window]) -> None:
        """Apply one collected chunk's consensus; windows whose result
        overflowed the padded anchor width collect into ``trunc``."""
        for w, c, cv in zip(ws, codes, covs):
            if c is None:
                # Consensus outgrew the chunk's padded anchor width
                # (sticky device ovf flag): the device result is
                # truncated; the host path is unbounded.
                trunc.append(w)
                continue
            w.apply_consensus(
                decode_bases(np.frombuffer(c, dtype=np.uint8)), cv,
                log=self.log)

    def _redo_trunc(self, trunc: List[Window]) -> None:
        """Flagged windows (anchor overflow / escape failure /
        saturation) re-run through the on-device wide-band second pass
        (ops/redo.py: 4x anchor growth slack, 2x band width); whatever
        the wide pass cannot certify — the saturation class, or growth
        past even the widened slack — takes the unbounded host path, as
        every flagged window did before round 8 (RACON_TPU_REDO=0
        restores that behavior wholesale)."""
        if not trunc:
            return
        from racon_tpu.obs.metrics import record_redo
        from racon_tpu.ops.redo import device_redo, redo_enabled
        remaining = trunc
        if redo_enabled():
            print(f"[racon_tpu::PoaEngine] {len(trunc)} window(s) "
                  "flagged; re-polishing through the wide-band device "
                  "pass", file=self.log)
            resolved, remaining = device_redo(
                trunc, match=self.match, mismatch=self.mismatch,
                gap=self.gap,
                ins_scale=self._round_scales(self.refine_rounds + 1),
                rounds=self.refine_rounds + 1, mesh=self.mesh,
                jobs_cap=self.device_batch, stats=self.stats,
                log=self.log)
            for w, c, cv in resolved:
                w.apply_consensus(
                    decode_bases(np.frombuffer(c, dtype=np.uint8)), cv,
                    log=self.log)
        record_redo(len(trunc) - len(remaining), len(remaining))
        if remaining:
            print(f"[racon_tpu::PoaEngine] {len(remaining)} window(s) "
                  "unresolved by the wide-band pass; re-polishing on "
                  "the host path", file=self.log)
            self._consensus_host(remaining, force_native=True)

    def _degrade(self, ws: List[Window], exc) -> None:
        """Last-resort graceful degradation: a transfer/dispatch choke
        point exhausted its retry budget (resilience/retry.py), so this
        chunk's windows polish on the host path instead of aborting the
        run. The host and device paths are bit-identical by design, so
        degraded output stays correct — only slower."""
        from racon_tpu.obs.metrics import record_degraded
        print(f"[racon_tpu::PoaEngine] device path gave up at "
              f"{getattr(exc, 'site', '?')} after retries ({exc}); "
              f"polishing {len(ws)} window(s) on the host path",
              file=self.log)
        record_degraded(len(ws))
        self._consensus_host(ws, force_native=True)

    def _make_scheduler(self):
        """ConvergenceScheduler wired to this engine's (shared, run-
        accumulating) telemetry — one construction for the serial sched
        path and the streaming pipeline's compute stage."""
        from racon_tpu.sched import ConvergenceScheduler, SchedTelemetry
        rounds = self.refine_rounds + 1
        if self.sched_telemetry is None or \
                self.sched_telemetry.rounds != rounds:
            self.sched_telemetry = SchedTelemetry(rounds)
        return ConvergenceScheduler(
            match=self.match, mismatch=self.mismatch, gap=self.gap,
            scales=self._round_scales(rounds), mesh=self.mesh,
            telemetry=self.sched_telemetry)

    def _consensus_device(self, active: List[Window], lq_max: int,
                          la_max: int) -> int:
        """Device-resident path: all refinement rounds on chip, one h2d /
        one d2h per chunk (racon_tpu/ops/device_poa.py)."""
        from racon_tpu.ops.device_poa import dispatch_chunk, collect_chunk
        sp = self._plan_device_slice(active, lq_max, la_max)
        if sp.overflow_msg:
            print(sp.overflow_msg, file=self.log)
            return self._consensus_host(sp.host, force_native=True)
        n_wide = 0
        if sp.host:
            n_wide = self._consensus_host(sp.host, force_native=True)
        groups = sp.groups
        active = [w for g in groups for w in g]
        trunc: List[Window] = []

        def make_plan(ws: List[Window]):
            return self._make_chunk_plan(sp, ws)

        def apply(ws, codes, covs) -> None:
            self._apply_group(ws, codes, covs, trunc)

        from racon_tpu.obs.trace import get_tracer
        from racon_tpu.resilience.retry import RetryExhausted
        from racon_tpu.sched import sched_enabled
        tracer = get_tracer()
        if sched_enabled():
            # Convergence-aware path (racon_tpu/sched/): per-window
            # early exit with survivor repacking. Its per-round host
            # syncs preclude the fixed path's depth-2 dispatch pipeline,
            # so overlap comes from prefetching the NEXT chunk's h2d
            # (async device_put) before running the current rounds.
            sched = self._make_scheduler()

            def prefetch(ws: List[Window]):
                plan = make_plan(ws)
                try:
                    return plan, sched.put_chunk(plan)
                except RetryExhausted as exc:
                    self._degrade(ws, exc)
                    return None

            nxt = prefetch(groups[0]) if groups else None
            for k, ws in enumerate(groups):
                cur = nxt
                nxt = prefetch(groups[k + 1]) \
                    if k + 1 < len(groups) else None
                if cur is None:
                    continue        # degraded at prefetch
                cur_plan, cur_bufs = cur
                try:
                    with tracer.span("chunk", f"chunk{k}",
                                     windows=len(ws), lanes=cur_plan.B,
                                     jobs=cur_plan.n_jobs):
                        codes, covs = sched.run_chunk(
                            cur_plan, bufs=cur_bufs, stats=self.stats)
                except RetryExhausted as exc:
                    self._degrade(ws, exc)
                    continue
                apply(ws, codes, covs)
        else:
            # Fixed-round pipeline: chunk i+1's h2d + dispatch go out
            # while chunk i still computes (depth 2 bounds in-flight
            # HBM). Stats collection forces depth 0 (strictly
            # sequential) so every phase time stays attributable to its
            # chunk (the pack timestamp lives in the shared stats dict).
            depth = 0 if self.stats is not None else 2
            pending: List[tuple] = []

            def finish(entry) -> None:
                # Chunks pipeline (dispatch i+1 overlaps compute i), so
                # chunk spans are emitted retroactively at collect time:
                # they overlap as siblings instead of nesting falsely.
                ws, plan, packed, k, t_disp = entry
                import time as _time
                try:
                    codes, covs = collect_chunk(plan, packed,
                                                stats=self.stats)
                except RetryExhausted as exc:
                    self._degrade(ws, exc)
                    return
                tracer.emit("chunk", f"chunk{k}", t_disp,
                            _time.perf_counter() - t_disp,
                            windows=len(ws), lanes=plan.B,
                            jobs=plan.n_jobs)
                apply(ws, codes, covs)

            import time as _time
            for k, ws in enumerate(groups):
                t_disp = _time.perf_counter()
                plan = make_plan(ws)
                try:
                    packed = dispatch_chunk(
                        plan, match=self.match, mismatch=self.mismatch,
                        gap=self.gap,
                        ins_scale=self._round_scales(
                            self.refine_rounds + 1),
                        rounds=self.refine_rounds + 1, stats=self.stats,
                        mesh=self.mesh)
                except RetryExhausted as exc:
                    self._degrade(ws, exc)
                    continue
                pending.append((ws, plan, packed, k, t_disp))
                if len(pending) > depth:
                    finish(pending.pop(0))
            for entry in pending:
                finish(entry)
        self._redo_trunc(trunc)
        return len(active) + n_wide

    @staticmethod
    def _run_band_width(active: List[Window], la_cap: int) -> int:
        """Run-level band width (0 when banding will not engage): the
        same shared geometry ChunkPlan uses per chunk
        (device_poa.window_band_delta / band_width_for), evaluated over
        the whole run so chunk sizing can assume banded dirs."""
        from racon_tpu.ops.device_poa import (window_band_delta,
                                              band_width_for)
        W = band_width_for(max((window_band_delta(w) for w in active),
                               default=0))
        return W if W + 128 <= la_cap else 0

    def _consensus_host(self, active: List[Window],
                        force_native: bool = False) -> int:
        backend = self.backend
        if force_native:
            self.backend = "native"
        try:
            return self._consensus_host_impl(active)
        finally:
            self.backend = backend

    def _consensus_host_impl(self, active: List[Window]) -> int:
        # Per-window state: current anchor (codes, weights) and layer maps
        # from original window coordinates into the current anchor.
        layers: List[List[Tuple[np.ndarray, np.ndarray, int, int]]] = []
        anchors: List[Tuple[np.ndarray, np.ndarray]] = []
        spans: List[List[Tuple[int, int]]] = []
        for w in active:
            lays, bb, bb_w = window_arrays(w)
            layers.append([(codes, wts) for codes, wts, _, _ in lays])
            spans.append([(b, e) for _, _, b, e in lays])
            anchors.append((bb, bb_w))

        results = None
        scales = self._round_scales(self.refine_rounds + 1)
        for r in range(self.refine_rounds + 1):
            jobs: List[_Job] = []
            for wi in range(len(active)):
                jobs.extend(self._build_jobs(wi, anchors[wi][0],
                                             layers[wi], spans[wi]))
            self._align(jobs)
            results = self._merge_round(anchors, jobs, scales[r])
            # Next round anchors: the fresh consensus with neutral weights
            # (reads re-vote from scratch); spans mapped through the merge.
            new_anchors = []
            new_spans = []
            for wi, (cons, cov, map_b, map_e) in enumerate(results):
                new_anchors.append(
                    (cons, np.zeros(len(cons), dtype=np.float32)))
                sp = []
                for (b, e) in spans[wi]:
                    nb = int(map_b[b]) if b < len(map_b) else 0
                    ne = int(map_e[e]) if e < len(map_e) else len(cons) - 1
                    sp.append((nb, ne))
                new_spans.append(sp)
            anchors = new_anchors
            spans = new_spans

        for w, (cons, cov, _, _) in zip(active, results):
            w.apply_consensus(decode_bases(cons), cov, log=self.log)
        return len(active)

    # ------------------------------------------------------------- job build

    def _build_jobs(self, wi: int, bb: np.ndarray,
                    lst: List[Tuple[np.ndarray, np.ndarray]],
                    sp: List[Tuple[int, int]]) -> List[_Job]:
        L = len(bb)
        offset = int(0.01 * L)  # reference truncates to uint32
        jobs = []
        for (codes, wts), (begin, end) in zip(lst, sp):
            begin = max(0, min(begin, L - 1))
            end = max(begin, min(end, L - 1))
            # Full-span layers align to the whole backbone, partial layers
            # to the [begin, end] slice (src/window.cpp:82-98: uint32
            # offset = 0.01 * L, strict `end > L - offset`).
            if begin < offset and end > L - offset:
                jobs.append(_Job(wi, codes, wts, bb, 0))
            else:
                jobs.append(_Job(wi, codes, wts, bb[begin:end + 1], begin))
        return jobs

    # ------------------------------------------------------------- alignment

    def _align(self, jobs: List[_Job]) -> None:
        if not jobs:
            return
        # Long-window routing (SURVEY.md "long-context"): when the mesh
        # has an "sp" axis, jobs whose DP matrix exceeds a single chip's
        # dirs budget align via the sequence-parallel NW (target axis
        # sharded over "sp" chips, cross-chip traceback) instead of the
        # host fallback — the windows themselves stay in this host
        # merge, only their alignment scales out.
        if (self.mesh is not None and
                "sp" in getattr(self.mesh, "axis_names", ())):
            sp_jobs = [j for j in jobs
                       if len(j.q) * j.t_len > self.sp_cell_budget]
            if sp_jobs:
                self._align_sp(sp_jobs)
                jobs = [j for j in jobs if j.ops is None]
                if not jobs:
                    return
        if self.backend == "native":
            self._align_native(jobs)
        else:
            self._align_jax(jobs)

    @staticmethod
    def _pack_jobs(jobs: List[_Job], B: int):
        """Pad a job list into dense (q, t, lq, lt) batch arrays."""
        Lq = _round_up(max(len(j.q) for j in jobs))
        Lt = _round_up(max(j.t_len for j in jobs))
        q = np.zeros((B, Lq), np.uint8)
        t = np.zeros((B, Lt), np.uint8)
        lq = np.ones(B, np.int32)
        lt = np.ones(B, np.int32)
        for b, j in enumerate(jobs):
            lq[b] = len(j.q)
            lt[b] = j.t_len
            q[b, :lq[b]] = j.q
            t[b, :lt[b]] = j.t
        return q, t, lq, lt

    def _align_sp(self, jobs: List[_Job]) -> None:
        """Sequence-parallel alignment for over-budget jobs
        (racon_tpu/parallel/dispatch.py::sp_nw_align)."""
        from racon_tpu.parallel.dispatch import sp_nw_align
        q, t, lq, lt = self._pack_jobs(jobs, len(jobs))
        ops, n = sp_nw_align(self.mesh, q, t, lq, lt, match=self.match,
                             mismatch=self.mismatch, gap=self.gap)
        W = ops.shape[1]
        for b, j in enumerate(jobs):
            j.ops = ops[b, W - int(n[b]):]

    def _align_native(self, jobs: List[_Job]) -> None:
        from racon_tpu.native.aligner import NativeAligner
        if self._native is None:
            self._native = NativeAligner(self.match, self.mismatch,
                                         self.gap, threads=self.threads)
        pairs = [(j.q, j.t) for j in jobs]
        for j, ops in zip(jobs, self._native.align_batch(pairs)):
            j.ops = ops

    def _align_jax(self, jobs: List[_Job]) -> None:
        import jax.numpy as jnp
        from racon_tpu.ops.align import nw_align_batch
        # Bucket by (target, query) length so one long-target job does not
        # inflate the padded DP for a whole chunk of short slices.
        order = np.lexsort((np.asarray([len(j.q) for j in jobs]),
                            np.asarray([j.t_len for j in jobs])))
        bs = self.device_batch
        for s in range(0, len(order), bs):
            chunk = [jobs[i] for i in order[s:s + bs]]
            # Pad the batch dimension onto a coarse grid (512, 1024, 2048,
            # 3072, 4096) so chunks reuse a handful of compiled
            # executables per (Lq, Lt) bucket without paying full-batch
            # padding; padded rows are length-1 dummies.
            B = 512 if len(chunk) <= 512 else _round_up(len(chunk), 1024)
            q, t, lq, lt = self._pack_jobs(chunk, B)
            from racon_tpu.ops.align import nw_align_auto
            ops, n = nw_align_auto(
                q, t, lq, lt, match=self.match,
                mismatch=self.mismatch, gap=self.gap)
            ops = np.asarray(ops)
            n = np.asarray(n)
            W = ops.shape[1]
            for b, j in enumerate(chunk):
                j.ops = ops[b, W - int(n[b]):]

    # ----------------------------------------------------------------- merge

    def _round_scales(self, rounds: int) -> Tuple[float, ...]:
        """Per-round insertion-vote scales (see ins_scale_final)."""
        base = self.ins_scale
        last = self.ins_scale_final if self.ins_scale_final is not None \
            else base
        return tuple([base] * (rounds - 1) + [last])

    def _merge_round(self, anchors: List[Tuple[np.ndarray, np.ndarray]],
                     jobs: List[_Job], scale: Optional[float] = None
                     ) -> List[Tuple[np.ndarray, np.ndarray, np.ndarray,
                                     np.ndarray]]:
        """Column-merge every aligned job of a round, all windows at once.

        All scatter work runs as flat numpy adds over concatenated
        per-window column/gap arrays (one ``np.add.at`` per vote class for
        the whole round, instead of per-job Python loops) — the host-side
        analogue of the device batching. Only multi-base insertion runs
        (rare) take a Python path.

        Returns per window (consensus_codes, coverage, map_b, map_e);
        map_b[p] / map_e[p] give, for every anchor position p, the
        consensus index of the first kept column >= p / last kept column
        <= p — the coordinate maps refinement rounds use to re-slice
        layer spans.
        """
        n_win = len(anchors)
        Ls = np.array([len(bb) for bb, _ in anchors], dtype=np.int64)
        col_off = np.concatenate([[0], np.cumsum(Ls)])
        gap_off = np.concatenate([[0], np.cumsum(Ls + 1)])
        total_c = int(col_off[-1])
        total_g = int(gap_off[-1])

        base_w = np.zeros(total_c * ALPHABET, dtype=np.float64)
        base_c = np.zeros(total_c * ALPHABET, dtype=np.int64)
        del_w = np.zeros(total_c, dtype=np.float64)
        # Gap g of window w = insertion point before column g (g in 0..L).
        direct_w = np.zeros(total_g, dtype=np.float64)
        ins1_w = np.zeros(total_g * ALPHABET, dtype=np.float64)
        ins1_c = np.zeros(total_g * ALPHABET, dtype=np.int64)
        ins1_stop = np.zeros(total_g, dtype=np.float64)
        piles: Dict[int, _InsPileup] = {}  # gaps with multi-base runs

        # Backbone votes (sequence 0, src/window.cpp:34-37): epsilon keeps
        # the backbone base winning argmax ties at zero read coverage.
        bb_flat = np.concatenate([bb for bb, _ in anchors])
        bbw_flat = np.concatenate([w for _, w in anchors])
        np.add.at(base_w, np.arange(total_c) * ALPHABET + bb_flat,
                  bbw_flat + _EPS)
        np.add.at(base_c, np.arange(total_c) * ALPHABET + bb_flat, 1)
        for wi, (bb, bw) in enumerate(anchors):
            cross = (np.concatenate([[bw[0]], bw]) +
                     np.concatenate([bw, [bw[-1]]])) * 0.5
            direct_w[gap_off[wi]:gap_off[wi + 1]] += cross + _EPS

        if jobs:
            self._scatter_jobs(jobs, col_off, gap_off, base_w, base_c,
                               del_w, direct_w, ins1_w, ins1_c, ins1_stop,
                               piles)

        # Column votes, flat across all windows.
        base_w2 = base_w.reshape(total_c, ALPHABET)
        best_code = np.argmax(base_w2, axis=1)
        ar_c = np.arange(total_c)
        best_w = base_w2[ar_c, best_code]
        kept_flat = del_w <= best_w
        cov_flat = base_c.reshape(total_c, ALPHABET)[ar_c, best_code]

        # Single-base insertion winners, flat across all gaps; gaps with
        # multi-base runs are re-decided through their pileups below.
        ins1_w2 = ins1_w.reshape(total_g, ALPHABET)
        g_tot = ins1_w2.sum(axis=1)
        g_arg = np.argmax(ins1_w2, axis=1)
        if scale is None:
            scale = self.ins_scale
        emit1 = g_tot > direct_w * scale

        # Hand each window only its own piles (sorted keys + searchsorted,
        # instead of scanning the round-global dict per window).
        pile_keys = np.array(sorted(piles.keys()), dtype=np.int64)
        pile_bounds = np.searchsorted(pile_keys, gap_off)

        results = []
        for wi in range(n_win):
            c0, c1 = int(col_off[wi]), int(col_off[wi + 1])
            g0, g1 = int(gap_off[wi]), int(gap_off[wi + 1])
            L = c1 - c0
            kept = kept_flat[c0:c1]
            codes = best_code[c0:c1]
            cov = cov_flat[c0:c1]

            ins_events: List[Tuple[int, np.ndarray, np.ndarray]] = []
            for g in np.flatnonzero(emit1[g0:g1]):
                gg = g0 + int(g)
                if gg in piles:
                    continue  # full pileup decides below
                ins_events.append((
                    int(g),
                    np.array([g_arg[gg]], dtype=np.uint8),
                    np.array([ins1_c.reshape(total_g, ALPHABET)
                              [gg, g_arg[gg]]], dtype=np.int64)))
            for gg in pile_keys[pile_bounds[wi]:pile_bounds[wi + 1]]:
                gg = int(gg)
                pile = piles[gg]
                seq, cnt = pile.consensus(
                    float(direct_w[gg]) * scale,
                    ins1_w2[gg], ins1_c.reshape(total_g, ALPHABET)[gg],
                    float(ins1_stop[gg]))
                if len(seq):
                    ins_events.append((gg - g0, seq, cnt))
            ins_events.sort(key=lambda e: e[0])

            # Assemble consensus + per-base coverage.
            ins_len_at = np.zeros(L + 1, dtype=np.int64)
            parts: List[np.ndarray] = []
            covs: List[np.ndarray] = []
            last = 0
            for g, seq, cnt in ins_events:
                ins_len_at[g] = len(seq)
                sel = kept[last:g]
                parts.append(codes[last:g][sel])
                covs.append(cov[last:g][sel])
                parts.append(seq)
                covs.append(cnt)
                last = g
            sel = kept[last:]
            parts.append(codes[last:][sel])
            covs.append(cov[last:][sel])
            consensus = np.concatenate(parts).astype(np.uint8)
            coverage = np.concatenate(covs).astype(np.int32)

            # Coordinate maps anchor->consensus for refinement re-slicing.
            kept_excl = np.cumsum(kept) - kept      # kept columns before p
            ins_before = np.cumsum(ins_len_at)[:L]  # inserted bases, g<=p
            new_col = kept_excl + ins_before        # index where p landed
            kept_idx = np.flatnonzero(kept)
            ar = np.arange(L)
            if len(kept_idx) == 0:
                map_b = np.zeros(L, dtype=np.int64)
                map_e = np.zeros(L, dtype=np.int64)
            else:
                nb = np.searchsorted(kept_idx, ar, side="left")
                map_b = new_col[kept_idx[np.minimum(nb, len(kept_idx) - 1)]]
                ne = np.searchsorted(kept_idx, ar, side="right") - 1
                map_e = new_col[kept_idx[np.maximum(ne, 0)]]
            np.clip(map_b, 0, max(len(consensus) - 1, 0), out=map_b)
            np.clip(map_e, 0, max(len(consensus) - 1, 0), out=map_e)
            results.append((consensus, coverage, map_b, map_e))
        return results

    def _scatter_jobs(self, jobs, col_off, gap_off, base_w, base_c, del_w,
                      direct_w, ins1_w, ins1_c, ins1_stop, piles) -> None:
        """Flat scatter of every job's votes into the round accumulators."""
        lens = np.array([len(j.ops) for j in jobs], dtype=np.int64)
        starts = np.concatenate([[0], np.cumsum(lens)[:-1]])
        o = np.concatenate([j.ops for j in jobs])
        q_flat = np.concatenate([j.q for j in jobs])
        w_flat = np.concatenate([j.w for j in jobs]).astype(np.float64)
        q_lens = np.array([len(j.q) for j in jobs], dtype=np.int64)
        q_offs = np.concatenate([[0], np.cumsum(q_lens)[:-1]])

        jid = np.repeat(np.arange(len(jobs)), lens)
        w_read = np.repeat(np.array([j.w_read for j in jobs]), lens)
        # Global column of each op's target position: window column offset
        # + slice offset + within-slice t index (segmented cumsum).
        wins = np.array([j.win for j in jobs], dtype=np.int64)
        t_base = np.repeat(col_off[wins] + [j.t_off for j in jobs], lens)
        g_base = np.repeat(gap_off[wins] + [j.t_off for j in jobs], lens)

        cq = o != LEFT
        ct = o != UP
        c_cq = np.cumsum(cq)
        c_ct = np.cumsum(ct)
        pre_q = c_cq - cq
        pre_t = c_ct - ct
        qpos = pre_q - np.repeat(pre_q[starts], lens)  # q index within job
        tpos = pre_t - np.repeat(pre_t[starts], lens)  # t index within slice
        gq = np.minimum(q_offs[jid] + qpos, q_offs[jid] + q_lens[jid] - 1)
        gcol = t_base + tpos
        ggap = g_base + tpos

        m = o == DIAG
        np.add.at(base_w, gcol[m] * ALPHABET + q_flat[gq[m]], w_flat[gq[m]])
        np.add.at(base_c, gcol[m] * ALPHABET + q_flat[gq[m]], 1)

        d = o == LEFT
        if d.any():
            np.add.at(del_w, gcol[d], w_read[d])

        # Direct crossings, weighted by the *local* flanking base
        # qualities: inserted/uncertain bases carry low Phred scores in
        # long reads, so a gap's "no insertion here" evidence is judged
        # against quality in the same neighbourhood, not the read mean.
        t_idx = np.flatnonzero(ct)
        if len(t_idx) > 1:
            wq = np.where(m, w_flat[gq], w_read)
            same = jid[t_idx[1:]] == jid[t_idx[:-1]]
            adj = (np.diff(t_idx) == 1) & same  # no I ops between
            g_cross = ggap[t_idx[1:]][adj]
            w_cross = 0.5 * (wq[t_idx[:-1]][adj] + wq[t_idx[1:]][adj])
            np.add.at(direct_w, g_cross, w_cross)

        i_mask = o == UP
        if not i_mask.any():
            return
        flat = np.flatnonzero(i_mask)
        brk = (np.diff(flat) > 1) | (jid[flat[1:]] != jid[flat[:-1]])
        run_s = flat[np.concatenate([[True], brk])]
        run_e = flat[np.concatenate([brk, [True]])]
        run_len = run_e - run_s + 1
        one = run_len == 1
        # Single-base runs (the vast majority): fully vectorized.
        s1 = run_s[one]
        g1 = ggap[s1]
        b1 = q_flat[gq[s1]]
        w1 = w_flat[gq[s1]]
        np.add.at(ins1_w, g1 * ALPHABET + b1, w1)
        np.add.at(ins1_c, g1 * ALPHABET + b1, 1)
        np.add.at(ins1_stop, g1, w1)
        # Multi-base runs: per-run pileups (Python path, rare).
        for s, e in zip(run_s[~one], run_e[~one]):
            g = int(ggap[s])
            qs, qe = int(gq[s]), int(gq[e])
            pile = piles.get(g)
            if pile is None:
                pile = piles[g] = _InsPileup()
            pile.add(q_flat[qs:qe + 1], w_flat[qs:qe + 1])


class _InsPileup:
    """Left-justified pileup of inserted segments at one backbone gap.

    Columns are voted independently; emission continues while the weight
    of reads still extending the insertion beats the weight of reads that
    stopped (direct crossings + shorter insertions) — the column-local
    heaviest-path criterion.
    """
    __slots__ = ("col_w", "col_c", "len_w")

    def __init__(self):
        self.col_w: List[np.ndarray] = []
        self.col_c: List[np.ndarray] = []
        self.len_w: Dict[int, float] = {}

    def add(self, seg: np.ndarray, w: np.ndarray) -> None:
        for k in range(len(seg)):
            if k == len(self.col_w):
                self.col_w.append(np.zeros(ALPHABET, dtype=np.float64))
                self.col_c.append(np.zeros(ALPHABET, dtype=np.int32))
            self.col_w[k][seg[k]] += w[k]
            self.col_c[k][seg[k]] += 1
        self.len_w[len(seg)] = self.len_w.get(len(seg), 0.0) + \
            float(w.astype(np.float64).mean())

    def consensus(self, direct: float, extra0_w=None, extra0_c=None,
                  extra_stop1: float = 0.0
                  ) -> Tuple[np.ndarray, np.ndarray]:
        """Vote out the insertion columns.

        extra0_w/extra0_c fold in single-base runs at the same gap that
        were accumulated in the round's flat arrays; their weight joins
        the stopped side after column 0 (extra_stop1).
        """
        out: List[int] = []
        cnt: List[int] = []
        stopped = float(direct)
        for k in range(len(self.col_w)):
            cw = self.col_w[k]
            cc = self.col_c[k]
            if k == 0 and extra0_w is not None:
                cw = cw + extra0_w
                cc = cc + extra0_c
            if cw.sum() <= stopped:
                break
            b = int(np.argmax(cw))
            out.append(b)
            cnt.append(int(cc[b]))
            stopped += self.len_w.get(k + 1, 0.0)
            if k == 0:
                stopped += extra_stop1
        return (np.asarray(out, dtype=np.uint8),
                np.asarray(cnt, dtype=np.int32))


def _accelerator_present() -> bool:
    try:
        import jax
        return any(d.platform != "cpu" for d in jax.devices())
    except Exception:
        return False
