"""Device-resident consensus merge — the TPU replacement for the host
column-vote (racon_tpu/ops/poa.py::_merge_round/_scatter_jobs/_InsPileup).

Same semantics as the numpy reference implementation (which mirrors
spoa's add_alignment + generate_consensus, reference src/window.cpp:
100-111), restructured for TPU execution:

- **No scatters.** XLA lowers general scatter-adds on TPU to serialized
  updates; every per-op scatter in the numpy merge is reformulated as a
  gather. The key identity: in a global alignment, ops with the same
  "target positions consumed so far" value form one contiguous block
  ``[insertion run at gap v][the op consuming column v]``, so a per-lane
  ``searchsorted`` over that monotone counter finds, for every anchor
  column, the op that consumed it and the insertion run before it — all
  columns in parallel.
- **Aggregation is a matmul.** Per-job dense per-column vote channels are
  summed into per-window accumulators by a window-membership one-hot
  matrix ([Nw, B] @ [B, LA*C]) on the MXU — weights are integer-valued
  (Phred or 1.0), so f32 accumulation is exact below 2^24.
- **Variable-length output without host round-trips.** Emitted consensus
  lives in a padded [Nw, LA+1, K+1] slot layout (K insertion slots per
  gap + the column slot); compaction to dense per-window strings is a
  searchsorted gather over the valid-slot cumsum. Only the final compact
  consensus + coverage leave the device.

Deviations from the numpy reference (documented, covered by tolerance in
differential tests): insertion pileups cap at K columns per gap (the
reference is unbounded; >K-base unanimous insertions are truncated), and
accumulator dtype is f32 (reference f64) so sub-ulp tie-breaks can differ
when non-integer mean weights collide exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from racon_tpu.ops.cigar import DIAG, UP, LEFT
from racon_tpu.ops.flat import PAD_OP  # shared op padding marker
from racon_tpu.ops.flat import U_SAT as _U_SAT
from racon_tpu.ops.poa import _EPS as EPS  # shared tie-break epsilon

# Pileup columns per gap kept on device. Insertion runs longer than
# K_INS raise the walk's sticky redo flag (flat.U_SAT = K_INS + 1) and
# re-polish on the unbounded host path — unlike the former K=8/U_SAT=15
# scheme, device output is never silently truncated. 10 is measured on
# the reference lambda dataset: every window's max run is <= 10 (zero
# redos), where K=8 would redo 8/96 windows and K=4 68/96.
K_INS = 10
# The contract above only holds when the walk's saturation threshold
# tracks K (and extract_votes_cols' packed-word layout is hand-laid for
# K = 10); fail loudly at import if either is retuned alone. ValueError,
# not assert: asserts are stripped under `python -O`, and silently
# running with a mismatched layout corrupts every consensus.
if _U_SAT != K_INS + 1:
    raise ValueError(
        "[racon_tpu::device_merge] flat.U_SAT must equal K_INS + 1 "
        f"(U_SAT={_U_SAT}, K_INS={K_INS})")
if K_INS != 10:
    raise ValueError(
        "[racon_tpu::device_merge] extract_votes_cols' packed-word "
        f"layout is hand-laid for K_INS=10 (got {K_INS})")
NBASE = 5          # A C G T N
# Python int, NOT jnp.int32: a module-level jax.Array closed over by a
# jitted function lowers as a hoisted buffer parameter on some traces, and
# jax 0.9's execution path then under-supplies the executable ("Execution
# supplied 11 buffers but compiled program expected 12") — the root cause
# of the round-3 INVALID_ARGUMENT crash on TPU (BENCH_r03; repro:
# scripts/tpu_two_shape_repro.py). A Python scalar is always inlined.
_HI = 2 ** 30

_PREC = jax.lax.Precision.HIGHEST


def _onehot(idx, depth, dtype=jnp.float32):
    return (idx[..., None] == jnp.arange(depth, dtype=idx.dtype)).astype(
        dtype)


def _take1(a, idx):
    """take_along_axis on axis 1 with clipping."""
    return jnp.take_along_axis(a, jnp.clip(idx, 0, a.shape[1] - 1), axis=1)


def extract_votes(ops, q, qw, w_read, lt, t_off, LA: int,
                  pallas: bool = False):
    """Per-job anchor-aligned dense vote channels from right-aligned ops.

    Args:
      ops:    uint8[B, S] right-aligned (PAD_OP prefix), start->end order.
      q:      uint8[B, Lq] query codes.
      qw:     f32[B, Lq] per-base weights.
      w_read: f32[B] read-mean weight.
      lt:     int32[B] target (slice) lengths.
      t_off:  int32[B] slice offset in the window anchor.
      LA:     static anchor padding length.
      pallas: route the monotone count through the Pallas kernel.

    Returns dict of [B, LA(+1), ...] channel arrays (see code).

    Perf notes (measured in-program on TPU v5e at B=3072, S=1408):
    the broadcast compare-reduce for F cost ~380 ms under XLA — it is a
    Pallas kernel now (racon_tpu/ops/pallas/count_kernel.py, ~10 ms) —
    and per-column gathers cost ~10-25 ms *per call* regardless of
    width, so the ~23 take_along_axis calls of the first version are
    coalesced into 4 stacked gathers over channel stacks.
    """
    from racon_tpu.ops.pallas.count_kernel import (monotone_count_pallas,
                                                   monotone_count_xla)
    B, S = ops.shape
    Lq = q.shape[1]
    valid = ops != PAD_OP
    tcons = valid & (ops != UP)
    qcons = valid & (ops != LEFT)
    ct = jnp.cumsum(tcons, axis=1, dtype=jnp.int32)
    cq = jnp.cumsum(qcons, axis=1, dtype=jnp.int32)
    ct_excl = ct - tcons
    cq_excl = cq - qcons
    # Monotone block key: pads (a prefix) sort below every real op.
    X = jnp.where(valid, ct_excl, -1)

    # F[v] = first op index of block v, for v = p - t_off at every anchor
    # gap/column p in [0, LA]. (+1 row for F[v+1].) searchsorted-left
    # over a monotone key == count of keys < v; shifting X by t_off turns
    # the per-lane v grid into the plain arange the count kernel wants.
    Xs = X + t_off[:, None]
    if pallas and B % 128 == 0:
        F = monotone_count_pallas(Xs, LA + 2)        # [B, LA+2]
    else:
        F = monotone_count_xla(Xs, LA + 2)
    Fa = F[:, :-1]                                    # F(c) at p
    F1 = F[:, 1:]                                     # F(c+1) at p

    ltc = lt[:, None]
    pa = jnp.arange(LA + 2, dtype=jnp.int32)[None, :]
    c = (pa - t_off[:, None])[:, :-1]                 # slice-rel position at p
    in_cols = (c >= 0) & (c < ltc)                    # column p exists
    in_gaps = (c >= 0) & (c <= ltc)                   # gap p exists

    # Insertion run before column c: block minus its t-step (absent at c==lt).
    ins_len = jnp.where(in_gaps,
                        F1 - Fa - jnp.where(c < ltc, 1, 0), 0)  # [B, LA+1]

    # Stacked gather #1 (op axis): channels [cq_excl[min(s, S-1)],
    # cq_excl[s-1], ops[s-1]] read at s = F[p] give, per column, the
    # first-insertion q index (at p) and the column-consuming op's
    # q index / op code (at p+1, where F[p+1]-1 is the consumer).
    # The stack has S+1 rows because F reaches S whenever an alignment's
    # last op consumes its last column; boundary rows replicate the
    # clipped-take semantics of a plain gather at F-1 / F.
    ops32 = ops.astype(jnp.int32)
    stack_s = jnp.stack(
        [jnp.concatenate([cq_excl, cq_excl[:, -1:]], axis=1),
         jnp.concatenate([cq_excl[:, :1], cq_excl], axis=1),
         jnp.concatenate([ops32[:, :1], ops32], axis=1)],
        axis=-1)                                      # [B, S+1, 3]
    G = jnp.take_along_axis(
        stack_s, jnp.clip(F, 0, S)[:, :, None], axis=1)      # [B, LA+2, 3]
    qstart = G[:, :-1, 0]                             # q idx of first ins base
    qi = G[:, 1:, 1]                                  # q idx matched at c
    op_at = G[:, 1:, 2]                               # op consuming column c
    is_match = in_cols & (op_at == DIAG)

    # Stacked gather #2 (query axis) at qi: [base code, weight].
    qx = q.astype(jnp.int32)
    stack_qi = jnp.stack([qx.astype(jnp.float32), qw], axis=-1)
    Gqi = jnp.take_along_axis(
        stack_qi, jnp.clip(qi, 0, Lq - 1)[:, :, None], axis=1)
    colbase = Gqi[..., 0].astype(jnp.int32)
    colw = Gqi[..., 1]
    wq = jnp.where(is_match, colw, w_read[:, None])   # per-column path weight

    cols = in_cols[:, :LA]
    base_idx = jnp.where(is_match[:, :LA], colbase[:, :LA], NBASE)  # 5 = del
    col_w = jnp.where(cols, jnp.where(is_match[:, :LA], colw[:, :LA],
                                      w_read[:, None]), 0.0)
    col_oh = _onehot(base_idx, NBASE + 1)
    col_w_ch = col_oh * col_w[..., None]                       # [B, LA, 6]
    col_c_ch = col_oh[..., :NBASE] * (is_match[:, :LA] &
                                      cols)[..., None]         # [B, LA, 5]

    # Direct crossings: columns c-1 and c both consumed, no insertion between.
    crossed = (c >= 1) & (c <= ltc - 1) & (ins_len == 0)
    wq_prev = jnp.concatenate([w_read[:, None], wq[:, :LA]], axis=1)
    cross_w = jnp.where(crossed, 0.5 * (wq_prev + wq), 0.0)    # [B, LA+1]

    # Stacked gather #3 (query axis) at qstart: the k = 0..K-1 shifted
    # base/weight channels (pileup columns without per-k gathers) plus
    # the weight prefix sum at the run start. Tail-clamped pads replicate
    # take-with-clip semantics for runs ending at the query edge.
    qwcum = jnp.concatenate(
        [jnp.zeros((B, 1), jnp.float32), jnp.cumsum(qw, axis=1)], axis=1)
    qx_pad = jnp.concatenate(
        [qx, jnp.repeat(qx[:, -1:], K_INS - 1, axis=1)], axis=1)
    qw_pad = jnp.concatenate(
        [qw, jnp.repeat(qw[:, -1:], K_INS - 1, axis=1)], axis=1)
    chans = ([qx_pad[:, k:k + Lq].astype(jnp.float32)
              for k in range(K_INS)] +
             [qw_pad[:, k:k + Lq] for k in range(K_INS)] +
             [qwcum[:, :Lq]])
    stack_qs = jnp.stack(chans, axis=-1)              # [B, Lq, 2K+1]
    Gqs = jnp.take_along_axis(
        stack_qs, jnp.clip(qstart, 0, Lq - 1)[:, :, None], axis=1)
    b_k = Gqs[..., :K_INS].astype(jnp.int32)          # q[qstart+k]
    w_k = Gqs[..., K_INS:2 * K_INS]                   # qw[qstart+k]
    cum_start = Gqs[..., 2 * K_INS]                   # qwcum[qstart]

    # Insertions.
    has1 = in_gaps & (ins_len == 1)
    multi = in_gaps & (ins_len >= 2)
    b1 = b_k[..., 0]
    w1 = w_k[..., 0]
    ins1_oh = _onehot(jnp.where(has1, b1, NBASE), NBASE + 1)[..., :NBASE]
    ins1_w_ch = ins1_oh * jnp.where(has1, w1, 0.0)[..., None]
    ins1_c_ch = ins1_oh * has1[..., None]
    ins1_stop = jnp.where(has1, w1, 0.0)

    # Pileup columns k = 0..K-1 for multi-base runs (no gathers).
    pk_w, pk_c = [], []
    for k in range(K_INS):
        inrun = multi & (ins_len > k)
        oh = _onehot(jnp.where(inrun, b_k[..., k], NBASE),
                     NBASE + 1)[..., :NBASE]
        pk_w.append(oh * jnp.where(inrun, w_k[..., k], 0.0)[..., None])
        pk_c.append(oh * inrun[..., None])
    pile_w_ch = jnp.stack(pk_w, axis=2)               # [B, LA+1, K, 5]
    pile_c_ch = jnp.stack(pk_c, axis=2)

    # Run mean weight -> stop-weight by run length (lengths 2..K).
    # Stacked gather #4: weight prefix sum at the run end.
    run_sum = _take1(qwcum, qstart + ins_len) - cum_start
    wmean = jnp.where(multi, run_sum / jnp.maximum(ins_len, 1), 0.0)
    lw_oh = (jnp.clip(ins_len, 0, K_INS)[..., None] ==
             jnp.arange(2, K_INS + 1)[None, None, :])
    lenw_ch = lw_oh * (wmean * multi)[..., None]      # [B, LA+1, K-1]

    return {
        "col_w": col_w_ch, "col_c": col_c_ch,
        "cross_w": cross_w[..., None],
        "ins1_w": ins1_w_ch, "ins1_c": ins1_c_ch,
        "ins1_stop": ins1_stop[..., None],
        "pile_w": pile_w_ch.reshape(B, LA + 1, -1),
        "pile_c": pile_c_ch.reshape(B, LA + 1, -1),
        "lenw": lenw_ch,
    }


def extract_votes_cols(cols, q, qw8, w_read, lt, t_off, LA: int):
    """Per-job anchor-aligned dense vote channels from column-walk output.

    The production replacement for :func:`extract_votes`. The column-walk
    traceback (racon_tpu/ops/colwalk.py) already emits ``ins_len /
    qstart / op_c / qi_c`` keyed by anchor position, so no re-keying
    gathers are needed; the only gather left is ONE merged query-window
    read. Key facts: the consumer's query index ``qi`` differs from the
    run start ``qstart`` by at most 1, and every unflagged insertion run
    is at most K_INS bases (longer runs saturate the walk's up-run
    counter at U_SAT = K_INS + 1 and take the host redo route), so the
    window only spans K_INS + 1 query codes and K_INS + 1 weights around
    qstart - 1. TPU gather cost scales with the number of gathered
    ELEMENTS, not bytes (measured round 5, scripts/ablate_gather_pack.py:
    a 26-channel u8 stacked gather costs ~100 ms at B=6144 where a
    3-word i32 gather costs ~30 ms), so the window ships as FOUR packed
    i32 words per query position: 11 base codes at 3 bits each and 11
    weights at 7 bits each (weights are Phred + 1 <= 94 + 1 on any real
    FASTQ — ChunkPlan clips the encoding at 126 accordingly). The run
    weight sum is an in-register masked sum over the decoded window —
    exact, since weights are integers and partial sums stay far below
    2^24.

    Every channel value consumed downstream is bit-identical to
    extract_votes' (masked-out garbage may differ; all returned channels
    are masked). Insertion runs longer than U_SAT are handled by the
    walk's saturation redo flag, never by these channels.

    Args:
      cols: dict from colwalk.col_walk ([B, LA+2] int16 arrays).
      q: uint8[B, Lq] query codes.
      qw8: uint8[B, Lq] encoded weights (value + 1, 0 = padding).
      w_read, lt, t_off, LA: as extract_votes.
    """
    B, Lq = q.shape
    ltc = lt[:, None]
    pa = jnp.arange(LA + 1, dtype=jnp.int32)[None, :]
    c = pa - t_off[:, None]                  # anchor-relative position
    in_cols = (c >= 0) & (c < ltc)
    in_gaps = (c >= 0) & (c <= ltc)

    ins_len = jnp.where(in_gaps, cols["ins_len"][:, :LA + 1]
                        .astype(jnp.int32), 0)
    qstart = cols["qstart"][:, :LA + 1].astype(jnp.int32)
    # Column p's consumer was emitted by the walk step at p + 1.
    op_at = cols["op_c"][:, 1:].astype(jnp.int32)
    qi = cols["qi_c"][:, 1:].astype(jnp.int32)
    is_match = in_cols & (op_at == DIAG)

    # Merged query-window gather over the FULL LA+2 walk grid: offsets
    # 0..K_INS around qstart-1, packed into FOUR i32 words per query
    # position (see docstring — gather cost scales with element count).
    # Word layout (QO = K_INS + 1 = 11 offsets):
    #   word0: q[0..9]  at 3 bits each            (bits 0..29)
    #   word1: w[0..3]  at 7 bits each | q[10]<<28 (bits 0..30)
    #   word2: w[4..7]  at 7 bits each            (bits 0..27)
    #   word3: w[8..10] at 7 bits each            (bits 0..20)
    # Gap consumers (pileup/run channels at anchor p) read row p; the
    # column-p consumer's query index qi was emitted by walk step p+1
    # and satisfies qi in {qstart[p+1]-1, qstart[p+1]}, so its
    # base/weight read row p+1 of the same gather.
    QO = K_INS + 1
    qpad = jnp.concatenate(
        [q, jnp.repeat(q[:, -1:], QO, axis=1)], axis=1).astype(jnp.int32)
    wpad = jnp.minimum(jnp.concatenate(
        [qw8, jnp.repeat(qw8[:, -1:], QO, axis=1)], axis=1)
        .astype(jnp.int32), 127)
    word0 = sum((qpad[:, o:o + Lq] << (3 * o)) for o in range(10))
    word1 = sum((wpad[:, o:o + Lq] << (7 * o)) for o in range(4)) \
        + (qpad[:, 10:10 + Lq] << 28)
    word2 = sum((wpad[:, o:o + Lq] << (7 * (o - 4))) for o in range(4, 8))
    word3 = sum((wpad[:, o:o + Lq] << (7 * (o - 8))) for o in range(8, 11))
    stack = jnp.stack([word0, word1, word2, word3], axis=-1)
    qs_full = cols["qstart"].astype(jnp.int32)        # [B, LA+2]
    qsc_full = jnp.clip(qs_full, 0, Lq - 1)
    s0_full = jnp.maximum(qsc_full - 1, 0)
    Gfull = jnp.take_along_axis(stack, s0_full[:, :, None], axis=1)
    Gg = Gfull[:, :LA + 1]                            # gap rows (step p)

    def _q_at(g, o):
        if o == 10:
            return (g[..., 1] >> 28) & 7
        return (g[..., 0] >> (3 * o)) & 7

    def _w_at(g, o):
        w, s = divmod(o, 4)
        raw = (g[..., 1 + w] >> (7 * s)) & 127
        return jnp.maximum(raw.astype(jnp.float32) - 1.0, 0.0)

    o1 = (qsc_full - s0_full)[:, :LA + 1] == 1

    def sel_q(o):
        return jnp.where(o1, _q_at(Gg, o + 1), _q_at(Gg, o))

    def sel_w(o):
        return jnp.where(o1, _w_at(Gg, o + 1), _w_at(Gg, o))

    Gc = Gfull[:, 1:]                                 # column rows (p+1)
    qi1 = (jnp.clip(qi, 0, Lq - 1) - s0_full[:, 1:]) == 1
    colbase = jnp.where(qi1, _q_at(Gc, 1), _q_at(Gc, 0))
    colw = jnp.where(qi1, _w_at(Gc, 1), _w_at(Gc, 0))
    wq = jnp.where(is_match, colw, w_read[:, None])   # per-column weight

    # Integer-valued channels (one-hot counts, integer Phred weights)
    # are emitted in bfloat16 — exact for these values, and they ride
    # aggregate_votes' cheap bf16 MXU matmul (see its docstring).
    bf16 = jnp.bfloat16
    cols_m = in_cols[:, :LA]
    base_idx = jnp.where(is_match[:, :LA], colbase[:, :LA], NBASE)
    col_w = jnp.where(cols_m, jnp.where(is_match[:, :LA], colw[:, :LA],
                                        w_read[:, None]), 0.0)
    col_oh = _onehot(base_idx, NBASE + 1)
    col_w_ch = col_oh * col_w[..., None]                       # [B, LA, 6]
    col_c_ch = (col_oh[..., :NBASE].astype(bf16) *
                (is_match[:, :LA] &
                 cols_m)[..., None].astype(bf16))              # [B, LA, 5]

    # Direct crossings: columns c-1 and c both consumed, no insertion.
    crossed = (c >= 1) & (c <= ltc - 1) & (ins_len == 0)
    wq_prev = jnp.concatenate([w_read[:, None], wq[:, :LA]], axis=1)
    cross_w = jnp.where(crossed, 0.5 * (wq_prev + wq), 0.0)    # [B, LA+1]

    # Insertions.
    has1 = in_gaps & (ins_len == 1)
    multi = in_gaps & (ins_len >= 2)
    b1 = sel_q(0)
    w1 = sel_w(0)
    ins1_oh = _onehot(jnp.where(has1, b1, NBASE), NBASE + 1,
                      bf16)[..., :NBASE]
    ins1_w_ch = ins1_oh * jnp.where(has1, w1, 0.0)[..., None].astype(bf16)
    ins1_c_ch = ins1_oh * has1[..., None].astype(bf16)
    ins1_stop = jnp.where(has1, w1, 0.0).astype(bf16)

    # Pileup columns k = 0..K-1 for multi-base runs (no gathers).
    pk_w, pk_c = [], []
    for k in range(K_INS):
        inrun = multi & (ins_len > k)
        oh = _onehot(jnp.where(inrun, sel_q(k), NBASE), NBASE + 1,
                     bf16)[..., :NBASE]
        pk_w.append(oh * jnp.where(inrun, sel_w(k), 0.0)[..., None]
                    .astype(bf16))
        pk_c.append(oh * inrun[..., None].astype(bf16))
    pile_w_ch = jnp.stack(pk_w, axis=2)               # [B, LA+1, K, 5]
    pile_c_ch = jnp.stack(pk_c, axis=2)

    # Run mean weight -> stop-weight by run length (lengths 2..K); the
    # full run weight sum comes from the same window (runs past K_INS
    # never reach here — the walk's sat flag reroutes them).
    run_sum = sum(jnp.where(ins_len > k, sel_w(k), 0.0)
                  for k in range(K_INS))
    wmean = jnp.where(multi, run_sum / jnp.maximum(ins_len, 1), 0.0)
    lw_oh = (jnp.clip(ins_len, 0, K_INS)[..., None] ==
             jnp.arange(2, K_INS + 1)[None, None, :])
    lenw_ch = lw_oh * (wmean * multi)[..., None]      # [B, LA+1, K-1]

    return {
        "col_w": col_w_ch, "col_c": col_c_ch,
        "cross_w": cross_w[..., None],
        "ins1_w": ins1_w_ch, "ins1_c": ins1_c_ch,
        "ins1_stop": ins1_stop[..., None],
        "pile_w": pile_w_ch.reshape(B, LA + 1, -1),
        "pile_c": pile_c_ch.reshape(B, LA + 1, -1),
        "lenw": lenw_ch,
    }


def aggregate_votes(votes, win, n_win: int, extras=None):
    """Sum per-job channels into per-window accumulators via one-hot
    matmul. ``extras``: optional dict of per-job [B] scalars summed per
    window with the same membership matrix (returned under their keys).

    Channels arriving in bfloat16 aggregate through a DEFAULT-precision
    bf16 matmul with f32 accumulation — EXACT for their values, which
    are one-hot 0/1 counts and integer Phred weights <= 126 (both
    representable in bf16; MXU accumulation is f32 and per-window sums
    stay far below 2^24) — at a fraction of the HIGHEST-precision f32
    matmul the fractional channels (w_read-derived crossings, run-mean
    length weights) still require. extract_votes_cols emits the integer
    channels as bf16 for this reason; the all-f32 legacy extract_votes
    path just lands every channel in the f32 group.
    """
    B = win.shape[0]
    M = (jnp.arange(n_win, dtype=jnp.int32)[:, None] ==
         win[None, :])                                # [Nw, B] bool
    M32 = M.astype(jnp.float32)
    M16 = M.astype(jnp.bfloat16)

    def agg(xs):
        """Concatenated matmul per dtype group; returns [Nw, L, C_total]
        in the order of ``xs``."""
        groups = {}
        for x in xs:
            groups.setdefault(x.dtype == jnp.bfloat16, []).append(x)
        outs = {}
        for is16, grp in groups.items():
            flat = jnp.concatenate(grp, axis=-1).reshape(B, -1)
            Lc = flat.shape[1] // grp[0].shape[1]
            if is16:
                o = jnp.matmul(M16, flat,
                               preferred_element_type=jnp.float32)
            else:
                o = jnp.matmul(M32, flat, precision=_PREC)
            outs[is16] = iter(jnp.split(
                o.reshape(n_win, grp[0].shape[1], Lc),
                np.cumsum([g.shape[-1] for g in grp])[:-1], axis=-1))
        return jnp.concatenate(
            [next(outs[x.dtype == jnp.bfloat16]) for x in xs], axis=-1)

    col = agg([votes["col_w"], votes["col_c"]])
    gap = agg([votes["cross_w"], votes["ins1_w"], votes["ins1_c"],
               votes["ins1_stop"], votes["pile_w"], votes["pile_c"],
               votes["lenw"]])
    out = {}
    if extras:
        for k, v in extras.items():
            out[k] = jnp.matmul(M32, v[:, None], precision=_PREC)[:, 0]
    out["base_w"] = col[..., :NBASE + 1]              # [Nw, LA, 6] (5=del)
    out["base_c"] = col[..., NBASE + 1:]              # [Nw, LA, 5]
    i = 0
    out["direct_w"] = gap[..., i]; i += 1
    out["ins1_w"] = gap[..., i:i + NBASE]; i += NBASE
    out["ins1_c"] = gap[..., i:i + NBASE]; i += NBASE
    out["ins1_stop"] = gap[..., i]; i += 1
    out["pile_w"] = gap[..., i:i + K_INS * NBASE].reshape(
        gap.shape[0], gap.shape[1], K_INS, NBASE); i += K_INS * NBASE
    out["pile_c"] = gap[..., i:i + K_INS * NBASE].reshape(
        gap.shape[0], gap.shape[1], K_INS, NBASE); i += K_INS * NBASE
    out["lenw"] = gap[..., i:i + K_INS - 1]; i += K_INS - 1
    return out


def aggregate_flags(flags, win, n_win: int):
    """Per-window sums of one per-job scalar via the same membership
    matmul aggregate_votes rides ([Nw, B] @ [B, 1] — one MXU pass, the
    "cheap reduction appended to the merge step" of the convergence
    scheduler). Exact for 0/1 flags (f32 sums far below 2^24)."""
    M32 = (jnp.arange(n_win, dtype=jnp.int32)[:, None] ==
           win[None, :]).astype(jnp.float32)
    return jnp.matmul(M32, flags[:, None].astype(jnp.float32),
                      precision=_PREC)[:, 0]


def converged_windows(codes, total, bb_old, alen_old, wchg):
    """Per-window fixed-point predicate for the convergence scheduler.

    A window is converged when this round reproduced its own input
    anchor exactly — same length, same code bytes (both arrays are
    zero-padded past their lengths, so full-row equality composes with
    the length check), and no lane span moved through the coordinate
    maps (``wchg``: per-window sum of lane span-change flags; a
    consensus can match byte-for-byte while deletions and insertions
    offset each other and still shift spans, so byte equality alone is
    NOT a fixed point). Only meaningful from round 1 on: the round-0
    anchor carries backbone quality weights, later anchors re-vote with
    neutral weights, so round 0's input is not a replayable state.
    """
    return (total == alen_old) & (wchg == 0) & \
        jnp.all(codes == bb_old, axis=1)


def add_backbone(acc, bb, bbw, alen):
    """Fold the backbone's votes in (sequence 0, epsilon tie-break)."""
    Nw, LA = bb.shape
    p = jnp.arange(LA, dtype=jnp.int32)[None, :]
    vcol = p < alen[:, None]
    oh = _onehot(bb.astype(jnp.int32), NBASE + 1)[..., :NBASE]
    acc["base_w"] = acc["base_w"].at[..., :NBASE].add(
        oh * (jnp.where(vcol, bbw + EPS, 0.0))[..., None])
    acc["base_c"] = acc["base_c"] + oh * vcol[..., None]
    bw0 = bbw[:, :1]
    bwl = _take1(bbw, jnp.maximum(alen - 1, 0)[:, None])
    left = jnp.concatenate([bw0, bbw], axis=1)        # bw[p-1], bw[0] at p=0
    right = jnp.concatenate([bbw, bwl], axis=1)       # bw[p], bw[L-1] at p=L
    # Right operand must be bw[alen-1] at p == alen (anchors are padded).
    pg = jnp.arange(LA + 1, dtype=jnp.int32)[None, :]
    right = jnp.where(pg == alen[:, None], bwl, right)
    left = jnp.where(pg == alen[:, None], bwl, left)
    vgap = pg <= alen[:, None]
    cross = 0.5 * (left + right)
    acc["direct_w"] = acc["direct_w"] + jnp.where(vgap, cross + EPS, 0.0)
    return acc


def assemble(acc, alen, ins_scale: float):
    """Vote out consensus into a per-gap prefix layout + coordinate maps.

    Emission at a gap stops permanently at the first pileup column that
    loses to the stopped weight, so a gap's emitted insertion slots are
    always a PREFIX of its K_INS columns; the layout is therefore fully
    described by a per-gap emit count — no (LA+1)*(K+1) flat slot cumsum
    needed (the former slot layout's searchsorted compaction was the
    round's tail cost at K_INS = 10).

    Returns dict with:
      ins_codes i32 [Nw, LA+1, K] pileup winner codes
      ins_cnt   i32 [Nw, LA+1, K] their coverage counts
      e         i32 [Nw, LA+1] emitted insertion count per gap
      col_code  i32 [Nw, LA] column winner code
      col_cov   i32 [Nw, LA]
      start     i32 [Nw, LA+1] output position of gap p's first slot
      total     i32 [Nw] new consensus lengths
      pos       i32 [Nw, LA] landing position of each kept column
      kept      bool [Nw, LA]
    """
    base_w, base_c = acc["base_w"], acc["base_c"]
    Nw, LA, _ = base_c.shape
    p = jnp.arange(LA, dtype=jnp.int32)[None, :]
    vcol = p < alen[:, None]
    pg = jnp.arange(LA + 1, dtype=jnp.int32)[None, :]
    vgap = pg <= alen[:, None]

    best_code = jnp.argmax(base_w[..., :NBASE], axis=-1)
    best_w = jnp.take_along_axis(base_w[..., :NBASE], best_code[..., None],
                                 axis=-1)[..., 0]
    del_w = base_w[..., NBASE]
    kept = vcol & (del_w <= best_w)
    cov = jnp.take_along_axis(base_c, best_code[..., None], axis=-1)[..., 0]

    # Gap emission: K sequential pileup columns (col 0 folds single runs).
    stopped = acc["direct_w"] * ins_scale
    emit_prev = vgap
    ins_codes, ins_cnt = [], []
    e = jnp.zeros((Nw, LA + 1), jnp.int32)
    for k in range(K_INS):
        cw = acc["pile_w"][:, :, k, :]
        cc = acc["pile_c"][:, :, k, :]
        if k == 0:
            cw = cw + acc["ins1_w"]
            cc = cc + acc["ins1_c"]
        tot = jnp.sum(cw, axis=-1)
        em = emit_prev & (tot > stopped)
        bk = jnp.argmax(cw, axis=-1)
        ck = jnp.take_along_axis(cc, bk[..., None], axis=-1)[..., 0]
        ins_codes.append(bk)
        ins_cnt.append(ck.astype(jnp.int32))
        e = e + em
        emit_prev = em
        # stopped += len_w[k+1] (+ single-run stops after column 0)
        if k == 0:
            stopped = stopped + acc["ins1_stop"]
        if k + 1 >= 2 and (k + 1) - 2 < acc["lenw"].shape[-1]:
            stopped = stopped + acc["lenw"][..., (k + 1) - 2]

    ins_codes = jnp.stack(ins_codes, axis=2)          # [Nw, LA+1, K]
    ins_cnt = jnp.stack(ins_cnt, axis=2)

    # Unit p = gap p's emitted insertions, then column p (absent at LA).
    ulen = e + jnp.concatenate(
        [kept.astype(jnp.int32), jnp.zeros((Nw, 1), jnp.int32)], axis=1)
    cum_u = jnp.cumsum(ulen, axis=1, dtype=jnp.int32)
    start = cum_u - ulen                              # exclusive cumsum
    total = cum_u[:, -1]
    pos = start[:, :LA] + e[:, :LA]                   # column p's landing

    return {
        "ins_codes": ins_codes,
        "ins_cnt": ins_cnt,
        "e": e,
        "col_code": best_code.astype(jnp.int32),
        "col_cov": cov.astype(jnp.int32),
        "start": start,
        "total": total,
        "pos": pos,
        "kept": kept,
    }


def compact(asm, out_len: int):
    """Gather-based stream compaction of the per-gap prefix layout.

    For output position j: its unit g = #{p : start[p] <= j} - 1 (start
    is monotone), offset o = j - start[g]; the emitted symbol is pileup
    column o of gap g while o < e[g], else column g's winner.

    Returns (codes u8 [Nw, out_len], cov i32 [Nw, out_len], total i32[Nw]).
    Positions beyond ``total`` hold code 0 / cov 0.
    """
    start, e, total = asm["start"], asm["e"], asm["total"]
    Nw, LA1 = start.shape
    jj = jnp.arange(out_len, dtype=jnp.int32)
    # Count-leq over the monotone starts (the only O(LA^2) op left; it
    # replaces the former count over (LA+1)*(K+1) slots).
    g = jnp.sum(start[:, :, None] <= jj[None, None, :], axis=1,
                dtype=jnp.int32) - 1
    off = jj[None, :] - _take1(start, g)
    eg = _take1(e, g)
    is_ins = off < eg
    K = asm["ins_codes"].shape[2]
    flat_i = g * K + jnp.minimum(off, K - 1)
    ins_code = _take1(asm["ins_codes"].reshape(Nw, LA1 * K), flat_i)
    ins_cov = _take1(asm["ins_cnt"].reshape(Nw, LA1 * K), flat_i)
    gc = jnp.minimum(g, LA1 - 2)                      # column g (g < LA)
    col_code = _take1(asm["col_code"], gc)
    col_cov = _take1(asm["col_cov"], gc)
    live = jj[None, :] < total[:, None]
    codes = jnp.where(live, jnp.where(is_ins, ins_code, col_code), 0)
    cov = jnp.where(live, jnp.where(is_ins, ins_cov, col_cov), 0)
    return codes.astype(jnp.uint8), cov, total


def coord_maps(asm, alen, LA: int):
    """map_b / map_e: for every old-anchor position, the landing position of
    the nearest kept column at-or-after / at-or-before it (falling back to
    the last / first kept column, 0 when none are kept) — the coordinate
    maps refinement rounds use to re-slice layer spans."""
    kept, pos = asm["kept"], asm["pos"]
    Nw = kept.shape[0]
    posk = jnp.where(kept, pos, _HI)
    # reverse cummin
    map_b = jnp.flip(jax.lax.cummin(jnp.flip(posk, axis=1), axis=1), axis=1)
    posk2 = jnp.where(kept, pos, -_HI)
    map_e = jax.lax.cummax(posk2, axis=1)
    any_kept = jnp.any(kept, axis=1, keepdims=True)
    last_kept = jnp.max(jnp.where(kept, pos, -_HI), axis=1, keepdims=True)
    first_kept = jnp.min(jnp.where(kept, pos, _HI), axis=1, keepdims=True)
    map_b = jnp.where(map_b == _HI, last_kept, map_b)
    map_e = jnp.where(map_e == -_HI, first_kept, map_e)
    map_b = jnp.where(any_kept, map_b, 0)
    map_e = jnp.where(any_kept, map_e, 0)
    hi = jnp.maximum(asm["total"][:, None] - 1, 0)
    map_b = jnp.clip(map_b, 0, hi)
    map_e = jnp.clip(map_e, 0, hi)
    return map_b.astype(jnp.int32), map_e.astype(jnp.int32)
