"""Pallas TPU kernel for the monotone counting step of the device merge:

    F[b, p] = #{ s : X[b, s] < p }   for p in [0, P)

This is extract_votes' searchsorted-left over the per-lane monotone block
key (racon_tpu/ops/device_merge.py) — the replacement for spoa's
aligned-node bookkeeping. XLA lowers the equivalent broadcast
compare-reduce to ~380 ms of VPU time at bench shapes (B=3072, S=1408,
P=770, measured in-program); this kernel streams X once through VMEM and
keeps the [8, 128] accumulator in registers, hitting the VPU's native
throughput instead.

Layout: X arrives transposed [S, B] so the per-step row read is a cheap
dynamic *sublane* slice; p values sit on sublanes, jobs on lanes. Output
is [P, B] (the caller transposes back — one XLA transpose of a few MB).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

PB = 8     # p values per program (sublanes)
TB = 128   # jobs per program (lanes)


def _kernel(XT_ref, out_ref, *, S):
    p = pl.program_id(0)
    pvals = p * PB + jax.lax.broadcasted_iota(jnp.int32, (PB, TB), 0)

    def body(s, acc):
        row = XT_ref[s]                       # [TB] int32 (sublane slice)
        return acc + jnp.where(row[None, :] < pvals, 1, 0)

    out_ref[...] = jax.lax.fori_loop(
        0, S, body, jnp.zeros((PB, TB), jnp.int32))


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


@functools.partial(jax.jit, static_argnames=("P",))
def monotone_count_pallas(X: jnp.ndarray, P: int) -> jnp.ndarray:
    """F[b, p] = sum_s (X[b, s] < p), int32[B, P].

    B must be a multiple of 128. Monotonicity of X is not actually
    required by the counting itself — only by callers interpreting F as
    a searchsorted result.
    """
    B, S = X.shape
    Pp = _round_up(P, PB)
    XT = X.T                                   # [S, B]
    kernel = functools.partial(_kernel, S=S)
    outT = pl.pallas_call(
        kernel,
        grid=(Pp // PB, B // TB),
        in_specs=[pl.BlockSpec((S, TB), lambda p, b: (0, b),
                               memory_space=pltpu.VMEM)],
        out_specs=pl.BlockSpec((PB, TB), lambda p, b: (p, b),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Pp, B), jnp.int32),
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(XT)
    return outT[:P].T


def monotone_count_xla(X: jnp.ndarray, P: int) -> jnp.ndarray:
    """Reference/fallback form (CPU tests, non-aligned shapes)."""
    pa = jnp.arange(P, dtype=jnp.int32)
    return jnp.sum(X[:, :, None] < pa[None, None, :], axis=1,
                   dtype=jnp.int32)
