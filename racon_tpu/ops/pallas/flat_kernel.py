"""Pallas TPU kernel for the full-width NW forward (flat.py semantics).

Layout: TB=128 jobs on sublanes, absolute target positions on lanes. The
target block is a *static* VMEM operand (no per-row rotation — see
PROFILE.md #6 for why the rolled banded variant was abandoned), the
previous-row state lives in a VMEM scratch across row-grid steps, and the
left-gap chain closes with log2(Lt) shift-max steps.

Bit-identical to flat.fw_dirs_xla (asserted in tests/test_flat.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

from racon_tpu.ops.cigar import DIAG, UP, LEFT

_NEG = -(2 ** 30)
TB = 128   # jobs per grid program
CH = 32    # query rows per grid step
from racon_tpu.ops.flat import U_SAT  # single source (= K_INS + 1)


def _kernel(tbuf_ref, qT_ref, dirs_ref, prev_ref, uprev_ref, cprev_ref, *,
            match, mismatch, gap, Lt):
    c = pl.program_id(1)
    jr = jax.lax.broadcasted_iota(jnp.int32, (TB, Lt), 1)
    jg = (jr + 1) * gap
    t32 = tbuf_ref[...]                    # [TB, Lt] int32 (static block)

    @pl.when(c == 0)
    def _():
        prev_ref[:] = jg                   # H[0][j] = j*gap
        uprev_ref[:] = jnp.zeros((TB, Lt), jnp.int32)
        cprev_ref[:] = jnp.full((TB, Lt), LEFT, jnp.int32)

    shifts = []
    k = 1
    while k < Lt:
        shifts.append(k)
        k *= 2

    def row(r, _):
        i = c * CH + r + 1                 # 1-based global row
        qrow = qT_ref[r]                   # [TB] int32
        sub = jnp.where(t32 == qrow[:, None], match, mismatch).astype(
            jnp.int32)
        P = prev_ref[:]
        Pshift = jnp.concatenate(
            [jnp.full((TB, 1), (i - 1) * gap, jnp.int32), P[:, :-1]], axis=1)
        diag = Pshift + sub
        up = P + gap
        tmp = jnp.maximum(diag, up)
        boundary = jnp.where(jr == 0, (i + 1) * gap, _NEG)
        f = jnp.maximum(tmp, boundary) - jg
        for s in shifts:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((TB, s), _NEG, jnp.int32), f[:, :-s]], axis=1))
        h = f + jg
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT))
        # UP-chain metadata (colwalk.py): in absolute coordinates the UP
        # predecessor (i-1, j) is the SAME lane of the previous row.
        isup = d == UP
        U = jnp.where(isup, jnp.minimum(uprev_ref[:] + 1, U_SAT), 0)
        C = jnp.where(isup, cprev_ref[:], d)
        dirs_ref[r] = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        uprev_ref[:] = U
        cprev_ref[:] = C
        prev_ref[:] = h
        return 0

    jax.lax.fori_loop(0, CH, row, 0)


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap",
                                             "interpret"))
def fw_dirs_pallas(tbuf: jnp.ndarray, qT: jnp.ndarray, *, match: int,
                   mismatch: int, gap: int,
                   interpret: bool = False) -> jnp.ndarray:
    """Direction tensor uint8[Lq, B, Lt].

    B must be a multiple of TB (128), Lq of CH (32), Lt of 128.
    ``interpret`` runs the kernel in Pallas interpreter mode so CPU
    tier-1 tests exercise the exact kernel body.
    """
    B, Lt = tbuf.shape
    Lq = qT.shape[0]
    kernel = functools.partial(_kernel, match=match, mismatch=mismatch,
                               gap=gap, Lt=Lt)
    return pl.pallas_call(
        kernel,
        grid=(B // TB, Lq // CH),
        in_specs=[
            pl.BlockSpec((TB, Lt), lambda b, c: (b, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, TB), lambda b, c: (c, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((CH, TB, Lt), lambda b, c: (c, b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Lq, B, Lt), jnp.uint8),
        scratch_shapes=[pltpu.VMEM((TB, Lt), jnp.int32),
                        pltpu.VMEM((TB, Lt), jnp.int32),
                        pltpu.VMEM((TB, Lt), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(tbuf.astype(jnp.int32), qT.astype(jnp.int32))
