"""Pallas TPU kernel: banded NW forward in per-lane diagonal coordinates.

The full-width kernel (flat_kernel.py) computes H over all Lt target
columns; at bench shapes its dirs tensor is the HBM ceiling (~1.5 GB per
refinement round, PROFILE.md #3). This kernel restricts each job to a
static-width diagonal band of W slots centered on its own length
difference — band column x of row i is target column

    j = i + klo_b + x,      klo_b = min(0, lt_b - lq_b) - wl_b,
    wl_b = (W - 1 - |lt_b - lq_b|) // 2

so the diag neighbour of (i, x) is (i-1, x) (same lane), the up
neighbour is (i-1, x+1) (static shift by one), and the left-gap chain
stays a lane-local cummax — no dynamic roll anywhere (pltpu.roll with a
dynamic shift corrupts >512-lane rows on this stack, PROFILE.md #6).
The per-lane geometry lives entirely in a pre-shifted target buffer
built by the caller:

    tband[b, y] = anchor_b[klo_b + y]   for y in [0, W + Lq)

(row i's window is tband[:, i-1 : i-1+W] — a row-uniform dynamic lane
slice). Out-of-matrix cells carry -inf-like scores so no in-band path
crosses them; cells right of each job's lt hold garbage the traceback
never visits (it starts at (lq, lt) and moves down-left), exactly like
the full-width kernel's padding story.

Exactness: the kernel also emits each lane's final row H[lq_b] (captured
when the row counter passes lq_b), from which the caller reads the
terminal score and applies the same provable escape bound as the native
aligner (racon_tpu/native/nw.cpp): any path leaving half-width w needs
more than |lt-lq| + 2(w+1) gap ops, so

    score >= max(m,0)*min(lq,lt) + g*(|lt-lq| + 2*wl + 2)

proves the banded optimum is the global optimum. Lanes that fail the
bound are flagged and their windows re-polished on the unbounded host
path (the ovf redo route in PoaEngine) — with w >= 128 and 500-base
windows this is a theoretical safety valve, not a hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.cigar import DIAG, UP, LEFT

_NEG = -(2 ** 30)
TB = 128   # jobs per grid program (sublanes)
CH = 32    # query rows per grid step


def _kernel(tbandT_ref, qT_ref, klo_ref, lq_ref, dirs_ref, hlast_ref,
            prev_ref, *, match, mismatch, gap, W):
    # Transposed layout: band slots x on SUBLANES, jobs on LANES. The
    # per-row moving target window is then a dynamic *sublane* slice
    # (supported by Mosaic at any offset), where the lane-major variant
    # would need a 128-aligned dynamic lane slice (rejected).
    c = pl.program_id(1)
    xr = jax.lax.broadcasted_iota(jnp.int32, (W, TB), 0)
    klo = klo_ref[0]                       # [TB] int32
    lqv = lq_ref[0]                        # [TB] int32

    @pl.when(c == 0)
    def _():
        # prev[x] = H[0][klo + x] = (klo+x)*gap where klo+x >= 0 (the
        # j = 0 column holds 0 = H[0][0]); cells left of j=0 are -inf.
        j0 = klo[None, :] + xr
        prev_ref[:] = jnp.where(j0 >= 0, j0 * gap, _NEG)
        hlast_ref[:] = jnp.where(j0 >= 0, j0 * gap, _NEG)

    def row(r, _):
        i = c * CH + r + 1                 # 1-based global row
        qrow = qT_ref[r]                   # [TB] int32
        tw = tbandT_ref[pl.dslice(i - 1, W), :]           # [W, TB] int32
        jcol = i + klo[None, :] + xr       # absolute target column j
        sub = jnp.where(tw == qrow[None, :], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, _NEG)  # no diag into j < 1
        P = prev_ref[:]
        diag = P + sub
        up = jnp.concatenate(
            [P[1:, :], jnp.full((1, TB), _NEG, jnp.int32)], axis=0) + gap
        tmp = jnp.maximum(diag, up)
        # j == 0 boundary column: H[i][0] = i*gap, entering at x0 = -i-klo.
        tmp = jnp.where(jcol == 0, i * gap, tmp)
        # Left-gap chain: shift-max ladder along sublanes (j grows with x).
        jg = jcol * gap
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((s, TB), _NEG // 2, jnp.int32), f[:-s, :]],
                    axis=0))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, _NEG)
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT)).astype(jnp.uint8)
        dirs_ref[r] = d
        prev_ref[:] = h
        # Capture each lane's true final row as the row counter passes it.
        hlast_ref[:] = jnp.where((lqv == i)[None, :], h, hlast_ref[:])
        return 0

    jax.lax.fori_loop(0, CH, row, 0)


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W"))
def fw_dirs_band(tband: jnp.ndarray, qT: jnp.ndarray, klo: jnp.ndarray,
                 lq: jnp.ndarray, *, match: int, mismatch: int, gap: int,
                 W: int):
    """Banded direction tensor + final-row scores (Pallas, transposed).

    Args:
      tband: int32[B, W + Lq] pre-shifted targets (see module docstring).
      qT:    uint8/int32[Lq, B] queries, transposed.
      klo:   int32[B] per-lane band origin.
      lq:    int32[B] per-lane query lengths (for final-row capture).

    Returns (dirs uint8[Lq, W, B], hlast int32[B, W]) — note dirs has
    band slots *before* jobs (kernel layout); fw_traceback_band takes
    ``transposed=True`` for it. hlast[b, x] = H[lq_b][lq_b + klo_b + x].
    B % 128 == 0, Lq % 32 == 0, W % 128 == 0 required.
    """
    B = tband.shape[0]
    Lq = qT.shape[0]
    kernel = functools.partial(_kernel, match=match, mismatch=mismatch,
                               gap=gap, W=W)
    dirs, hlast = pl.pallas_call(
        kernel,
        grid=(B // TB, Lq // CH),
        in_specs=[
            pl.BlockSpec((W + Lq, TB), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((CH, TB), lambda b, c: (c, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TB), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, TB), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((CH, W, TB), lambda b, c: (c, 0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((W, TB), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((Lq, W, B), jnp.uint8),
            jax.ShapeDtypeStruct((W, B), jnp.int32),
        ],
        scratch_shapes=[pltpu.VMEM((W, TB), jnp.int32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(tband.astype(jnp.int32).T, qT.astype(jnp.int32),
      klo[None, :], lq[None, :])
    return dirs, hlast.T


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W"))
def fw_dirs_band_xla(tband: jnp.ndarray, qT: jnp.ndarray, klo: jnp.ndarray,
                     lq: jnp.ndarray, *, match: int, mismatch: int,
                     gap: int, W: int):
    """Row-scan twin of fw_dirs_band (CPU tests / non-TPU fallback);
    bit-identical outputs by construction."""
    B = tband.shape[0]
    Lq = qT.shape[0]
    xr = jnp.arange(W, dtype=jnp.int32)[None, :]
    t32 = tband.astype(jnp.int32)
    j0 = klo[:, None] + xr
    P0 = jnp.where(j0 >= 0, j0 * gap, _NEG) + jnp.zeros_like(t32[:, :1])
    hl0 = P0

    def step(carry, inp):
        P, hl = carry
        i, qrow = inp
        tw = jax.lax.dynamic_slice_in_dim(t32, i - 1, W, axis=1)
        jcol = i + klo[:, None] + xr
        sub = jnp.where(tw == qrow[:, None], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, _NEG)
        diag = P + sub
        up = jnp.concatenate(
            [P[:, 1:], jnp.full((B, 1), _NEG, jnp.int32)], axis=1) + gap
        tmp = jnp.maximum(diag, up)
        tmp = jnp.where(jcol == 0, i * gap, tmp)
        jg = jcol * gap
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((B, s), _NEG // 2, jnp.int32), f[:, :-s]],
                    axis=1))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, _NEG)
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT)).astype(jnp.uint8)
        hl = jnp.where((lq == i)[:, None], h, hl)
        return (h, hl), d

    ii = jnp.arange(1, Lq + 1, dtype=jnp.int32)
    (_, hlast), dirs = jax.lax.scan(step, (P0, hl0),
                                    (ii, qT.astype(jnp.int32)))
    return dirs, hlast


def band_geometry(lq, lt, W: int):
    """Per-lane (klo, wl) for a W-slot band (all int32 vectors)."""
    delta = lt - lq
    wl = (W - 1 - jnp.abs(delta)) // 2
    klo = jnp.minimum(0, delta) - wl
    return klo, wl


def fw_traceback_band(dirs: jnp.ndarray, lq: jnp.ndarray, lt: jnp.ndarray,
                      klo: jnp.ndarray, steps: int,
                      transposed: bool = False):
    """Traceback over banded dirs: rev ops uint8[B, steps].

    Identical walk to flat.fw_traceback with the column index mapped to
    band coordinates x = j - i - klo per lane. ``transposed`` selects
    the Pallas kernel's [Lq, W, B] dirs layout (vs [Lq, B, W]).
    """
    if transposed:
        Lq, W, B = dirs.shape
    else:
        Lq, B, W = dirs.shape
    d1 = dirs.reshape(-1)
    lane = jnp.arange(B, dtype=jnp.int32)

    def step(state, _):
        i, j = state
        done = (i == 0) & (j == 0)
        x = jnp.clip(j - i - klo, 0, W - 1)
        if transposed:
            idx = (jnp.maximum(i - 1, 0) * (B * W) + x * B + lane)
        else:
            idx = (jnp.maximum(i - 1, 0) * (B * W) + lane * W + x)
        dv = jnp.take(d1, idx)
        d = jnp.where(done, 3,
                      jnp.where(i == 0, LEFT,
                                jnp.where(j == 0, UP, dv))).astype(jnp.uint8)
        i = i - jnp.where((d == DIAG) | (d == UP), 1, 0).astype(i.dtype)
        j = j - jnp.where((d == DIAG) | (d == LEFT), 1, 0).astype(j.dtype)
        return (i, j), d

    (_, _), rev_ops = jax.lax.scan(
        step, (lq.astype(jnp.int32), lt.astype(jnp.int32)), None,
        length=steps)
    return rev_ops.T
