"""Pallas TPU kernel: banded NW forward in per-lane diagonal coordinates.

The full-width kernel (flat_kernel.py) computes H over all Lt target
columns; at bench shapes its dirs tensor is the HBM ceiling (~1.5 GB per
refinement round, PROFILE.md #3). This kernel restricts each job to a
static-width diagonal band of W slots centered on its own length
difference — band column x of row i is target column

    j = i + klo_b + x,      klo_b = min(0, lt_b - lq_b) - wl_b,
    wl_b = (W - 1 - |lt_b - lq_b|) // 2

so the diag neighbour of (i, x) is (i-1, x) (same lane), the up
neighbour is (i-1, x+1) (static shift by one), and the left-gap chain
stays a lane-local cummax — no dynamic roll anywhere (pltpu.roll with a
dynamic shift corrupts >512-lane rows on this stack, PROFILE.md #6).
The per-lane geometry lives entirely in a pre-shifted target buffer
built by the caller:

    tband[b, y] = anchor_b[klo_b + y]   for y in [0, W + Lq)

(row i's window is tband[:, i-1 : i-1+W] — a row-uniform dynamic lane
slice). Out-of-matrix cells carry -inf-like scores so no in-band path
crosses them; cells right of each job's lt hold garbage the traceback
never visits (it starts at (lq, lt) and moves down-left), exactly like
the full-width kernel's padding story.

Exactness: the kernel also emits each lane's final row H[lq_b] (captured
when the row counter passes lq_b), from which the caller reads the
terminal score and applies the same provable escape bound as the native
aligner (racon_tpu/native/nw.cpp): any path leaving half-width w needs
more than |lt-lq| + 2(w+1) gap ops, so

    score >= max(m,0)*min(lq,lt) + g*(|lt-lq| + 2*wl + 2)

proves the banded optimum is the global optimum. Lanes that fail the
bound are flagged and their windows re-polished on the unbounded host
path (the ovf redo route in PoaEngine) — with w >= 128 and 500-base
windows this is a theoretical safety valve, not a hot path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

from racon_tpu.ops.cigar import DIAG, UP, LEFT

_NEG = -(2 ** 30)
_NEG16 = -16384  # int16 kernel's -inf (see _score_dtype for the proof)
TB = 128   # jobs per grid program (sublanes)
CH = 32    # query rows per grid step
from racon_tpu.ops.flat import U_SAT  # single source (= K_INS + 1)


def uc_boundary(nxt_k: int = 2) -> int:
    """Packed row-0 / out-of-band frontier fill for a ``nxt_k``-deep
    predecessor plane: every 6-bit hop field (and the base (U, C) pair)
    decodes as (up_run 0, consumer LEFT) — the values the walk is forced
    to at the matrix boundary anyway. k=2 packs ``(N1 << 6) | (U << 2) |
    C`` (12 bits, the PR 5 layout); k=4 extends to ``(N3 << 18) |
    (N2 << 12) | (N1 << 6) | (U << 2) | C`` (24 bits)."""
    v = LEFT
    for _ in range(max(int(nxt_k) - 1, 1)):
        v = (v << 6) | LEFT
    return v


def _score_dtype(match: int, mismatch: int, gap: int, Lq: int, W: int):
    """int16 when every DP intermediate provably fits, else int32.

    The int16 scheme uses NEG16 = -16384 with one extra clamp (see
    _kernel): masked-cell chains stay <= NEG16 + gap at any real cell, so
    results are bit-identical to the int32 kernel as long as
      |jcol| * |gap| <= 16383   (jg magnitude; |jcol| <= Lq + W)
      match * Lq + (Lq + W) * |gap| <= 32767  (f = tmp - jg upper bound)
    hold — halving VPU register traffic for the whole forward pass.
    """
    a = max(abs(match), abs(mismatch), abs(gap))
    jgmax = (Lq + W) * abs(gap)
    if jgmax <= 16383 and max(match, 0) * Lq + a * Lq + jgmax <= 32767:
        # DISABLED: Mosaic on this stack cannot legalize vector int16
        # max ('failed to legalize operation arith.maxsi'), and the DP
        # row is max-heavy (11 of ~19 vector ops), so a compare+select
        # emulation would give back most of the halved register traffic.
        # Shape analysis kept for a future stack where i16 max lowers.
        return jnp.int32
    return jnp.int32


def _kernel(tbandT_ref, qT_ref, klo_ref, lq_ref, *refs, match, mismatch,
            gap, W, dtype, TB, CH, nxt_k=2):
    # Transposed layout: band slots x on SUBLANES, jobs on LANES. The
    # per-row moving target window is then a dynamic *sublane* slice
    # (supported by Mosaic at any offset), where the lane-major variant
    # would need a 128-aligned dynamic lane slice (rejected).
    if nxt_k >= 4:
        dirs_ref, nxt_ref, nxt2_ref, hlast_ref, prev_ref, ucprev_ref = refs
    else:
        dirs_ref, nxt_ref, hlast_ref, prev_ref, ucprev_ref = refs
        nxt2_ref = None
    c = pl.program_id(1)
    NEG = _NEG16 if dtype == jnp.int16 else _NEG   # Python int: inlines
    BND = uc_boundary(nxt_k)               # Python int: inlines
    xr = jax.lax.broadcasted_iota(jnp.int32, (W, TB), 0)
    klo = klo_ref[0]                       # [TB] int32
    lqv = lq_ref[0]                        # [TB] int32

    @pl.when(c == 0)
    def _():
        # prev[x] = H[0][klo + x] = (klo+x)*gap where klo+x >= 0 (the
        # j = 0 column holds 0 = H[0][0]); cells left of j=0 are -inf.
        j0 = klo[None, :] + xr
        init = jnp.where(j0 >= 0, j0 * gap, NEG).astype(dtype)
        prev_ref[:] = init
        hlast_ref[:] = init
        # UP-chain metadata boundary (row 0): no UP can start above row 1,
        # and a chain that reaches row 0 is consumed by the forced LEFT
        # walk along the top row — encode that as consumer dir LEFT.
        # N, U and C share one packed scratch (N << 6 | U << 2 | C,
        # extended by the N2/N3 hop fields at nxt_k=4): a long-read
        # overlap chunk's VMEM budget is tight (ovl_align), and
        # separate buffers cost another (W, TB) i32 block each. Row-0 N
        # is (U=0, C=LEFT) — the walk's forced top-row values — matching
        # what a reader at row 0 would be forced to anyway.
        ucprev_ref[:] = jnp.full((W, TB), BND, jnp.int32)

    def row(r, _):
        i = c * CH + r + 1                 # 1-based global row
        qrow = qT_ref[r]                   # [TB] int32
        # (int32 tband: Mosaic requires 8-aligned dynamic sublane
        # slices for narrower dtypes, and i - 1 is arbitrary.)
        tw = tbandT_ref[pl.dslice(i - 1, W), :]
        jcol = i + klo[None, :] + xr       # absolute target column j
        sub = jnp.where(tw == qrow[None, :], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, NEG).astype(dtype)
        P = prev_ref[:]
        diag = P + sub                     # >= 2*NEG, exactly int16-min
        up = jnp.concatenate(
            [P[1:, :], jnp.full((1, TB), NEG, dtype)], axis=0) + \
            jnp.asarray(gap, dtype)
        tmp = jnp.maximum(diag, up)
        # j == 0 boundary column: H[i][0] = i*gap, entering at x0 = -i-klo.
        tmp = jnp.where(jcol == 0, i * gap, tmp).astype(dtype)
        # Clamp before the jg subtraction: masked cells carry 2*NEG and
        # would wrap int16 under "- jg" for negative jcol. Real cells are
        # far above NEG, and clamped masked chains still lose at every
        # real cell by >= |gap| (see _score_dtype).
        tmp = jnp.maximum(tmp, jnp.asarray(NEG, dtype))
        # Left-gap chain: shift-max ladder along sublanes (j grows with x).
        jg = (jcol * gap).astype(dtype)
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((s, TB), NEG, dtype), f[:-s, :]],
                    axis=0))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, NEG).astype(dtype)
        # The direction select stays in the score dtype end to end: a
        # mask from an int16 compare selecting int32 scalars needs an i1
        # relayout Mosaic rejects ("Invalid relayout ... vector<...xi1>"),
        # while same-width select + one plain convert lowers cleanly.
        d = jnp.where(h == diag, jnp.asarray(DIAG, dtype),
                      jnp.where(h == up, jnp.asarray(UP, dtype),
                                jnp.asarray(LEFT, dtype))).astype(jnp.int32)
        # UP-chain metadata for the column-walk traceback (colwalk.py):
        # cell (i, j)'s UP predecessor is (i-1, j) = band slot x+1 of the
        # previous row, so chains run along the +1 sublane shift. U counts
        # the chain length into this cell (saturating at U_SAT; saturated
        # lanes are re-polished on the host path), C carries the chain
        # top's consumer direction down the chain.
        isup = d == UP
        ucp = ucprev_ref[:]
        ucup = jnp.concatenate(
            [ucp[1:, :], jnp.full((1, TB), BND, jnp.int32)],
            axis=0)
        U = jnp.where(isup, jnp.minimum(((ucup >> 2) & 0xF) + 1, U_SAT), 0)
        C = jnp.where(isup, ucup & 3, d)
        # k-step predecessor metadata (the extra output planes): hop
        # field m is uc_m = the packed (U' << 2 | C') of pred^m — the
        # cell the walk visits after undoing m [UP run][consumer]
        # blocks, where pred^1 of (i, j) is (i - U - (C==DIAG), j - 1).
        # One gather then undoes nxt_k target columns (docs/KERNELS.md).
        # Each hop propagates by the same three static shifts, reading
        # the PREVIOUS hop's field (uc_m(cell) = uc_{m-1}(pred^1(cell))):
        #   UP:   inherit field m from the cell above (the whole chain
        #         shares its chain top's undo target, so pred^1 — and
        #         hence every deeper pred — is chain-invariant),
        #   DIAG: predecessor is (i-1, j-1) = prev row, same slot, so
        #         field m comes from the prev row's field m-1,
        #   LEFT: predecessor is (i, j-1) = this row, slot x-1: shift of
        #         this row's just-finalized field m-1 (U and C are
        #         finalized for the whole row before these selects).
        # Slot-0 LEFT reads a boundary fill — out-of-band predecessors
        # only occur on paths that fail the escape bound (redo route).
        ucnow = (U << 2) + C
        nleft = jnp.concatenate(
            [jnp.full((1, TB), LEFT, jnp.int32), ucnow[:-1, :]], axis=0)
        N = jnp.where(isup, (ucup >> 6) & 0x3F,
                      jnp.where(d == DIAG, ucp & 0x3F, nleft))
        dirs_ref[r] = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        nxt_ref[r] = N.astype(jnp.uint8)
        if nxt_k >= 4:
            n1left = jnp.concatenate(
                [jnp.full((1, TB), LEFT, jnp.int32), N[:-1, :]], axis=0)
            N2 = jnp.where(isup, (ucup >> 12) & 0x3F,
                           jnp.where(d == DIAG, (ucp >> 6) & 0x3F, n1left))
            n2left = jnp.concatenate(
                [jnp.full((1, TB), LEFT, jnp.int32), N2[:-1, :]], axis=0)
            N3 = jnp.where(isup, (ucup >> 18) & 0x3F,
                           jnp.where(d == DIAG, (ucp >> 12) & 0x3F, n2left))
            # u16 plane: hop 2 in the low byte, hop 3 in the high byte
            # (byte-aligned so the walk decodes without cross-byte
            # shifts beyond one >> 8).
            nxt2_ref[r] = ((N3 << 8) + N2).astype(jnp.uint16)
            ucprev_ref[:] = (N3 << 18) + (N2 << 12) + (N << 6) + ucnow
        else:
            ucprev_ref[:] = (N << 6) + ucnow
        prev_ref[:] = h
        # Capture each lane's true final row as the row counter passes it.
        hlast_ref[:] = jnp.where((lqv == i)[None, :], h, hlast_ref[:])
        return 0

    jax.lax.fori_loop(0, CH, row, 0)


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W",
                                    "tb", "ch", "interpret", "nxt_k"))
def fw_dirs_band(tband: jnp.ndarray, qT: jnp.ndarray, klo: jnp.ndarray,
                 lq: jnp.ndarray, *, match: int, mismatch: int, gap: int,
                 W: int, tb: int = TB, ch: int = CH,
                 interpret: bool = False, nxt_k: int = 2):
    """Banded packed-cell tensors + final-row scores (Pallas, transposed).

    Args:
      tband: int32[B, W + Lq] pre-shifted targets (see module docstring).
      qT:    uint8/int32[Lq, B] queries, transposed.
      klo:   int32[B] per-lane band origin.
      lq:    int32[B] per-lane query lengths (for final-row capture).

    Returns (cells uint8[Lq, W, B], nxt uint8[Lq, W, B],
    hlast int32[B, W]) — note cells/nxt have band slots *before* jobs
    (kernel layout); fw_traceback_band takes ``transposed=True`` for it.
    hlast[b, x] = H[lq_b][lq_b + klo_b + x].
    Each cell byte packs ``dir | consumer_dir << 2 | up_run << 4``; the
    matching ``nxt`` byte packs the predecessor cell's
    ``consumer_dir | up_run << 2`` so one traceback gather undoes TWO
    target columns (see racon_tpu/ops/colwalk.py for the walk and
    docs/KERNELS.md for the contract; the plain direction is the low 2
    bits of the cell byte). With ``nxt_k=4`` a THIRD plane rides along —
    ``nxt2`` uint16[Lq, W, B] packing hops 2 and 3 (low/high byte) so
    one gather undoes FOUR target columns; the return becomes
    (cells, nxt, nxt2, hlast). B % tb == 0, Lq % ch == 0 required.
    ``tb``/``ch`` tile the lane/row grid: the defaults suit
    consensus-window shapes; long-read overlap alignment (W in the
    thousands, racon_tpu/ops/ovl_align.py) passes smaller tiles so the
    per-lane (W + Lq) target window plus scratch stays inside the
    ~16 MiB VMEM budget (racon_tpu/ops/budget.py::vmem_est).
    ``interpret`` runs the kernel in Pallas interpreter mode so CPU
    tier-1 tests exercise the exact kernel body (tests/
    test_kernels_interpret.py).
    """
    B = tband.shape[0]
    Lq = qT.shape[0]
    dtype = _score_dtype(match, mismatch, gap, Lq, W)
    kernel = functools.partial(_kernel, match=match, mismatch=mismatch,
                               gap=gap, W=W, dtype=dtype, TB=tb, CH=ch,
                               nxt_k=nxt_k)
    plane_spec = pl.BlockSpec((ch, W, tb), lambda b, c: (c, 0, b),
                              memory_space=pltpu.VMEM)
    out_specs = [plane_spec, plane_spec]
    out_shape = [jax.ShapeDtypeStruct((Lq, W, B), jnp.uint8),
                 jax.ShapeDtypeStruct((Lq, W, B), jnp.uint8)]
    if nxt_k >= 4:
        out_specs.append(plane_spec)
        out_shape.append(jax.ShapeDtypeStruct((Lq, W, B), jnp.uint16))
    out_specs.append(pl.BlockSpec((W, tb), lambda b, c: (0, b),
                                  memory_space=pltpu.VMEM))
    out_shape.append(jax.ShapeDtypeStruct((W, B), dtype))
    outs = pl.pallas_call(
        kernel,
        grid=(B // tb, Lq // ch),
        in_specs=[
            pl.BlockSpec((W + Lq, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, tb), lambda b, c: (c, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=[pltpu.VMEM((W, tb), dtype),
                        pltpu.VMEM((W, tb), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(tband.astype(jnp.int32).T, qT.astype(jnp.int32),
      klo[None, :], lq[None, :])
    if nxt_k >= 4:
        dirs, nxt, nxt2, hlast = outs
        return dirs, nxt, nxt2, hlast.T.astype(jnp.int32)
    dirs, nxt, hlast = outs
    return dirs, nxt, hlast.T.astype(jnp.int32)


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W",
                                    "nxt_k"))
def fw_dirs_band_xla(tband: jnp.ndarray, qT: jnp.ndarray, klo: jnp.ndarray,
                     lq: jnp.ndarray, *, match: int, mismatch: int,
                     gap: int, W: int, nxt_k: int = 2):
    """Row-scan twin of fw_dirs_band (CPU tests / non-TPU fallback);
    bit-identical outputs by construction (same score dtype selection,
    fills and clamps as the Pallas kernel). ``nxt_k=4`` adds the
    ``nxt2`` uint16 plane to the return, like the Pallas entry point."""
    B = tband.shape[0]
    Lq = qT.shape[0]
    dtype = _score_dtype(match, mismatch, gap, Lq, W)
    NEG = _NEG16 if dtype == jnp.int16 else _NEG
    xr = jnp.arange(W, dtype=jnp.int32)[None, :]
    t32 = tband.astype(jnp.int32)
    j0 = klo[:, None] + xr
    P0 = (jnp.where(j0 >= 0, j0 * gap, NEG) +
          jnp.zeros_like(t32[:, :1])).astype(dtype)
    hl0 = P0
    U0 = jnp.zeros((B, W), jnp.int32)
    C0 = jnp.full((B, W), LEFT, jnp.int32)
    N0 = jnp.full((B, W), LEFT, jnp.int32)
    deep = nxt_k >= 4
    hops0 = (N0, N0) if deep else ()

    def step(carry, inp):
        P, hl, Up, Cp, Np, *hp = carry
        i, qrow = inp
        tw = jax.lax.dynamic_slice_in_dim(t32, i - 1, W, axis=1)
        jcol = i + klo[:, None] + xr
        sub = jnp.where(tw == qrow[:, None], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, NEG).astype(dtype)
        diag = P + sub
        up = jnp.concatenate(
            [P[:, 1:], jnp.full((B, 1), NEG, dtype)], axis=1) + \
            jnp.asarray(gap, dtype)
        tmp = jnp.maximum(diag, up)
        tmp = jnp.where(jcol == 0, i * gap, tmp).astype(dtype)
        tmp = jnp.maximum(tmp, jnp.asarray(NEG, dtype))
        jg = (jcol * gap).astype(dtype)
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((B, s), NEG, dtype), f[:, :-s]],
                    axis=1))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, NEG).astype(dtype)
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT))
        isup = d == UP

        def shift_up(A, fill):
            return jnp.concatenate(
                [A[:, 1:], jnp.full((B, 1), fill, jnp.int32)], axis=1)

        def shift_left(A):
            return jnp.concatenate(
                [jnp.full((B, 1), LEFT, jnp.int32), A[:, :-1]], axis=1)

        uup = shift_up(Up, 0)
        cup = shift_up(Cp, LEFT)
        nup = shift_up(Np, LEFT)
        U = jnp.where(isup, jnp.minimum(uup + 1, U_SAT), 0)
        C = jnp.where(isup, cup, d)
        # k-step predecessor metadata — same three-shift propagation as
        # the Pallas kernel (see _kernel): UP inherits field m from
        # above, DIAG takes the previous row's same-slot field m-1,
        # LEFT this row's just-computed field m-1 at slot x-1.
        ucnow = (U << 2) + C
        N = jnp.where(isup, nup,
                      jnp.where(d == DIAG, (Up << 2) + Cp, shift_left(ucnow)))
        packed = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        hl = jnp.where((lq == i)[:, None], h, hl)
        # ONE stacked uint8 ys (not a tuple): a scan emitting a TUPLE of
        # narrow-dtype ys miscompiles under XLA CPU jit in jax 0.9 (the
        # reverse-scan int16 variant is the verified case, see
        # racon_tpu/ops/colwalk.py) — don't gamble on the forward form.
        # At nxt_k=4 the hop-2/3 bytes ride the same stacked u8 ys; the
        # u16 nxt2 plane is assembled OUTSIDE the scan.
        if deep:
            N2p, N3p = hp
            N2 = jnp.where(isup, shift_up(N2p, LEFT),
                           jnp.where(d == DIAG, Np, shift_left(N)))
            N3 = jnp.where(isup, shift_up(N3p, LEFT),
                           jnp.where(d == DIAG, N2p, shift_left(N2)))
            ys = jnp.stack([packed, N.astype(jnp.uint8),
                            N2.astype(jnp.uint8), N3.astype(jnp.uint8)],
                           axis=0)
            return (h, hl, U, C, N, N2, N3), ys
        return (h, hl, U, C, N), jnp.stack(
            [packed, N.astype(jnp.uint8)], axis=0)

    ii = jnp.arange(1, Lq + 1, dtype=jnp.int32)
    carry0 = (P0, hl0, U0, C0, N0) + hops0
    carry, ys = jax.lax.scan(step, carry0, (ii, qT.astype(jnp.int32)))
    hlast = carry[1]
    if deep:
        nxt2 = (ys[:, 2].astype(jnp.uint16) |
                (ys[:, 3].astype(jnp.uint16) << 8))
        return ys[:, 0], ys[:, 1], nxt2, hlast.astype(jnp.int32)
    return ys[:, 0], ys[:, 1], hlast.astype(jnp.int32)


UC_BOUNDARY = uc_boundary(2)   # row-0 / out-of-band packed (N,U,C)


def _kernel_tile(tbandT_ref, qT_ref, klo_ref, lq_ref, i0_ref, pin_ref,
                 ucin_ref, hlin_ref, *refs, match, mismatch, gap, W,
                 dtype, TB, CH, nxt_k=2):
    # Tiled variant of _kernel for the ultralong overlap path: identical
    # row recurrence, but rows are numbered from a runtime tile origin
    # i0 (so ONE compiled kernel serves every tile of a lax.scan over
    # tiles), and the DP frontier — last band row of scores, packed
    # (N << 6 | U << 2 | C) metadata, and the captured hlast — enters as
    # inputs and leaves as outputs instead of being scratch-initialized.
    # Kept as a separate body rather than a parameterization of _kernel:
    # the untiled kernel is the consensus path's pinned production
    # kernel, and this stack's Mosaic quirks (PROFILE.md "Platform
    # findings") make "refactor shared, hope TPU lowering is unchanged"
    # a bad trade against ~60 duplicated lines.
    if nxt_k >= 4:
        dirs_ref, nxt_ref, nxt2_ref, hlast_ref, prev_ref, ucprev_ref = refs
    else:
        dirs_ref, nxt_ref, hlast_ref, prev_ref, ucprev_ref = refs
        nxt2_ref = None
    c = pl.program_id(1)
    NEG = _NEG16 if dtype == jnp.int16 else _NEG
    BND = uc_boundary(nxt_k)
    xr = jax.lax.broadcasted_iota(jnp.int32, (W, TB), 0)
    klo = klo_ref[0]                       # [TB] int32 (this tile's band)
    lqv = lq_ref[0]                        # [TB] int32
    i0 = i0_ref[0][None, :]                # (1, TB) int32 tile row origin

    @pl.when(c == 0)
    def _():
        prev_ref[:] = pin_ref[:]
        ucprev_ref[:] = ucin_ref[:]
        hlast_ref[:] = hlin_ref[:]

    def row(r, _):
        rl = c * CH + r + 1                # 1-based row within the tile
        i = i0 + rl                        # (1, TB) global 1-based row
        qrow = qT_ref[r]                   # [TB] int32
        tw = tbandT_ref[pl.dslice(rl - 1, W), :]
        jcol = i + klo[None, :] + xr       # absolute target column j
        sub = jnp.where(tw == qrow[None, :], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, NEG).astype(dtype)
        P = prev_ref[:]
        diag = P + sub
        up = jnp.concatenate(
            [P[1:, :], jnp.full((1, TB), NEG, dtype)], axis=0) + \
            jnp.asarray(gap, dtype)
        tmp = jnp.maximum(diag, up)
        tmp = jnp.where(jcol == 0, i * gap, tmp).astype(dtype)
        tmp = jnp.maximum(tmp, jnp.asarray(NEG, dtype))
        jg = (jcol * gap).astype(dtype)
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((s, TB), NEG, dtype), f[:-s, :]],
                    axis=0))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, NEG).astype(dtype)
        d = jnp.where(h == diag, jnp.asarray(DIAG, dtype),
                      jnp.where(h == up, jnp.asarray(UP, dtype),
                                jnp.asarray(LEFT, dtype))).astype(jnp.int32)
        isup = d == UP
        ucp = ucprev_ref[:]
        ucup = jnp.concatenate(
            [ucp[1:, :], jnp.full((1, TB), BND, jnp.int32)],
            axis=0)
        U = jnp.where(isup, jnp.minimum(((ucup >> 2) & 0xF) + 1, U_SAT), 0)
        C = jnp.where(isup, ucup & 3, d)
        ucnow = (U << 2) + C
        nleft = jnp.concatenate(
            [jnp.full((1, TB), LEFT, jnp.int32), ucnow[:-1, :]], axis=0)
        N = jnp.where(isup, (ucup >> 6) & 0x3F,
                      jnp.where(d == DIAG, ucp & 0x3F, nleft))
        dirs_ref[r] = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        nxt_ref[r] = N.astype(jnp.uint8)
        if nxt_k >= 4:
            n1left = jnp.concatenate(
                [jnp.full((1, TB), LEFT, jnp.int32), N[:-1, :]], axis=0)
            N2 = jnp.where(isup, (ucup >> 12) & 0x3F,
                           jnp.where(d == DIAG, (ucp >> 6) & 0x3F, n1left))
            n2left = jnp.concatenate(
                [jnp.full((1, TB), LEFT, jnp.int32), N2[:-1, :]], axis=0)
            N3 = jnp.where(isup, (ucup >> 18) & 0x3F,
                           jnp.where(d == DIAG, (ucp >> 12) & 0x3F, n2left))
            nxt2_ref[r] = ((N3 << 8) + N2).astype(jnp.uint16)
            ucprev_ref[:] = (N3 << 18) + (N2 << 12) + (N << 6) + ucnow
        else:
            ucprev_ref[:] = (N << 6) + ucnow
        prev_ref[:] = h
        hlast_ref[:] = jnp.where(lqv[None, :] == i, h, hlast_ref[:])
        return 0

    jax.lax.fori_loop(0, CH, row, 0)


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W",
                                    "tb", "ch", "interpret", "nxt_k"))
def fw_dirs_band_tile(tband: jnp.ndarray, qT: jnp.ndarray,
                      klo: jnp.ndarray, lq: jnp.ndarray, i0: jnp.ndarray,
                      prev: jnp.ndarray, uc: jnp.ndarray,
                      hlast: jnp.ndarray, *, match: int, mismatch: int,
                      gap: int, W: int, tb: int = TB, ch: int = CH,
                      interpret: bool = False, nxt_k: int = 2):
    """One query-axis tile of the banded forward with an explicit DP
    frontier (Pallas).

    Args:
      tband: uint8/int32[B, W + T] targets pre-shifted for THIS tile:
             ``tband[b, y] = target_b[klo_b + i0_b + y]`` (fill 7).
      qT:    uint8/int32[T, B] this tile's query rows, transposed.
      klo:   int32[B] this tile's band origin (may differ per tile after
             re-centering; ops/ovl_align.py records the per-tile values
             for the stitched column walk).
      lq/i0: int32[B] query lengths / 0-based global row origin of the
             tile (rows i0+1 .. i0+T are computed; i0 is identical
             across lanes of one dispatch but ships as a lane vector so
             the kernel stays shape-stable under lax.scan).
      prev/uc/hlast: int32[B, W] carried frontier — H[i0] over the band,
             the packed ``(N << 6) | (U << 2) | C`` metadata of row i0
             (extended by the ``(N3 << 18) | (N2 << 12)`` hop fields at
             ``nxt_k=4``), and the running final-row capture. For tile 0
             the caller passes the same init the untiled kernel builds
             internally (j0*gap / uc_boundary(nxt_k) / init), making a
             single-tile call bit-identical to :func:`fw_dirs_band`.

    Returns (cells uint8[T, W, B], nxt uint8[T, W, B], hlast int32[B, W],
    prev int32[B, W], uc int32[B, W]) — the trailing three are the
    frontier after row i0+T, in the SAME band coordinates as the input
    (the caller shifts them when it re-centers klo for the next tile).
    With ``nxt_k=4`` the ``nxt2`` uint16[T, W, B] plane is inserted
    after ``nxt`` (6 outputs). Scores are always int32: frontier
    magnitudes grow with the GLOBAL query length, which this per-tile
    entry point cannot bound.
    """
    B = tband.shape[0]
    T = qT.shape[0]
    dtype = jnp.int32
    kernel = functools.partial(_kernel_tile, match=match,
                               mismatch=mismatch, gap=gap, W=W,
                               dtype=dtype, TB=tb, CH=ch, nxt_k=nxt_k)
    plane_spec = pl.BlockSpec((ch, W, tb), lambda b, c: (c, 0, b),
                              memory_space=pltpu.VMEM)
    # Frontier outputs persist across the sequential c steps via the
    # constant index map — same contract the untiled kernel's hlast
    # output already relies on.
    front_spec = pl.BlockSpec((W, tb), lambda b, c: (0, b),
                              memory_space=pltpu.VMEM)
    out_specs = [plane_spec, plane_spec]
    out_shape = [jax.ShapeDtypeStruct((T, W, B), jnp.uint8),
                 jax.ShapeDtypeStruct((T, W, B), jnp.uint8)]
    if nxt_k >= 4:
        out_specs.append(plane_spec)
        out_shape.append(jax.ShapeDtypeStruct((T, W, B), jnp.uint16))
    out_specs += [front_spec, front_spec, front_spec]
    out_shape += [jax.ShapeDtypeStruct((W, B), dtype),
                  jax.ShapeDtypeStruct((W, B), dtype),
                  jax.ShapeDtypeStruct((W, B), jnp.int32)]
    outs = pl.pallas_call(
        kernel,
        grid=(B // tb, T // ch),
        in_specs=[
            pl.BlockSpec((W + T, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((ch, tb), lambda b, c: (c, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, tb), lambda b, c: (0, b),
                         memory_space=pltpu.VMEM),
            front_spec,
            front_spec,
            front_spec,
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
        interpret=interpret,
    )(tband.astype(jnp.int32).T, qT.astype(jnp.int32),
      klo[None, :], lq[None, :], i0[None, :],
      prev.astype(dtype).T, uc.astype(jnp.int32).T,
      hlast.astype(dtype).T)
    if nxt_k >= 4:
        dirs, nxt, nxt2, hl, pout, ucout = outs
        return (dirs, nxt, nxt2, hl.T.astype(jnp.int32),
                pout.T.astype(jnp.int32), ucout.T)
    dirs, nxt, hl, pout, ucout = outs
    return (dirs, nxt, hl.T.astype(jnp.int32), pout.T.astype(jnp.int32),
            ucout.T)


@functools.partial(jax.jit,
                   static_argnames=("match", "mismatch", "gap", "W",
                                    "nxt_k"))
def fw_dirs_band_xla_tile(tband: jnp.ndarray, qT: jnp.ndarray,
                          klo: jnp.ndarray, lq: jnp.ndarray,
                          i0: jnp.ndarray, prev: jnp.ndarray,
                          uc: jnp.ndarray, hlast: jnp.ndarray, *,
                          match: int, mismatch: int, gap: int, W: int,
                          nxt_k: int = 2):
    """Row-scan twin of fw_dirs_band_tile (CPU tests / non-TPU
    fallback); bit-identical outputs by construction. Cells/nxt come
    back [T, B, W] (vs the kernel's [T, W, B]), like the untiled pair.
    ``nxt_k=4`` inserts the ``nxt2`` uint16 plane after ``nxt``.
    """
    B = tband.shape[0]
    T = qT.shape[0]
    dtype = jnp.int32
    NEG = _NEG
    xr = jnp.arange(W, dtype=jnp.int32)[None, :]
    t32 = tband.astype(jnp.int32)
    P0 = prev.astype(dtype)
    hl0 = hlast.astype(dtype)
    U0 = (uc >> 2) & 0xF
    C0 = uc & 3
    N0 = (uc >> 6) & 0x3F
    deep = nxt_k >= 4
    hops0 = ((uc >> 12) & 0x3F, (uc >> 18) & 0x3F) if deep else ()

    def step(carry, inp):
        P, hl, Up, Cp, Np, *hp = carry
        rl, qrow = inp
        i = (i0 + rl)[:, None]             # (B, 1) global 1-based row
        tw = jax.lax.dynamic_slice_in_dim(t32, rl - 1, W, axis=1)
        jcol = i + klo[:, None] + xr
        sub = jnp.where(tw == qrow[:, None], match, mismatch)
        sub = jnp.where(jcol >= 1, sub, NEG).astype(dtype)
        diag = P + sub
        up = jnp.concatenate(
            [P[:, 1:], jnp.full((B, 1), NEG, dtype)], axis=1) + \
            jnp.asarray(gap, dtype)
        tmp = jnp.maximum(diag, up)
        tmp = jnp.where(jcol == 0, i * gap, tmp).astype(dtype)
        tmp = jnp.maximum(tmp, jnp.asarray(NEG, dtype))
        jg = (jcol * gap).astype(dtype)
        f = tmp - jg
        s = 1
        while s < W:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((B, s), NEG, dtype), f[:, :-s]],
                    axis=1))
            s *= 2
        h = f + jg
        h = jnp.where(jcol >= 0, h, NEG).astype(dtype)
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT))
        isup = d == UP

        def shift_up(A, fill):
            return jnp.concatenate(
                [A[:, 1:], jnp.full((B, 1), fill, jnp.int32)], axis=1)

        def shift_left(A):
            return jnp.concatenate(
                [jnp.full((B, 1), LEFT, jnp.int32), A[:, :-1]], axis=1)

        uup = shift_up(Up, 0)
        cup = shift_up(Cp, LEFT)
        nup = shift_up(Np, LEFT)
        U = jnp.where(isup, jnp.minimum(uup + 1, U_SAT), 0)
        C = jnp.where(isup, cup, d)
        ucnow = (U << 2) + C
        N = jnp.where(isup, nup,
                      jnp.where(d == DIAG, (Up << 2) + Cp,
                                shift_left(ucnow)))
        packed = (d + (C << 2) + (U << 4)).astype(jnp.uint8)
        hl = jnp.where((lq == i[:, 0])[:, None], h, hl)
        if deep:
            N2p, N3p = hp
            N2 = jnp.where(isup, shift_up(N2p, LEFT),
                           jnp.where(d == DIAG, Np, shift_left(N)))
            N3 = jnp.where(isup, shift_up(N3p, LEFT),
                           jnp.where(d == DIAG, N2p, shift_left(N2)))
            ys = jnp.stack([packed, N.astype(jnp.uint8),
                            N2.astype(jnp.uint8), N3.astype(jnp.uint8)],
                           axis=0)
            return (h, hl, U, C, N, N2, N3), ys
        return (h, hl, U, C, N), jnp.stack(
            [packed, N.astype(jnp.uint8)], axis=0)

    ii = jnp.arange(1, T + 1, dtype=jnp.int32)
    carry, ys = jax.lax.scan(
        step, (P0, hl0, U0, C0, N0) + hops0, (ii, qT.astype(jnp.int32)))
    if deep:
        Pf, hlf, Uf, Cf, Nf, N2f, N3f = carry
        ucout = ((N3f << 18) + (N2f << 12) + (Nf << 6) + (Uf << 2) + Cf)
        nxt2 = (ys[:, 2].astype(jnp.uint16) |
                (ys[:, 3].astype(jnp.uint16) << 8))
        return (ys[:, 0], ys[:, 1], nxt2, hlf.astype(jnp.int32),
                Pf.astype(jnp.int32), ucout)
    Pf, hlf, Uf, Cf, Nf = carry
    ucout = (Nf << 6) + (Uf << 2) + Cf
    return (ys[:, 0], ys[:, 1], hlf.astype(jnp.int32),
            Pf.astype(jnp.int32), ucout)


def band_geometry(lq, lt, W: int):
    """Per-lane (klo, wl) for a W-slot band (all int32 vectors)."""
    delta = lt - lq
    wl = (W - 1 - jnp.abs(delta)) // 2
    klo = jnp.minimum(0, delta) - wl
    return klo, wl


def fw_traceback_band(dirs: jnp.ndarray, lq: jnp.ndarray, lt: jnp.ndarray,
                      klo: jnp.ndarray, steps: int,
                      transposed: bool = False):
    """Traceback over banded packed cells: rev ops uint8[B, steps].

    Identical walk to flat.fw_traceback with the column index mapped to
    band coordinates x = j - i - klo per lane. ``transposed`` selects
    the Pallas kernel's [Lq, W, B] dirs layout (vs [Lq, B, W]). Legacy
    op-by-op walk kept for tests and the sp path; the production
    traceback is the column-walk (racon_tpu/ops/colwalk.py).
    """
    if transposed:
        Lq, W, B = dirs.shape
    else:
        Lq, B, W = dirs.shape
    d1 = dirs.reshape(-1)
    lane = jnp.arange(B, dtype=jnp.int32)

    def step(state, _):
        i, j = state
        done = (i == 0) & (j == 0)
        x = jnp.clip(j - i - klo, 0, W - 1)
        if transposed:
            idx = (jnp.maximum(i - 1, 0) * (B * W) + x * B + lane)
        else:
            idx = (jnp.maximum(i - 1, 0) * (B * W) + lane * W + x)
        dv = jnp.take(d1, idx) & 3        # low bits of the packed cell
        d = jnp.where(done, 3,
                      jnp.where(i == 0, LEFT,
                                jnp.where(j == 0, UP, dv))).astype(jnp.uint8)
        i = i - jnp.where((d == DIAG) | (d == UP), 1, 0).astype(i.dtype)
        j = j - jnp.where((d == DIAG) | (d == LEFT), 1, 0).astype(j.dtype)
        return (i, j), d

    (_, _), rev_ops = jax.lax.scan(
        step, (lq.astype(jnp.int32), lt.astype(jnp.int32)), None,
        length=steps)
    return rev_ops.T
