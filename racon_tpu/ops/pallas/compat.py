"""jax version compatibility for the Pallas TPU kernels.

jax renamed ``pltpu.TPUCompilerParams`` to ``pltpu.CompilerParams``
(jax 0.5 series); the kernels must import under either name so the
interpreter-mode tier-1 tests (tests/test_kernels_interpret.py) can run
them on CPU regardless of the installed jax. Resolve the name once here.
"""

from __future__ import annotations

from jax.experimental.pallas import tpu as pltpu

CompilerParams = getattr(pltpu, "CompilerParams", None) or \
    pltpu.TPUCompilerParams
