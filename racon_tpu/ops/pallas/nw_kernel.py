"""Pallas TPU kernel for the batched NW direction-matrix forward pass.

The pure-XLA forward (racon_tpu/ops/align.py::_nw_dirs) is a lax.scan
whose per-row step only touches [B, Lt] elements — far too little work to
amortize per-step overhead. This kernel restructures the DP:

- a tile of TB=128 alignments rides the *sublane* dimension, the target
  axis rides the lanes, so each row update is a [128, Lt] register-tiled
  VPU op — 16x the width of the 8-sublane naive layout;
- the grid is (B/TB, Lq/CH): query rows are processed CH at a time from a
  VMEM-resident block while the row state H[i-1, :] persists in a VMEM
  scratch across grid steps (sequential "arbitrary" grid semantics);
- the gap-chain closure is the max-plus prefix trick as log2(Lt)
  shift-max steps;
- dynamic indexing only ever touches the leading (untiled) dimension —
  a Mosaic requirement — hence the [rows, TB, Lt] block layouts, with
  the substitution matrix precomputed in XLA as a fused broadcast-compare
  (int8, to keep pipelined VMEM blocks in budget).

Semantics are bit-identical to _nw_dirs (same boundaries, same
DIAG > UP > LEFT tie-breaking) — asserted by tests/test_align.py.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from racon_tpu.ops.pallas.compat import CompilerParams as _CompilerParams

from racon_tpu.ops.cigar import DIAG, UP, LEFT

_NEG = -(2 ** 30)
TB = 128  # alignments per grid program (sublane width of each row op)
CH = 32   # query rows per grid step


def _kernel(sub_ref, dirs_ref, prev_ref, *, gap, Lt):
    c = pl.program_id(1)
    jr = jax.lax.broadcasted_iota(jnp.int32, (TB, Lt), 1) + 1
    jg = jr * gap

    @pl.when(c == 0)
    def _():
        prev_ref[:] = jg  # H[0, j] = j * gap

    shifts = []
    k = 1
    while k < Lt:
        shifts.append(k)
        k *= 2

    def row(r, _):
        i = c * CH + r + 1  # global row number
        sub = sub_ref[r].astype(jnp.int32)              # [TB, Lt]
        prev = prev_ref[:]
        prev_shift = jnp.concatenate(
            [jnp.full((TB, 1), 0, jnp.int32) + (i - 1) * gap,
             prev[:, :-1]], axis=1)
        diag = prev_shift + sub
        up = prev + gap
        f = jnp.maximum(diag, up) - jg
        for s in shifts:
            f = jnp.maximum(
                f, jnp.concatenate(
                    [jnp.full((TB, s), _NEG, jnp.int32), f[:, :-s]],
                    axis=1))
        h = jnp.maximum(f, i * gap) + jg
        d = jnp.where(h == diag, DIAG,
                      jnp.where(h == up, UP, LEFT)).astype(jnp.uint8)
        dirs_ref[r] = d
        prev_ref[:] = h
        return 0

    jax.lax.fori_loop(0, CH, row, 0)


@functools.partial(jax.jit, static_argnames=("match", "mismatch", "gap"))
def nw_dirs_pallas(q: jnp.ndarray, t: jnp.ndarray, *, match: int,
                   mismatch: int, gap: int) -> jnp.ndarray:
    """Direction matrices uint8[Lq, B, Lt] for a padded batch.

    B must be a multiple of TB (128), Lq of CH (32), Lt of 128. Note the
    rows-leading layout — the traceback consumes it directly.
    """
    B, Lq = q.shape
    Lt = t.shape[1]
    # Fused broadcast-compare in XLA: sub[i, b, j] = score(q[b,i], t[b,j]).
    sub = jnp.where(q.T[:, :, None] == t[None, :, :], match,
                    mismatch).astype(jnp.int8)
    kernel = functools.partial(_kernel, gap=gap, Lt=Lt)
    return pl.pallas_call(
        kernel,
        grid=(B // TB, Lq // CH),
        in_specs=[
            pl.BlockSpec((CH, TB, Lt), lambda b, c: (c, b, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((CH, TB, Lt), lambda b, c: (c, b, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((Lq, B, Lt), jnp.uint8),
        scratch_shapes=[pltpu.VMEM((TB, Lt), jnp.int32)],
        compiler_params=_CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary")),
    )(sub)
