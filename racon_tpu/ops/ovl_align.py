"""Device-side overlap alignment: PAF/MHAP breaking points on the TPU.

The reference aligns every CIGAR-less overlap with edlib on a CPU thread
pool, then walks the CIGAR base by base to find per-window breaking
points (src/polisher.cpp:351-364, src/overlap.cpp:179-282). At genome
scale this phase dominates initialize: 551 s of a 1325 s 2 Mb/30x run
on this image's single core (scripts/genome_bench.py, round 5).

TPU restructuring: overlaps batch through the same banded NW forward
kernel as window consensus (racon_tpu/ops/pallas/band_kernel.py), the
column-walk traceback (racon_tpu/ops/colwalk.py) yields the consuming
op + query index per TARGET column, and the breaking points fall out as
per-window first/last-match reductions over that column grid — no CIGAR
string ever materializes, and only [B, NW, 4] breaking-point rows leave
the device (a CIGAR d2h would be ~Lq+Lt bytes per overlap through the
tunnel).

Exactness contract (same as the consensus engine): per-lane banded
optimality is certified by the tightened escape bound; lanes that fail
it — or whose walk saturated an up-run counter — are returned to the
caller for the native aligner fallback. Jobs too long for the device
budget (band width must grow ~Lq/7 to certify at ONT error rates, and
128 * Lq * W is capped by the int32 flat-index budget, so ~9 kb is the
practical ceiling) skip the device entirely.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import functools

import numpy as np

from racon_tpu.ops.cigar import DIAG
from racon_tpu.ops.device_poa import _packed_byte_slice, _round_up
from racon_tpu.ops.pallas.band_kernel import TB   # lane grid (= chunk B)
from racon_tpu.ops.budget import (VMEM_BUDGET as _VMEM_BUDGET,
                                  max_dir_elems, vmem_est as _vmem_est)
# Dirs/nxt-plane element budget: the column walk's flat gather index
# must stay under 2^31 and each plane's HBM buffer under the TPU's 2 GB
# single-buffer ceiling. Derived in racon_tpu/ops/budget.py, SHARED with
# the consensus engine — round 5's independently-maintained caps (1.6e9
# there, 1.9e9 here) disagreed by 0.7% and silently routed EVERY 8 kb
# genome overlap (128 x 8192 x 1536 = 1.61e9) to the native path.
MAX_DIR_ELEMS = max_dir_elems(1)


def _pick_tiles(W: int, Lq: int) -> Tuple[int, int]:
    """(tb, ch) for the band kernel: full 128 lanes, row tile shrunk
    until the VMEM model fits (admission guarantees ch=4 fits; the ch=4
    tier exists because the dual-column nxt plane's block doubled the
    row-tile term and would otherwise evict the 8 kb genome geometry
    that fit at ch=8 — see budget.vmem_est)."""
    for ch in (32, 8, 4):
        if Lq % ch == 0 and _vmem_est(W, Lq, ch) <= _VMEM_BUDGET:
            return TB, ch
    return TB, 4


def band_width_for_read(lq: int, lt: int) -> int:
    """Band width that certifies noisy long-read alignments.

    At edit-distance scoring (m=0, g=-1 — edlib parity) the tightened
    escape bound certifies iff ED_banded <= |lt-lq| + 2*wl + 2, so the
    half-width must exceed half the expected edit distance: read-vs-
    draft difference runs ~12-15% for ONT, hence wl ~ L/13 plus slack.
    Under-banding is safe (escape failure -> native fallback), just
    wasted device work. |lt - lq| rides on top.
    """
    return _round_up(abs(lt - lq) + 2 * (max(lq, lt) // 13 + 64) + 1, 128)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "W", "w_len", "NW", "Lq",
                     "LA", "pallas"))
def _chunk_breaking_points(q, t, lq, lt, t_begin, *, match, mismatch, gap,
                           W, w_len, NW, Lq, LA, pallas):
    """One device chunk: banded forward + column walk + per-window
    first/last-match reduction.

    Returns (first_c, qi_f, last_c, qi_l  — all int32[B, NW], column/
    query indices RELATIVE to each lane's slice —, valid bool[B, NW],
    fail f32[B] nonzero where the lane needs the native fallback).
    """
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops.colwalk import col_walk
    from racon_tpu.ops.pallas.band_kernel import (
        fw_dirs_band, fw_dirs_band_xla, band_geometry)

    B = q.shape[0]
    klo, wl = band_geometry(lq, lt, W)
    PW = W + Lq
    # Pre-shifted per-lane target window: tband[b, y] = t[b, klo_b + y],
    # built from the FLATTENED target table via the shared i32-packed
    # batched dynamic_slice (4 cells per descriptor word). A slice may
    # spill into the neighbouring lane's row where the old per-row
    # padded build read zeros — every such byte is out of [0, lt) and
    # the okb mask overwrites it, so tband is bit-identical.
    tab = jnp.concatenate(
        [jnp.zeros((PW,), jnp.uint8), t.reshape(-1),
         jnp.zeros((PW,), jnp.uint8)])
    y = jnp.arange(PW, dtype=jnp.int32)[None, :]
    rel = klo[:, None] + y
    okb = (rel >= 0) & (rel < lt[:, None])
    start = jnp.arange(B, dtype=jnp.int32) * LA + klo + PW
    sl = _packed_byte_slice(tab, start, PW)
    tband = jnp.where(okb, sl, 7).astype(jnp.uint8)

    if pallas:
        tb, ch = _pick_tiles(W, Lq)
        dirs, nxt, hlast = fw_dirs_band(
            tband, q.T, klo, lq, match=match, mismatch=mismatch, gap=gap,
            W=W, tb=tb, ch=ch)
    else:
        dirs, nxt, hlast = fw_dirs_band_xla(
            tband, q.T, klo, lq, match=match, mismatch=mismatch, gap=gap,
            W=W)
    cols = col_walk(dirs, lq, lt, klo, jnp.zeros(B, jnp.int32), LA=LA,
                    layout="band_t" if pallas else "band", nxt=nxt)

    # Tightened escape bound (same derivation as device_poa._round_core).
    xend = jnp.clip(lt - lq - klo, 0, W - 1)
    score = jnp.take_along_axis(hlast, xend[:, None], axis=1)[:, 0]
    bound = (jnp.maximum(match, 0) * (jnp.minimum(lq, lt) - wl - 1) +
             gap * (jnp.abs(lt - lq) + 2 * wl + 2))
    fail = ((score < bound) | (wl < 16)).astype(jnp.float32) + \
        cols["sat"].astype(jnp.float32)

    # Consumer op / query index per target column c (walk step c + 1).
    op = cols["op_c"][:, 1:LA + 1].astype(jnp.int32)     # [B, LA]
    qi = cols["qi_c"][:, 1:LA + 1].astype(jnp.int32)
    c = jnp.arange(LA, dtype=jnp.int32)[None, :]
    is_m = (c < lt[:, None]) & (op == DIAG)
    # Window of column c (absolute target coordinate), relative to the
    # lane's first touched window.
    widx = (t_begin[:, None] + c) // w_len - (t_begin // w_len)[:, None]
    HUGE = 2 ** 30
    firsts, lasts, valids = [], [], []
    for k in range(NW):
        mask = is_m & (widx == k)
        firsts.append(jnp.min(jnp.where(mask, c, HUGE), axis=1))
        lasts.append(jnp.max(jnp.where(mask, c, -1), axis=1))
        valids.append(jnp.any(mask, axis=1))
    first_c = jnp.stack(firsts, axis=1)                  # [B, NW]
    last_c = jnp.stack(lasts, axis=1)
    valid = jnp.stack(valids, axis=1)
    qi_f = jnp.take_along_axis(qi, jnp.clip(first_c, 0, LA - 1), axis=1)
    qi_l = jnp.take_along_axis(qi, jnp.clip(last_c, 0, LA - 1), axis=1)
    return first_c, qi_f, last_c, qi_l, valid, fail


def device_breaking_points(pending, sequences, window_length: int, *,
                           match: int, mismatch: int, gap: int,
                           log=None) -> List:
    """Compute breaking points on device for as many overlaps as the
    budget admits; returns the overlaps that still need the native path
    (too long, escape-bound failure, or walk saturation).

    Sets ``o.breaking_points`` (int64[N, 4], reference row format) on
    every handled overlap — ``find_breaking_points`` then no-ops.
    """
    import jax
    from racon_tpu.ops.encode import encode_bases

    jobs = []      # (overlap, q_codes, t_codes, q_start)
    fallback = []
    for o in pending:
        qb, tb = o.alignment_operands(sequences)
        lq, lt = len(qb), len(tb)
        if lq < 1 or lt < 1:
            fallback.append(o)
            continue
        W = _round_up(band_width_for_read(lq, lt), 512)
        lqp = _round_up(lq, 2048)
        if (TB * lqp * W > MAX_DIR_ELEMS or
                _vmem_est(W, lqp, 4) > _VMEM_BUDGET or
                max(lq, lt) >= 2 ** 14):   # int16 walk emissions
            fallback.append(o)
            continue
        q_start = o.q_begin if not o.strand else o.q_length - o.q_end
        jobs.append((o, encode_bases(bytes(qb)), encode_bases(bytes(tb)),
                     q_start))
    if not jobs:
        # A fully-rejected set must still say so — this exact condition
        # once hid the genome workload falling back wholesale.
        if log is not None and fallback:
            print(f"[racon_tpu::Polisher::initialize] all {len(pending)} "
                  "overlap alignments exceed the device length budget; "
                  "using the native path", file=log)
        return fallback

    pallas = jax.default_backend() in ("tpu", "axon")
    # RUN-level shape buckets: every distinct (Lq, LA, W) triple is a
    # fresh executable, and a compile through this environment's remote
    # AOT helper costs 1-2 MINUTES — per-chunk shape maxima turned the
    # 2 Mb genome run's alignment phase into compile churn (503 s for
    # ~20 s of device work, round-5 measurement). Jobs sort by length
    # and buckets grow greedily under the running-maxima budget (padded
    # Lq from one job combined with the band width of another can
    # overflow the int32 flat-index budget even when each job fits
    # alone), so a uniform read set compiles exactly once; each bucket
    # then executes in TB-lane chunks.
    jobs.sort(key=lambda j: (len(j[1]), len(j[2])))
    buckets = []
    cur: List = []
    Lq = LA = W = 1
    for j in jobs:
        _, qc, tc, _ = j
        tLq = max(Lq, _round_up(len(qc), 2048))
        tLA = max(LA, _round_up(len(tc), 2048))
        tW = max(W, _round_up(band_width_for_read(len(qc), len(tc)), 512))
        if cur and (TB * tLq * tW > MAX_DIR_ELEMS or
                    _vmem_est(tW, tLq, 4) > _VMEM_BUDGET):
            buckets.append((cur, Lq, LA, W))
            cur = []
            tLq = _round_up(len(qc), 2048)
            tLA = _round_up(len(tc), 2048)
            tW = _round_up(band_width_for_read(len(qc), len(tc)), 512)
        Lq, LA, W = tLq, tLA, tW
        cur.append(j)
    if cur:
        buckets.append((cur, Lq, LA, W))

    # Dispatch every chunk before collecting any: jit calls are async,
    # so chunk i+1's h2d overlaps chunk i's compute (the tunnel's h2d
    # otherwise serializes with device time).
    import os
    import sys as _sys
    import time as _time
    verbose = os.environ.get("RACON_TPU_TIMING", "") not in ("", "0")
    t_disp = _time.perf_counter()
    pending_out = []
    for bucket, Lq, LA, W in buckets:
        NW = LA // window_length + 2
        B = TB
        for s in range(0, len(bucket), B):
            sub = bucket[s:s + B]
            q = np.zeros((B, Lq), np.uint8)
            t = np.zeros((B, LA), np.uint8)
            lq = np.ones(B, np.int32)
            lt = np.ones(B, np.int32)
            t_begin = np.zeros(B, np.int32)
            for b, (o, qc, tc, _) in enumerate(sub):
                q[b, :len(qc)] = qc
                t[b, :len(tc)] = tc
                lq[b] = len(qc)
                lt[b] = len(tc)
                t_begin[b] = o.t_begin
            pending_out.append((sub, _chunk_breaking_points(
                q, t, lq, lt, t_begin, match=match, mismatch=mismatch,
                gap=gap, W=W, w_len=window_length, NW=NW, Lq=Lq, LA=LA,
                pallas=pallas)))

    if verbose:
        print(f"[racon_tpu::ovl_align] dispatch {len(pending_out)} "
              f"chunks ({len(buckets)} shape buckets): "
              f"{_time.perf_counter() - t_disp:.2f}s", file=_sys.stderr)
        t_disp = _time.perf_counter()
    for sub, out in pending_out:
        first_c, qi_f, last_c, qi_l, valid, fail = map(np.asarray, out)
        for b, (o, _, _, q_start) in enumerate(sub):
            if fail[b]:
                fallback.append(o)
                continue
            v = valid[b]
            rows = np.stack([
                o.t_begin + first_c[b][v],
                q_start + qi_f[b][v],
                o.t_begin + last_c[b][v] + 1,
                q_start + qi_l[b][v] + 1,
            ], axis=1).astype(np.int64)
            o.breaking_points = rows
    if verbose:
        print(f"[racon_tpu::ovl_align] collect: "
              f"{_time.perf_counter() - t_disp:.2f}s", file=_sys.stderr)
    if log is not None and fallback:
        n_budget = len(pending) - len(jobs)
        print(f"[racon_tpu::Polisher::initialize] {len(fallback)} of "
              f"{len(pending)} overlap alignments fall back to the "
              f"native path ({n_budget} over the device length budget, "
              f"{len(fallback) - n_budget} uncertified)", file=log)
    return fallback
