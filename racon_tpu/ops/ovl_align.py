"""Device-side overlap alignment: PAF/MHAP breaking points on the TPU.

The reference aligns every CIGAR-less overlap with edlib on a CPU thread
pool, then walks the CIGAR base by base to find per-window breaking
points (src/polisher.cpp:351-364, src/overlap.cpp:179-282). At genome
scale this phase dominates initialize: 551 s of a 1325 s 2 Mb/30x run
on this image's single core (scripts/genome_bench.py, round 5).

TPU restructuring: overlaps batch through the same banded NW forward
kernel as window consensus (racon_tpu/ops/pallas/band_kernel.py), the
column-walk traceback (racon_tpu/ops/colwalk.py) yields the consuming
op + query index per TARGET column, and the breaking points fall out as
per-window first/last-match reductions over that column grid — no CIGAR
string ever materializes, and only [B, NW, 4] breaking-point rows leave
the device (a CIGAR d2h would be ~Lq+Lt bytes per overlap through the
tunnel).

Exactness contract (same as the consensus engine): per-lane banded
optimality is certified by the tightened escape bound; lanes that fail
it — or whose walk saturated an up-run counter — are returned to the
caller for the native aligner fallback.

Length routing (round 7): jobs that fit the untiled whole-read budget
(~9 kb at the 128-lane grid) run exactly as before, bit-identically.
Longer jobs no longer skip the device: they route through the TILED
forward (``_tiled_chunk_breaking_points``) — a lax.scan over
query-axis tiles of the frontier-carrying band kernel
(band_kernel.fw_dirs_band_tile), with per-tile band re-centering, a
staircase escape certificate over the running band clearance, and a
stitched column walk over the per-tile slabs. Admission comes from
budget.tile_plan's (lanes, W, T, ch) tier table; only jobs no tier
admits (or whose certificate fails) reach the native path.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import functools
import os
from racon_tpu.utils import envspec

import numpy as np

from racon_tpu.ops.cigar import DIAG
from racon_tpu.ops.device_poa import _packed_byte_slice, _round_up
from racon_tpu.ops.pallas.band_kernel import TB   # lane grid (= chunk B)
from racon_tpu.ops.budget import (VMEM_BUDGET as _VMEM_BUDGET,
                                  max_dir_elems, tile_plan,
                                  vmem_est as _vmem_est)
# Dirs/nxt-plane element budget: the column walk's flat gather index
# must stay under 2^31 and each plane's HBM buffer under the TPU's 2 GB
# single-buffer ceiling. Derived in racon_tpu/ops/budget.py, SHARED with
# the consensus engine — round 5's independently-maintained caps (1.6e9
# there, 1.9e9 here) disagreed by 0.7% and silently routed EVERY 8 kb
# genome overlap (128 x 8192 x 1536 = 1.61e9) to the native path.
MAX_DIR_ELEMS = max_dir_elems(1)


def _pick_tiles(W: int, Lq: int, nxt_k: int = 2) -> Tuple[int, int]:
    """(tb, ch) for the band kernel: full 128 lanes, row tile shrunk
    until the VMEM model fits (admission guarantees ch=4 fits; the ch=4
    tier exists because the dual-column nxt plane's block doubled the
    row-tile term and would otherwise evict the 8 kb genome geometry
    that fit at ch=8 — see budget.vmem_est). ``nxt_k >= 4`` adds the u16
    nxt2 block to the model (the caller degrades k, not ch, when even
    ch=4 cannot host it)."""
    for ch in (32, 8, 4):
        if Lq % ch == 0 and _vmem_est(W, Lq, ch, nxt_k) <= _VMEM_BUDGET:
            return TB, ch
    return TB, 4


def band_width_for_read(lq: int, lt: int) -> int:
    """Band width that certifies noisy long-read alignments.

    At edit-distance scoring (m=0, g=-1 — edlib parity) the tightened
    escape bound certifies iff ED_banded <= |lt-lq| + 2*wl + 2, so the
    half-width must exceed half the expected edit distance: read-vs-
    draft difference runs ~12-15% for ONT, hence wl ~ L/13 plus slack.
    Under-banding is safe (escape failure -> native fallback), just
    wasted device work. |lt - lq| rides on top.
    """
    return _round_up(abs(lt - lq) + 2 * (max(lq, lt) // 13 + 64) + 1, 128)


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "W", "w_len", "NW", "Lq",
                     "LA", "pallas", "nxt_k"))
def _chunk_breaking_points(q, t, lq, lt, t_begin, *, match, mismatch, gap,
                           W, w_len, NW, Lq, LA, pallas, nxt_k=2):
    """One device chunk: banded forward + column walk + per-window
    first/last-match reduction.

    Returns (first_c, qi_f, last_c, qi_l  — all int32[B, NW], column/
    query indices RELATIVE to each lane's slice —, valid bool[B, NW],
    fail f32[B] nonzero where the lane needs the native fallback).
    """
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops.colwalk import col_walk
    from racon_tpu.ops.pallas.band_kernel import (
        fw_dirs_band, fw_dirs_band_xla, band_geometry)

    B = q.shape[0]
    klo, wl = band_geometry(lq, lt, W)
    PW = W + Lq
    # Pre-shifted per-lane target window: tband[b, y] = t[b, klo_b + y],
    # built from the FLATTENED target table via the shared i32-packed
    # batched dynamic_slice (4 cells per descriptor word). A slice may
    # spill into the neighbouring lane's row where the old per-row
    # padded build read zeros — every such byte is out of [0, lt) and
    # the okb mask overwrites it, so tband is bit-identical.
    tab = jnp.concatenate(
        [jnp.zeros((PW,), jnp.uint8), t.reshape(-1),
         jnp.zeros((PW,), jnp.uint8)])
    y = jnp.arange(PW, dtype=jnp.int32)[None, :]
    rel = klo[:, None] + y
    okb = (rel >= 0) & (rel < lt[:, None])
    start = jnp.arange(B, dtype=jnp.int32) * LA + klo + PW
    sl = _packed_byte_slice(tab, start, PW)
    tband = jnp.where(okb, sl, 7).astype(jnp.uint8)

    if pallas:
        tb, ch = _pick_tiles(W, Lq, nxt_k)
        fwd = functools.partial(fw_dirs_band, tb=tb, ch=ch)
    else:
        fwd = fw_dirs_band_xla
    if nxt_k >= 4:
        dirs, nxt, nxt2, hlast = fwd(
            tband, q.T, klo, lq, match=match, mismatch=mismatch, gap=gap,
            W=W, nxt_k=4)
    else:
        dirs, nxt, hlast = fwd(
            tband, q.T, klo, lq, match=match, mismatch=mismatch, gap=gap,
            W=W)
        nxt2 = None
    cols = col_walk(dirs, lq, lt, klo, jnp.zeros(B, jnp.int32), LA=LA,
                    layout="band_t" if pallas else "band", nxt=nxt,
                    nxt2=nxt2)

    # Tightened escape bound (same derivation as device_poa._round_core).
    xend = jnp.clip(lt - lq - klo, 0, W - 1)
    score = jnp.take_along_axis(hlast, xend[:, None], axis=1)[:, 0]
    bound = (jnp.maximum(match, 0) * (jnp.minimum(lq, lt) - wl - 1) +
             gap * (jnp.abs(lt - lq) + 2 * wl + 2))
    fail = ((score < bound) | (wl < 16)).astype(jnp.float32) + \
        cols["sat"].astype(jnp.float32)

    # Consumer op / query index per target column c (walk step c + 1).
    op = cols["op_c"][:, 1:LA + 1].astype(jnp.int32)     # [B, LA]
    qi = cols["qi_c"][:, 1:LA + 1].astype(jnp.int32)
    c = jnp.arange(LA, dtype=jnp.int32)[None, :]
    is_m = (c < lt[:, None]) & (op == DIAG)
    # Window of column c (absolute target coordinate), relative to the
    # lane's first touched window.
    widx = (t_begin[:, None] + c) // w_len - (t_begin // w_len)[:, None]
    HUGE = 2 ** 30
    firsts, lasts, valids = [], [], []
    for k in range(NW):
        mask = is_m & (widx == k)
        firsts.append(jnp.min(jnp.where(mask, c, HUGE), axis=1))
        lasts.append(jnp.max(jnp.where(mask, c, -1), axis=1))
        valids.append(jnp.any(mask, axis=1))
    first_c = jnp.stack(firsts, axis=1)                  # [B, NW]
    last_c = jnp.stack(lasts, axis=1)
    valid = jnp.stack(valids, axis=1)
    qi_f = jnp.take_along_axis(qi, jnp.clip(first_c, 0, LA - 1), axis=1)
    qi_l = jnp.take_along_axis(qi, jnp.clip(last_c, 0, LA - 1), axis=1)
    return first_c, qi_f, last_c, qi_l, valid, fail


@functools.partial(
    __import__("jax").jit,
    static_argnames=("match", "mismatch", "gap", "W", "w_len", "NW", "Lq",
                     "LA", "T", "tb", "ch", "pallas", "nxt_k"))
def _tiled_chunk_breaking_points(q, t, lq, lt, t_begin, *, match, mismatch,
                                 gap, W, w_len, NW, Lq, LA, T, tb, ch,
                                 pallas, nxt_k=2):
    """One ULTRALONG device chunk: lax.scan over query-axis tiles of the
    frontier-carrying band kernel, then one stitched column walk.

    Per tile the scan (one kernel compile serves every tile — the row
    origin i0 is a runtime input):

    1. gathers the tile's pre-shifted target window at the CURRENT band
       origin klo (re-centered between tiles, so each tile is a straight
       band but the tile sequence forms a staircase that can track
       |lt - lq| <= W/2 of drift),
    2. runs fw_dirs_band_tile / its XLA twin with the carried frontier
       (H row i0, packed (N,U,C) metadata of row i0, running hlast),
    3. updates the running band clearance ``cmin`` — the certificate
       below needs the MINIMUM distance from any tile's band edges to
       the legal-origin interval, and
    4. re-centers klo on the frontier argmax with a W/4..3W/4 dead zone
       (no-drift reads keep klo fixed and are bit-identical to the
       untiled straight band) clamped to [max(0,d)-W+1, min(0,d)] — the
       clamp keeps both DP corners reachable, so the terminal cell
       x_end = lt - lq - klo stays inside [0, W) at every tile and the
       captured end score survives the frontier shifts. The frontier
       shifts by d = klo' - klo (score fill NEG, metadata fill
       UC_BOUNDARY, hlast fill NEG — a shifted-out terminal score would
       mean the clamp proof was violated, and NEG fails the certificate
       rather than fabricating a result).

    The per-tile klo values are stacked and handed to the column walk
    (colwalk.py tile_klo), which maps stored row r through tile
    r // T's origin; the dual-column nxt contract survives tile
    boundaries unchanged because nxt bytes carry predecessor VALUES,
    not band slots. Emissions are int32 (absolute query indices exceed
    int16 past 32 kb).

    Staircase escape certificate: a path leaving the tiled band must
    cross a band edge at some tile, where its clearance to the legal
    diagonals is at least cmin, so (same counting as the straight-band
    bound with wl := cmin)

        score >= max(m,0)*(min(lq,lt) - cmin - 1)
                 + gap*(|lt - lq| + 2*cmin + 2)

    certifies banded == global. With the dead zone inactive cmin == wl
    and this is exactly the untiled bound.

    Returns the same tuple contract as _chunk_breaking_points.
    """
    import jax
    import jax.numpy as jnp
    from racon_tpu.ops.colwalk import col_walk
    from racon_tpu.ops.pallas.band_kernel import (
        fw_dirs_band_tile, fw_dirs_band_xla_tile, uc_boundary)

    BND = uc_boundary(nxt_k)

    B = q.shape[0]
    n_tiles = Lq // T
    NEG = -(2 ** 30)
    lanei = jnp.arange(B, dtype=jnp.int32)
    xr = jnp.arange(W, dtype=jnp.int32)[None, :]
    delta = lt - lq
    # Legal band-origin interval: klo must keep (0, 0) reachable
    # (klo <= 0 via klo_hi; start corner at x = -klo < W via klo_lo) and
    # the terminal (lq, lt) in band (x_end = delta - klo in [0, W)).
    klo_lo = jnp.maximum(0, delta) - (W - 1)
    klo_hi = jnp.minimum(0, delta)
    wl = (W - 1 - jnp.abs(delta)) // 2
    klo0 = jnp.clip(jnp.minimum(0, delta) - wl, klo_lo, klo_hi)
    j00 = klo0[:, None] + xr
    prev0 = jnp.where(j00 >= 0, j00 * gap, NEG).astype(jnp.int32)
    uc0 = jnp.full((B, W), BND, jnp.int32)
    hl0 = prev0

    PW = W + T
    tab = jnp.concatenate(
        [jnp.zeros((PW,), jnp.uint8), t.reshape(-1),
         jnp.zeros((PW,), jnp.uint8)])
    y = jnp.arange(PW, dtype=jnp.int32)[None, :]
    qT = q.T

    def tile_body(carry, i0):
        prev, uc, hl, klo, cmin = carry
        cmin = jnp.minimum(
            cmin, jnp.minimum(klo_hi - klo, klo - klo_lo))
        # This tile's pre-shifted target window at the current origin:
        # tband[b, y] = t[b, klo_b + i0 + y] (bucketing guarantees
        # LA >= Lq, so the padded-table slice stays in range).
        rel = klo[:, None] + i0 + y
        okb = (rel >= 0) & (rel < lt[:, None])
        start = lanei * LA + klo + i0 + PW
        sl = _packed_byte_slice(tab, start, PW)
        tband = jnp.where(okb, sl, 7).astype(jnp.uint8)
        qT_t = jax.lax.dynamic_slice_in_dim(qT, i0, T, axis=0)
        i0v = jnp.full((B,), i0, jnp.int32)
        if pallas:
            fwd = functools.partial(fw_dirs_band_tile, tb=tb, ch=ch)
        else:
            fwd = fw_dirs_band_xla_tile
        if nxt_k >= 4:
            dirs, nxt, nxt2, hl2, prev2, uc2 = fwd(
                tband, qT_t, klo, lq, i0v, prev, uc, hl, match=match,
                mismatch=mismatch, gap=gap, W=W, nxt_k=4)
        else:
            dirs, nxt, hl2, prev2, uc2 = fwd(
                tband, qT_t, klo, lq, i0v, prev, uc, hl, match=match,
                mismatch=mismatch, gap=gap, W=W)
            nxt2 = None
        # Dead-zone re-centering on the frontier argmax (step 4 above).
        xstar = jnp.argmax(prev2, axis=1).astype(jnp.int32)
        shift = jnp.where(xstar < W // 4, xstar - W // 4,
                          jnp.where(xstar > (3 * W) // 4,
                                    xstar - (3 * W) // 4, 0))
        klo_n = jnp.clip(klo + shift, klo_lo, klo_hi)
        d = klo_n - klo
        xi = xr + d[:, None]
        okx = (xi >= 0) & (xi < W)
        xig = jnp.clip(xi, 0, W - 1)
        prev3 = jnp.where(
            okx, jnp.take_along_axis(prev2, xig, axis=1), NEG)
        uc3 = jnp.where(
            okx, jnp.take_along_axis(uc2, xig, axis=1), BND)
        hl3 = jnp.where(
            okx, jnp.take_along_axis(hl2, xig, axis=1), NEG)
        ys = (dirs, nxt, nxt2, klo) if nxt_k >= 4 else (dirs, nxt, klo)
        return (prev3, uc3, hl3, klo_n, cmin), ys

    i0s = jnp.arange(n_tiles, dtype=jnp.int32) * T
    carry0 = (prev0, uc0, hl0, klo0,
              jnp.full(klo0.shape, 2 ** 30, jnp.int32))
    if nxt_k >= 4:
        (_, _, hlF, kloF, cmin), (dslab, nslab, n2slab, klos) = \
            jax.lax.scan(tile_body, carry0, i0s)
    else:
        (_, _, hlF, kloF, cmin), (dslab, nslab, klos) = jax.lax.scan(
            tile_body, carry0, i0s)
        n2slab = None
    # Stacked per-tile slabs ARE the whole-read tensors: [n_tiles, T,
    # W, B] -> [Lq, W, B] (kernel layout; twin analogous) with rows in
    # global order.
    shape = (Lq, W, B) if pallas else (Lq, B, W)
    cells = dslab.reshape(shape)
    nxtp = nslab.reshape(shape)
    nxt2p = None if n2slab is None else n2slab.reshape(shape)
    cols = col_walk(cells, lq, lt, None, jnp.zeros(B, jnp.int32), LA=LA,
                    layout="band_t" if pallas else "band", nxt=nxtp,
                    nxt2=nxt2p, tile_klo=klos, tile_len=T, emit=jnp.int32)

    # hlF rides the frontier shifts, so the terminal cell is indexed
    # through the FINAL origin; the clamp proof keeps it in [0, W).
    xend = jnp.clip(lt - lq - kloF, 0, W - 1)
    score = jnp.take_along_axis(hlF, xend[:, None], axis=1)[:, 0]
    bound = (jnp.maximum(match, 0) * (jnp.minimum(lq, lt) - cmin - 1) +
             gap * (jnp.abs(delta) + 2 * cmin + 2))
    fail = ((score < bound) | (cmin < 16)).astype(jnp.float32) + \
        cols["sat"].astype(jnp.float32)

    op = cols["op_c"][:, 1:LA + 1]
    qi = cols["qi_c"][:, 1:LA + 1]
    c = jnp.arange(LA, dtype=jnp.int32)[None, :]
    is_m = (c < lt[:, None]) & (op == DIAG)
    widx = (t_begin[:, None] + c) // w_len - (t_begin // w_len)[:, None]
    # Scatter-reduce per window instead of the untiled path's per-window
    # Python loop: LA // w_len reaches ~230 at 114 kb reads, and the
    # loop's NW full-[B, LA] masked reductions would dominate the walk.
    wc = jnp.clip(widx, 0, NW - 1)
    rows = jnp.broadcast_to(lanei[:, None], (B, LA))
    HUGE = 2 ** 30
    first_c = jnp.full((B, NW), HUGE, jnp.int32).at[rows, wc].min(
        jnp.where(is_m, c, HUGE))
    last_c = jnp.full((B, NW), -1, jnp.int32).at[rows, wc].max(
        jnp.where(is_m, c, -1))
    valid = last_c >= 0
    qi_f = jnp.take_along_axis(qi, jnp.clip(first_c, 0, LA - 1), axis=1)
    qi_l = jnp.take_along_axis(qi, jnp.clip(last_c, 0, LA - 1), axis=1)
    # Trailing klos [n_tiles, B] is observability for tests/debugging
    # (which tiles re-centered); the collect loop reads out[:6] only.
    return first_c, qi_f, last_c, qi_l, valid, fail, klos


def device_breaking_points(pending, sequences, window_length: int, *,
                           match: int, mismatch: int, gap: int,
                           log=None) -> List:
    """Compute breaking points on device for as many overlaps as the
    budget admits; returns the overlaps that still need the native path
    (too long, escape-bound failure, or walk saturation).

    Sets ``o.breaking_points`` (int64[N, 4], reference row format) on
    every handled overlap — ``find_breaking_points`` then no-ops.
    """
    import jax
    from racon_tpu.obs import metrics as obs_metrics
    from racon_tpu.obs import trace as _trace
    from racon_tpu.ops.encode import encode_bases

    tracer = _trace.get_tracer()
    tiled_on = envspec.read("RACON_TPU_OVL_TILED") != "0"
    jobs = []        # (overlap, q_codes, t_codes, q_start)
    tiled_jobs = []  # (overlap, q_codes, t_codes, q_start, plan)
    fallback = []
    # The two fallback causes are counted INDEPENDENTLY, at the point
    # each is known: n_budget here at classification, n_uncert at
    # collect. The old `len(pending) - len(jobs)` subtraction lumped
    # uncertified lanes in with over-budget ones whenever both occurred
    # in one batch.
    n_budget = 0
    n_uncert = 0
    for o in pending:
        qb, tb = o.alignment_operands(sequences)
        lq, lt = len(qb), len(tb)
        if lq < 1 or lt < 1:
            fallback.append(o)
            n_budget += 1
            continue
        q_start = o.q_begin if not o.strand else o.q_length - o.q_end
        W = _round_up(band_width_for_read(lq, lt), 512)
        lqp = _round_up(lq, 2048)
        if (TB * lqp * W <= MAX_DIR_ELEMS and
                _vmem_est(W, lqp, 4) <= _VMEM_BUDGET and
                max(lq, lt) < 2 ** 14):   # int16 walk emissions
            jobs.append((o, encode_bases(bytes(qb)),
                         encode_bases(bytes(tb)), q_start))
            continue
        plan = tile_plan(lq, lt) if tiled_on else None
        if plan is not None:
            tiled_jobs.append((o, encode_bases(bytes(qb)),
                               encode_bases(bytes(tb)), q_start, plan))
        else:
            fallback.append(o)
            n_budget += 1
    if not jobs and not tiled_jobs:
        # A fully-rejected set must still say so — this exact condition
        # once hid the genome workload falling back wholesale.
        if log is not None and fallback:
            print(f"[racon_tpu::Polisher::initialize] all {len(pending)} "
                  "overlap alignments exceed the device length budget; "
                  "using the native path", file=log)
        obs_metrics.record_ovl(device_jobs=0, native_jobs=len(fallback),
                               tiles=0)
        return fallback

    pallas = jax.default_backend() in ("tpu", "axon")
    # RUN-level shape buckets: every distinct (Lq, LA, W) triple is a
    # fresh executable, and a compile through this environment's remote
    # AOT helper costs 1-2 MINUTES — per-chunk shape maxima turned the
    # 2 Mb genome run's alignment phase into compile churn (503 s for
    # ~20 s of device work, round-5 measurement). Jobs sort by length
    # and buckets grow greedily under the running-maxima budget (padded
    # Lq from one job combined with the band width of another can
    # overflow the int32 flat-index budget even when each job fits
    # alone), so a uniform read set compiles exactly once; each bucket
    # then executes in TB-lane chunks.
    jobs.sort(key=lambda j: (len(j[1]), len(j[2])))
    buckets = []
    cur: List = []
    Lq = LA = W = 1
    for j in jobs:
        _, qc, tc, _ = j
        tLq = max(Lq, _round_up(len(qc), 2048))
        tLA = max(LA, _round_up(len(tc), 2048))
        tW = max(W, _round_up(band_width_for_read(len(qc), len(tc)), 512))
        if cur and (TB * tLq * tW > MAX_DIR_ELEMS or
                    _vmem_est(tW, tLq, 4) > _VMEM_BUDGET):
            buckets.append((cur, Lq, LA, W))
            cur = []
            tLq = _round_up(len(qc), 2048)
            tLA = _round_up(len(tc), 2048)
            tW = _round_up(band_width_for_read(len(qc), len(tc)), 512)
        Lq, LA, W = tLq, tLA, tW
        cur.append(j)
    if cur:
        buckets.append((cur, Lq, LA, W))

    # Tiled jobs bucket per tier (lanes, W, T, ch): every member passed
    # tile_plan's element gate at ITS OWN padded Lq, and the bucket's
    # running maxima only ever equal some member's padding, so one
    # bucket per tier never overflows the cap. LA additionally rides up
    # to Lq — the per-tile tband slice into the padded target table
    # indexes lane*LA + klo + i0 + y and needs LA >= Lq to stay inside
    # the neighbouring-lane slack (_tiled_chunk_breaking_points).
    tiled_buckets = []
    bytier = {}
    for j in tiled_jobs:
        bytier.setdefault(j[4].key(), []).append(j)
    for (lanes, W_t, T_t, ch_t, k_t), js in sorted(bytier.items()):
        js.sort(key=lambda j: (len(j[1]), len(j[2])))
        Lq_t = max(_round_up(len(j[1]), T_t) for j in js)
        LA_t = max(Lq_t, max(_round_up(len(j[2]), 2048) for j in js))
        tiled_buckets.append((js, lanes, W_t, T_t, ch_t, Lq_t, LA_t, k_t))

    # Dispatch every chunk before collecting any: jit calls are async,
    # so chunk i+1's h2d overlaps chunk i's compute (the tunnel's h2d
    # otherwise serializes with device time).
    import sys as _sys
    import time as _time
    verbose = envspec.read("RACON_TPU_TIMING") not in ("", "0")
    t_disp = _time.perf_counter()
    pending_out = []
    from racon_tpu.ops.budget import walk_k_for
    for bucket, Lq, LA, W in buckets:
        # Per-bucket walk depth: the u16 nxt2 plane must fit the element
        # cap at the BUCKET's padded geometry (walk_k_for degrades the
        # 8 kb genome overlaps to the dual-column walk) and its VMEM
        # block the smallest row tile.
        nxt_k = walk_k_for(TB * Lq * W)
        if nxt_k >= 4 and _vmem_est(W, Lq, 4, 4) > _VMEM_BUDGET:
            nxt_k = 2
        NW = LA // window_length + 2
        B = TB
        for s in range(0, len(bucket), B):
            sub = bucket[s:s + B]
            q = np.zeros((B, Lq), np.uint8)
            t = np.zeros((B, LA), np.uint8)
            lq = np.ones(B, np.int32)
            lt = np.ones(B, np.int32)
            t_begin = np.zeros(B, np.int32)
            for b, (o, qc, tc, _) in enumerate(sub):
                q[b, :len(qc)] = qc
                t[b, :len(tc)] = tc
                lq[b] = len(qc)
                lt[b] = len(tc)
                t_begin[b] = o.t_begin
            with tracer.span("dispatch", "ovl_chunk", lanes=B, W=W):
                pending_out.append((sub, _chunk_breaking_points(
                    q, t, lq, lt, t_begin, match=match, mismatch=mismatch,
                    gap=gap, W=W, w_len=window_length, NW=NW, Lq=Lq, LA=LA,
                    pallas=pallas, nxt_k=nxt_k)))

    n_tiles_exec = 0
    for bucket, lanes, W, T, ch, Lq, LA, nxt_k in tiled_buckets:
        NW = LA // window_length + 2
        n_tiles = Lq // T
        for s in range(0, len(bucket), lanes):
            sub = bucket[s:s + lanes]
            # Lane count adapts down to the actual job count (pow2,
            # min 8): the stitched tensors scale with B, and a 3-job
            # tail chunk at 64 lanes would pay 21x the forward work.
            B = lanes
            while B // 2 >= max(8, len(sub)):
                B //= 2
            q = np.zeros((B, Lq), np.uint8)
            t = np.zeros((B, LA), np.uint8)
            lq = np.ones(B, np.int32)
            lt = np.ones(B, np.int32)
            t_begin = np.zeros(B, np.int32)
            for b, (o, qc, tc, _, _) in enumerate(sub):
                q[b, :len(qc)] = qc
                t[b, :len(tc)] = tc
                lq[b] = len(qc)
                lt[b] = len(tc)
                t_begin[b] = o.t_begin
            with tracer.span("dispatch", "ovl_tiled_chunk", lanes=B,
                             W=W, tiles=n_tiles):
                for ti in range(n_tiles):
                    tracer.point("tile", f"t{ti}", index=ti, rows=T, W=W)
                pending_out.append((sub, _tiled_chunk_breaking_points(
                    q, t, lq, lt, t_begin, match=match, mismatch=mismatch,
                    gap=gap, W=W, w_len=window_length, NW=NW, Lq=Lq,
                    LA=LA, T=T, tb=B, ch=ch, pallas=pallas,
                    nxt_k=nxt_k)))
            n_tiles_exec += n_tiles

    if verbose:
        print(f"[racon_tpu::ovl_align] dispatch {len(pending_out)} "
              f"chunks ({len(buckets)} shape buckets, "
              f"{len(tiled_buckets)} tiled tiers): "
              f"{_time.perf_counter() - t_disp:.2f}s", file=_sys.stderr)
        t_disp = _time.perf_counter()
    for sub, out in pending_out:
        # Untiled chunks return 6 fields; tiled chunks append a klos
        # observability field that the collect path does not consume.
        first_c, qi_f, last_c, qi_l, valid, fail = map(np.asarray, out[:6])
        for b, job in enumerate(sub):
            o, q_start = job[0], job[3]
            if fail[b]:
                fallback.append(o)
                n_uncert += 1
                continue
            v = valid[b]
            rows = np.stack([
                o.t_begin + first_c[b][v],
                q_start + qi_f[b][v],
                o.t_begin + last_c[b][v] + 1,
                q_start + qi_l[b][v] + 1,
            ], axis=1).astype(np.int64)
            o.breaking_points = rows
    if verbose:
        print(f"[racon_tpu::ovl_align] collect: "
              f"{_time.perf_counter() - t_disp:.2f}s", file=_sys.stderr)
    obs_metrics.record_ovl(
        device_jobs=len(jobs) + len(tiled_jobs) - n_uncert,
        native_jobs=len(fallback), tiles=n_tiles_exec)
    if log is not None and fallback:
        print(f"[racon_tpu::Polisher::initialize] {len(fallback)} of "
              f"{len(pending)} overlap alignments fall back to the "
              f"native path ({n_budget} over the device length budget, "
              f"{n_uncert} uncertified)", file=log)
    return fallback
