"""Base-space encodings shared by host packing code and device kernels.

The device kernels operate on small-integer base codes in a 5-letter alphabet
(A, C, G, T, N) — the same alphabet size the reference preallocates its POA
engine with (reference: src/polisher.cpp:154, `prealloc(window_length, 5)`).
"""

import numpy as np

# Base codes. Anything that is not ACGT (IUPAC ambiguity codes etc.) maps to N.
A, C, G, T, N = 0, 1, 2, 3, 4
ALPHABET = 5

_ENCODE = np.full(256, N, dtype=np.uint8)
for _i, _ch in enumerate("ACGTN"):
    _ENCODE[ord(_ch)] = _i
    _ENCODE[ord(_ch.lower())] = _i

_DECODE = np.frombuffer(b"ACGTN", dtype=np.uint8)

# Reverse-complement table over raw ASCII, matching the reference semantics:
# A<->T, C<->G, all other characters copied verbatim
# (reference: src/sequence.cpp:49-84).
_COMP = np.arange(256, dtype=np.uint8)
for _a, _b in (("A", "T"), ("C", "G"), ("a", "t"), ("c", "g")):
    _COMP[ord(_a)] = ord(_b)
    _COMP[ord(_b)] = ord(_a)
COMPLEMENT_TABLE = bytes(_COMP.tobytes())


def encode_bases(data: bytes) -> np.ndarray:
    """ASCII bytes -> uint8 base codes (0..4)."""
    return _ENCODE[np.frombuffer(data, dtype=np.uint8)]


def decode_bases(codes: np.ndarray) -> bytes:
    """uint8 base codes -> ASCII bytes."""
    return _DECODE[np.asarray(codes, dtype=np.uint8)].tobytes()


def reverse_complement(data: bytes) -> bytes:
    """Reverse complement of raw ASCII sequence data. Accepts the
    ingest plane's ``memoryview`` payloads (one copy here is
    unavoidable — the result is a new reversed string anyway)."""
    if not isinstance(data, (bytes, bytearray)):
        data = bytes(data)
    return data.translate(COMPLEMENT_TABLE)[::-1]
