"""Alignment-op encoding shared by every aligner backend (numpy-only).

Op encoding (used by the JAX device kernel in racon_tpu/ops/align.py and
the native C++ aligner in racon_tpu/native/nw.cpp):
  0 = DIAG  (consumes query+target -> CIGAR 'M')
  1 = UP    (consumes query only   -> CIGAR 'I')
  2 = LEFT  (consumes target only  -> CIGAR 'D')

This module has no jax dependency so the native/host path stays importable
without an accelerator stack.
"""

from __future__ import annotations

import numpy as np

DIAG, UP, LEFT = 0, 1, 2

_OP_TO_CIGAR = np.frombuffer(b"MID", dtype=np.uint8)


def ops_to_cigar(ops: np.ndarray) -> bytes:
    """Run-length encode an op array (0/1/2) into CIGAR bytes (M/I/D)."""
    ops = np.asarray(ops, dtype=np.uint8)
    if ops.size == 0:
        return b""
    edges = np.flatnonzero(np.diff(ops)) + 1
    starts = np.concatenate([[0], edges])
    ends = np.concatenate([edges, [ops.size]])
    out = []
    for s, e in zip(starts, ends):
        out.append(str(e - s).encode())
        out.append(_OP_TO_CIGAR[ops[s]:ops[s] + 1].tobytes())
    return b"".join(out)


def nw_oracle(q, t, match: int, mismatch: int, gap: int):
    """Reference numpy NW (row loop) -> (score, ops uint8[n]). Test oracle
    and small-input fallback; semantics identical to the device kernel."""
    qa = np.frombuffer(q, dtype=np.uint8) if isinstance(q, (bytes, bytearray)) \
        else np.asarray(q, dtype=np.uint8)
    ta = np.frombuffer(t, dtype=np.uint8) if isinstance(t, (bytes, bytearray)) \
        else np.asarray(t, dtype=np.uint8)
    lq, lt = len(qa), len(ta)
    H = np.zeros((lq + 1, lt + 1), dtype=np.int64)
    H[0, :] = np.arange(lt + 1) * gap
    H[:, 0] = np.arange(lq + 1) * gap
    D = np.zeros((lq, lt), dtype=np.uint8)
    for i in range(1, lq + 1):
        sub = np.where(ta == qa[i - 1], match, mismatch)
        diag = H[i - 1, :-1] + sub
        up = H[i - 1, 1:] + gap
        tmp = np.maximum(diag, up)
        row = np.empty(lt + 1, dtype=np.int64)
        row[0] = i * gap
        for j in range(1, lt + 1):
            row[j] = max(tmp[j - 1], row[j - 1] + gap)
        H[i] = row
        D[i - 1] = np.where(row[1:] == diag, DIAG,
                            np.where(row[1:] == up, UP, LEFT))
    ops = []
    i, j = lq, lt
    while i > 0 or j > 0:
        d = LEFT if i == 0 else (UP if j == 0 else int(D[i - 1, j - 1]))
        ops.append(d)
        if d != LEFT:
            i -= 1
        if d != UP:
            j -= 1
    return int(H[lq, lt]), np.asarray(ops[::-1], dtype=np.uint8)
